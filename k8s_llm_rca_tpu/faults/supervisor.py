"""Supervised kill/restart of the serving stack — the process-level
"crash" fault (docs/durability.md, docs/faults.md).

A real worker kill destroys the Python process: the ``AssistantService``
store, every backend handle, and the engine's device KV all evaporate;
only two artifacts survive on disk — the run journal (serve/journal.py)
and the sweep output file.  ``CrashSupervisor`` reproduces exactly that
inside one test process, deterministically:

- it polls ``inject.SITE_PROCESS`` on its OWN FaultPlan at incident
  boundaries (``run_chaos_soak`` calls ``checkpoint`` after each
  incident).  The supervisor plan is deliberately separate from the armed
  chaos plan: a crash must not shift the armed plan's poll counters, or
  the crashed run's fault schedule — and therefore the report — would
  diverge from the uninterrupted run and the byte-identity proof would be
  comparing different fault histories;
- on a "crash" fault it tears the stack down the way a kill does: the
  journal file handle is closed, every live backend run is cancelled
  (releasing engine slots/pages, since the engine OBJECT stands in for
  the restarted worker's recompiled engine — recompiling identical
  weights per crash would buy no extra coverage and minutes of compile),
  and the service object is dropped;
- then it restarts: a fresh backend from the factory, a reopened journal
  (RunJournal's open drops any torn tail), ``recover_service`` replaying
  the journal, and the recovered service rebound into the RCA pipeline's
  stage clients.

What survives a supervised crash ON PURPOSE: the ResiliencePolicy
(breaker/retry counters model cluster-level state the report asserts on)
and the VirtualClock (monotonic across restarts, like wall time).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


def rebind_pipeline(pipeline, service) -> None:
    """Point an RCAPipeline (and its three stage clients) at a recovered
    service.  Stage clients hold object references (assistant/thread)
    into the dead service's store; each is re-resolved by id against the
    replayed store — ids are journaled, so they match exactly."""
    pipeline.service = service
    for client in (pipeline.locator, pipeline.cypher_generator,
                   pipeline.analyzer):
        client.service = service
        if client.assistant is not None:
            client.assistant = service.assistants[client.assistant.id]
        if client.thread is not None:
            # a thread may predate journaling or belong to a finished
            # incident; rebind when replay knows it, else leave the stale
            # reference for reset_threads to replace
            t = service.threads.get(client.thread.id)
            if t is not None:
                client.thread = t


class CrashSupervisor:
    """Deterministic kill/restart harness for ``run_chaos_soak``.

    ``plan``: the supervisor's own FaultPlan scheduling "crash" faults at
    ``inject.SITE_PROCESS`` (never the armed chaos plan — see module
    docstring).  ``journal_path``: the run journal both halves share.
    """

    def __init__(self, plan: FaultPlan, journal_path: str):
        self.plan = plan
        self.journal_path = journal_path
        self.crashes = 0
        self.recoveries: List[Dict[str, Any]] = []

    def checkpoint(self, pipeline, service,
                   backend_factory: Callable[[], Any],
                   run_timeout_s: float, clock=None):
        """Incident-boundary poll: returns the service to keep using —
        the same one, or a journal-recovered replacement after a crash."""
        fault = self.plan.poll(inject.SITE_PROCESS)
        if fault is None:
            return service
        if fault.kind != "crash":
            log.warning("supervisor fault %r ignored: only 'crash' is "
                        "meaningful at %s", fault.kind, inject.SITE_PROCESS)
            return service
        return self.crash_restart(pipeline, service, backend_factory,
                                  run_timeout_s, clock)

    def crash_restart(self, pipeline, service,
                      backend_factory: Callable[[], Any],
                      run_timeout_s: float, clock=None):
        """Tear the stack down (process-kill semantics) and rebuild it
        from the journal.  See the module docstring for what dies and
        what survives."""
        from k8s_llm_rca_tpu.serve.journal import RunJournal
        from k8s_llm_rca_tpu.serve.recover import recover_service

        self.crashes += 1
        log.warning("supervised crash #%d: tearing down serving stack",
                    self.crashes)
        journal = getattr(service, "_journal", None)
        if journal is not None:
            journal.close()
        backend = service.backend
        # the dead process's engine sequences: cancel through the backend
        # so slots/pages are released on the engine object that stands in
        # for the restarted worker's engine
        for handle in list(getattr(backend, "_live", ())):
            backend.cancel(handle)
        service._inflight.clear()

        new_backend = backend_factory()
        new_journal = RunJournal(self.journal_path)
        svc, report = recover_service(
            self.journal_path, new_backend, run_timeout_s=run_timeout_s,
            clock=clock, journal=new_journal)
        self.recoveries.append(report)
        if pipeline is not None:
            rebind_pipeline(pipeline, svc)
        METRICS.inc("faults.supervised_crashes")
        log.warning("supervised restart #%d: %d records replayed, "
                    "%d runs resubmitted", self.crashes, report["records"],
                    len(report["resubmitted"]))
        return svc


class ReplicaKiller:
    """Deterministic replica-kill harness for cluster soaks
    (``run_chaos_soak(backend="cluster*", killer=...)``).

    Same two disciplines as ``CrashSupervisor``, for the same reasons:
    it polls ``inject.SITE_REPLICA`` on its OWN FaultPlan (never the
    armed chaos plan — a kill must not shift the armed plan's poll
    counters, or the killed run's report would diverge from the
    unkilled run and the byte-identity proof would compare different
    fault histories), and it is polled exactly once per incident
    boundary on both outcome paths, so its kill schedule is a pure
    function of (plan, n_incidents).

    On a scheduled "crash" fault it kills one alive replica — process-
    kill semantics: the replica's device KV is treated as gone and its
    in-flight runs re-start from their recorded prompts on survivors
    (greedy decode makes the final outputs identical).  The victim is
    chosen deterministically from the alive list by the fault's poll
    index.  HOW it kills is the explicit per-plan ``mode``:

    - ``"auto"`` (default, the historical behavior): *wedge* the victim
      when a self-healing router (``attach_health``) is armed — the
      process "dies" silently and the watchdog owns detection, failover
      and restart — else ``router.fail_replica`` directly (PR 6
      semantics: the kill and the failover are one external call).
      Auto REFUSES out-of-process victims loudly: a ProcReplica's
      worker is a real OS process, and silently wedging its proxy would
      test nothing the fleet claims to survive — say ``mode="sigkill"``
      (or use ``ProcKiller``) to mean it, or ``mode="wedge"`` to
      simulate on purpose.
    - ``"wedge"``: always simulate (requires an attached watchdog —
      without one, nobody would ever detect the wedge).
    - ``"sigkill"``: deliver a REAL SIGKILL through the victim's
      ``kill_process()`` (cluster/proc.py).  With a watchdog the
      detection path is the hard-evidence escalation (pipe EOF / exit
      code); without one the killer SIGKILLs and then calls
      ``fail_replica`` itself, since no machinery would ever notice.

    The last alive replica is killed only when a restart-enabled
    supervisor is attached (the fleet provably recovers); otherwise a
    wedge-mode kill is skipped with a warning (the historical
    contract), while ``"sigkill"`` raises ValueError — really killing
    the last real process with no restart path is an outage by
    construction, and asking for it is a plan bug, not a scenario.

    ``router`` may be bound after construction (``killer.router = r``) —
    ``run_chaos_soak`` builds the router itself and binds the killer to
    it before the sweep starts.
    """

    KILL_MODES = ("auto", "wedge", "sigkill", "partition", "halfopen")
    site = inject.SITE_REPLICA

    def __init__(self, plan: FaultPlan, router=None, mode: str = "auto"):
        if mode not in self.KILL_MODES:
            raise ValueError(f"unknown kill mode {mode!r}: expected one "
                             f"of {self.KILL_MODES}")
        self.plan = plan
        self.router = router
        self.mode = mode
        self.kills: List[int] = []

    def _refuse_mid_scale(self, victim: int, replica) -> None:
        """Refuse a victim inside the drain/retire window
        (cluster/autoscale.py scale events set ``Replica.draining`` /
        ``Replica.retiring``): a kill there would orphan the drain
        snapshot mid-migration — its pinned sequences belong to neither
        side — which is a plan bug, not a chaos scenario."""
        draining = getattr(replica, "draining", False)
        retiring = getattr(replica, "retiring", False)
        if draining or retiring:
            b_kind = getattr(replica.backend, "kind",
                             type(replica.backend).__name__)
            b_transport = getattr(replica.backend, "transport_kind",
                                  "in-process")
            raise ValueError(
                f"{type(self).__name__} refuses replica {victim} "
                f"(kind={b_kind!r}, transport={b_transport!r}): it is "
                f"mid-{'drain' if draining else 'retire'} — a kill "
                f"inside the scale-event window would orphan the drain "
                f"snapshot; schedule the kill outside scale events")

    def _kill(self, victim: int, mode: Optional[str] = None) -> None:
        """Deliver the kill per ``mode`` (defaults to ``self.mode``;
        victim already chosen, last-alive policy already applied in
        ``checkpoint``)."""
        replica = self.router.replicas[victim]
        self._refuse_mid_scale(victim, replica)
        is_proc = hasattr(replica, "kill_process")
        health = getattr(self.router, "health", None)
        # name the victim precisely in refusals: its worker kind and
        # transport tell the reader WHICH fleet shape the plan mismatched
        b_kind = getattr(replica.backend, "kind",
                         type(replica.backend).__name__)
        b_transport = getattr(replica.backend, "transport_kind",
                              "in-process")
        mode = self.mode if mode is None else mode
        if mode == "auto":
            if is_proc:
                raise ValueError(
                    f"ReplicaKiller(mode='auto') refuses out-of-process "
                    f"replica {victim} (kind={b_kind!r}, "
                    f"transport={b_transport!r}): wedging a "
                    f"ProcReplica's proxy "
                    f"would only simulate a death the fleet could take "
                    f"for real — say mode='sigkill' (or ProcKiller) for "
                    f"a real SIGKILL, or mode='wedge' to simulate on "
                    f"purpose")
            mode = "wedge" if health is not None else "fail"
        if mode == "wedge":
            if health is None:
                raise ValueError(
                    f"ReplicaKiller(mode='wedge') without an attached "
                    f"HealthWatchdog: nothing would ever detect the "
                    f"wedge on replica {victim} (attach_health, or use "
                    f"mode='auto' for direct fail_replica)")
            replica.wedge()
        elif mode in ("partition", "halfopen"):
            # link fault, not a kill: sever the victim's REAL socket in
            # one ("halfopen") or both ("partition") directions — the
            # router's relink path must heal the SAME incarnation
            if not getattr(replica, "supports_relink", False):
                raise ValueError(
                    f"ReplicaKiller(mode={mode!r}) refuses replica "
                    f"{victim} (kind={b_kind!r}, "
                    f"transport={b_transport!r}): partitioning needs a "
                    f"socket-transport "
                    f"ProcReplica (transport='socket') — a pipe/in-"
                    f"process replica has no network link to cut")
            if health is None:
                raise ValueError(
                    f"ReplicaKiller(mode={mode!r}) without an attached "
                    f"HealthWatchdog/relink supervisor: nothing would "
                    f"ever heal the partitioned link on replica {victim} "
                    f"(attach_health first)")
            replica.partition_link(halfopen=(mode == "halfopen"))
        elif mode == "sigkill":
            if not is_proc:
                raise ValueError(
                    f"ReplicaKiller(mode='sigkill') needs an out-of-"
                    f"process victim with kill_process() (cluster/"
                    f"proc.py ProcReplica); replica {victim} "
                    f"(kind={b_kind!r}, transport={b_transport!r}) is "
                    f"in-process — use mode='wedge'/'auto'")
            replica.kill_process()
            if health is None:
                # no watchdog: nobody would ever observe the corpse —
                # the killer completes the PR 6 two-in-one semantics
                self.router.fail_replica(victim)
        else:
            self.router.fail_replica(victim)

    def checkpoint(self) -> Optional[int]:
        """Incident-boundary poll: kills one replica on a scheduled
        "crash"; returns the victim's replica id, else None."""
        fault = self.plan.poll(self.site)
        if fault is None or self.router is None:
            return None
        if fault.kind in ("partition", "halfopen"):
            # a LINK fault, not a kill: mode rides the fault kind, and
            # the last-alive kill policy does not apply — a partitioned
            # link heals by relink (same incarnation), which _kill's
            # watchdog requirement guarantees is supervised
            mode = fault.kind
        elif fault.kind == "crash":
            mode = None               # _kill resolves self.mode
        else:
            log.warning("replica fault %r ignored: only 'crash'/"
                        "'partition'/'halfopen' are meaningful at %s",
                        fault.kind, self.site)
            return None
        alive = self.router.alive_ids()
        sup = getattr(self.router, "supervisor", None)
        restart_on = sup is not None and getattr(sup, "restart_enabled",
                                                 False)
        if mode is None and len(alive) <= 1 and not restart_on:
            if self.mode == "sigkill":
                raise ValueError(
                    f"refusing SIGKILL: {len(alive)} replica(s) alive "
                    f"and no restart-enabled supervisor — killing the "
                    f"last real process is an unrecoverable outage, "
                    f"not a chaos scenario (attach a restart-enabled "
                    f"ReplicaSupervisor)")
            log.warning("replica kill skipped: %d replica(s) alive and "
                        "no restart-enabled supervisor", len(alive))
            return None
        victim = alive[fault.index % len(alive)]
        self._kill(victim, mode)
        self.kills.append(victim)
        METRICS.inc("faults.replica_kills")
        log.warning("replica kill #%d: replica %d killed (%d alive)",
                    len(self.kills), victim, len(self.router.alive_ids()))
        return victim


class ProcKiller(ReplicaKiller):
    """ReplicaKiller specialized for out-of-process fleets: polls
    ``inject.SITE_PROC`` on its own plan and always delivers a REAL
    SIGKILL (``mode="sigkill"``), so the 100-incident kill-and-heal soak
    exercises actual OS process death — pipe EOF / exit-code detection,
    real restart-and-rejoin — under the exact boundary-poll discipline
    the byte-identity proof requires."""

    site = inject.SITE_PROC

    def __init__(self, plan: FaultPlan, router=None):
        super().__init__(plan, router, mode="sigkill")


class NetKiller(ReplicaKiller):
    """ReplicaKiller specialized for LINK faults on a socket-transport
    fleet: polls ``inject.SITE_NET`` on its own plan and severs the
    victim's REAL loopback socket — ``partition`` (both directions) or
    ``halfopen`` (receive direction only), per the scheduled fault kind
    (``mode`` is the default for plain "crash" draws, which a SITE_NET
    plan normally never schedules).  The worker process stays alive and
    warm; healing MUST be a relink (same incarnation, fresh session
    nonce) — ``_kill`` refuses victims without ``supports_relink`` or a
    bound watchdog, so a partition can never become a silent outage."""

    site = inject.SITE_NET

    def __init__(self, plan: FaultPlan, router=None,
                 mode: str = "partition"):
        super().__init__(plan, router, mode=mode)


class HandoffKiller(ReplicaKiller):
    """Kill a tier member EXACTLY between EXPORT and ADOPT — the one
    window where a death could tear a sequence in two (cluster/disagg.py
    ``TierRouter._attempt_handoff`` opens the window on every transfer
    attempt).

    Discipline differs from the incident-boundary killers on purpose:
    ``checkpoint()`` is a no-op (the soak still calls it once per
    incident for uniformity, but nothing is polled there — a mid-handoff
    kill is only meaningful mid-handoff), and ``window()`` polls this
    killer's OWN FaultPlan exactly once per transfer attempt.  Fault
    kinds: "crash" (SIGKILL the victim's worker — real OS death between
    the two phases), "partition"/"halfopen" (sever a socket victim's
    link mid-handoff).  ``target`` picks which side dies: "prefill" (the
    exporter — the run must re-prefill on a surviving prefill replica),
    "decode" (the adopter — ordinary failover on another decode
    replica), or "alternate" (the fault's poll index picks a side, so a
    seeded plan exercises both).

    The TierRouter observes the carnage on its very next step: the
    post-window re-lookup sees the victim dead or the run moved, counts
    a retried handoff, and leaves the run wherever the failover placed
    it — never half-adopted.  Victims killed here pre-stamp their
    backend's ``death_kind`` as "handoff" so the watchdog's
    hard-evidence breakdown (``health.hard_kinds``, the
    ``cluster_hard_detections{kind=}`` Prometheus counter) attributes
    the death to the handoff window, not a generic proc death."""

    site = inject.SITE_HANDOFF
    TARGETS = ("prefill", "decode", "alternate")

    def __init__(self, plan: FaultPlan, router=None,
                 mode: str = "sigkill", target: str = "prefill"):
        if target not in self.TARGETS:
            raise ValueError(f"unknown handoff kill target {target!r}: "
                             f"expected one of {self.TARGETS}")
        super().__init__(plan, router, mode=mode)
        self.target = target
        self.windows = 0       # EXPORT->ADOPT windows opened

    def checkpoint(self) -> Optional[int]:
        """Incident-boundary no-op: this killer only fires inside the
        EXPORT->ADOPT window (``window()``), never at boundaries — the
        soak calls checkpoint on every killer uniformly, and a poll here
        would double-count the plan per incident."""
        return None

    def window(self, router, ghandle: int, src_rid: int,
               dst_rid: int) -> Optional[int]:
        """The EXPORT->ADOPT window for one transfer attempt: poll the
        killer's own plan ONCE; on a scheduled fault, kill the targeted
        tier member while the exported frame is in flight.  Returns the
        victim's replica id, else None."""
        if self.router is None:
            self.router = router
        self.windows += 1
        fault = self.plan.poll(self.site)
        if fault is None:
            return None
        if fault.kind in ("partition", "halfopen"):
            mode = fault.kind
        elif fault.kind == "crash":
            mode = self.mode
        else:
            log.warning("handoff fault %r ignored: only 'crash'/"
                        "'partition'/'halfopen' are meaningful at %s "
                        "(frame kinds drop/corrupt/delay/stale-fence "
                        "belong on the TierRouter's handoff_plan)",
                        fault.kind, self.site)
            return None
        if self.target == "prefill":
            victim = src_rid
        elif self.target == "decode":
            victim = dst_rid
        else:
            victim = (src_rid, dst_rid)[fault.index % 2]
        alive = self.router.alive_ids()
        sup = getattr(self.router, "supervisor", None)
        restart_on = sup is not None and getattr(sup, "restart_enabled",
                                                 False)
        if (mode not in ("partition", "halfopen") and len(alive) <= 1
                and not restart_on):
            # partitions heal by relink (no replica lost) — every other
            # mode removes a replica, so the last-alive policy applies
            log.warning("mid-handoff kill skipped: %d replica(s) alive "
                        "and no restart-enabled supervisor", len(alive))
            return None
        replica = self.router.replicas[victim]
        # the proc-sigkill path below bypasses _kill, so the mid-drain/
        # mid-retire refusal must be applied here as well
        self._refuse_mid_scale(victim, replica)
        if mode == "sigkill":
            if not hasattr(replica, "kill_process"):
                # in-process tier member: no OS process to SIGKILL —
                # wedge if a watchdog can detect it, else fail directly
                # (same deterministic healing path either way)
                health = getattr(self.router, "health", None)
                self._kill(victim,
                           "wedge" if health is not None else "fail")
            else:
                backend = replica.backend
                if getattr(backend, "death_kind", False) is None:
                    # stamp BEFORE the kill: evidence_kind() returns the
                    # first-stamped kind, so the watchdog attributes
                    # this death to the handoff window
                    backend.death_kind = "handoff"
                replica.kill_process()
                if getattr(self.router, "health", None) is None:
                    self.router.fail_replica(victim)
        else:
            self._kill(victim, mode)
        self.kills.append(victim)
        METRICS.inc("faults.handoff_kills")
        log.warning("mid-handoff kill #%d: replica %d (%s side) killed "
                    "between EXPORT and ADOPT of run %d",
                    len(self.kills), victim,
                    "prefill" if victim == src_rid else "decode",
                    ghandle)
        return victim


class StoreKiller:
    """Kill and heal the cross-host prefix-store server
    (cluster/store.py) at incident boundaries.

    Not a ReplicaKiller subclass on purpose: the store is a shared
    DEPENDENCY, not a fleet member — killing it must never remove a
    replica, fail a run, or touch the router at all.  The whole point of
    the fabric's failure contract is that the sweep's report bytes are
    identical with the store alive, dead, or flapping; this killer is
    how the soak proves it.

    Discipline matches the other incident-boundary killers:
    ``checkpoint()`` polls this killer's OWN FaultPlan exactly once per
    incident at ``inject.SITE_STORE`` (never the armed chaos plan, so a
    store death cannot perturb any other site's schedule).  Fault kinds:
    "crash" (SIGKILL the store server — L1 dies with it, L2 ``.page``
    files survive for the next incarnation) and "heal" (respawn it,
    same address when the port can be rebound).  While dead, every
    store op in the fleet degrades to a counted cold miss
    (``engine.prefix_store_misses_remote``) — zero engine errors.

    ``store`` may be a ``StoreServer`` or a ``StoreFabric`` (the soak
    binds the fabric's server after construction, mirroring the
    ``killer.router = r`` idiom)."""

    site = inject.SITE_STORE

    def __init__(self, plan: FaultPlan, store=None):
        self.plan = plan
        self.store = store
        self.router = None     # bound by the soak for uniformity; unused
        self.kills: List[int] = []
        self.heals: List[int] = []
        self._incident = -1

    def _server(self):
        server = getattr(self.store, "server", self.store)
        if server is None:
            raise ValueError(
                "StoreKiller has no store bound: attach a StoreFabric/"
                "StoreServer (run_chaos_soak does this when "
                "store_fabric= is passed) before the sweep starts")
        return server

    def checkpoint(self) -> Optional[int]:
        """One boundary poll; returns the incident index on a kill."""
        self._incident += 1
        fault = self.plan.poll(self.site)
        if fault is None:
            return None
        server = self._server()
        if fault.kind == "crash":
            server.kill()
            self.kills.append(self._incident)
            METRICS.inc("faults.store_kills")
            log.warning("store kill #%d: server pid %s SIGKILLed at "
                        "incident %d (fleet degrades to cold misses)",
                        len(self.kills), server.pid, self._incident)
            return self._incident
        if fault.kind == "heal":
            server.respawn()
            self.heals.append(self._incident)
            METRICS.inc("faults.store_heals")
            log.warning("store heal #%d: server respawned as pid %s "
                        "(incarnation %d) at incident %d",
                        len(self.heals), server.pid, server.incarnation,
                        self._incident)
            return None
        log.warning("store fault %r ignored: only 'crash'/'heal' are "
                    "meaningful at %s (op kinds drop/corrupt/delay/"
                    "partition belong on the RemoteStore's own "
                    "store plan)", fault.kind, self.site)
        return None
