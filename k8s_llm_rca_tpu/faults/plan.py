"""Seeded, fully deterministic fault schedules (the chaos half of the
faults subsystem; the production half is faults/policy.py).

A ``FaultPlan`` maps named injection sites to invocation indices and fault
kinds.  Sites are plain strings counted independently: the i-th call that
polls a site fires the fault scheduled at index i (or nothing).  Because
the schedule is a pure function of ``(seed, spec)`` and the counters
advance one per poll, any chaos run over deterministic code is exactly
reproducible — same seed, same faults, same report bytes (the virtual CPU
mesh and greedy decode keep the rest deterministic).

The plan carries an injectable ``VirtualClock``: slow-call and host-stall
faults advance *virtual* time instead of sleeping, and the retry/backoff
policies (faults/policy.py) read the same clock, so timeout arithmetic in
a chaos run neither sleeps for real nor depends on the wall clock.

The reference has no failure injection of any kind — its only resilience
artifact is the JSONDecodeError retry loop (test_all.py:63-83), which is
exercised by hoping the remote model misbehaves.  Here misbehavior is a
scheduled, replayable input.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

# the vocabulary of injectable behaviors; sites implement the subset that
# makes sense for them (graph queries: error/timeout/slow/poison/empty;
# backend runs: error/budget/stall; engine ticks: oom/preempt/stall/crash;
# the serve process boundary: crash — a supervised kill/restart,
# faults/supervisor.py; the parent<->worker network link at SITE_NET:
# partition (both directions die), halfopen (one direction), delay,
# trickle (byte-at-a-time), duplicate (frame delivered twice), corrupt
# (bit-flipped frame), and heal (clear any sticky link fault) —
# faults/netem.py; the prefill->decode KV handoff at SITE_HANDOFF:
# drop (EXPORT frame lost), corrupt/delay (shared kinds), and
# stale-fence (ADOPT ack loses the fencing race) — cluster/disagg.py)
FAULT_KINDS = ("error", "timeout", "slow", "poison", "empty",
               "budget", "stall", "oom", "preempt", "crash",
               "partition", "halfopen", "delay", "trickle",
               "duplicate", "corrupt", "heal", "drop", "stale-fence")


@dataclass(frozen=True)
class Fault:
    """One scheduled fault: fire ``kind`` at the ``index``-th poll of
    ``site``.  ``delay_s`` is virtual-clock time for slow/stall kinds;
    ``wave`` is the preemption-wave width for the engine "preempt" kind."""

    site: str
    index: int
    kind: str
    delay_s: float = 0.0
    wave: int = 1


class VirtualClock:
    """Deterministic time source: ``sleep`` advances time instead of
    blocking.  Duck-compatible with the ``time`` module for the two
    methods the policies use (``time``/``sleep``), so production code
    takes the real module and chaos runs take this."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def time(self) -> float:
        return self._t

    def perf_counter(self) -> float:
        return self._t

    def sleep(self, seconds: float) -> None:
        self._t += max(0.0, float(seconds))

    advance = sleep


class FaultPlan:
    """Site -> invocation-index -> Fault schedule, with per-site poll
    counters and a fired-fault log (``snapshot`` summarizes a run)."""

    def __init__(self, faults: Sequence[Fault] = (),
                 seed: Optional[int] = None,
                 clock: Optional[VirtualClock] = None):
        self.seed = seed
        self.clock = clock if clock is not None else VirtualClock()
        self._by_site: Dict[str, Dict[int, Fault]] = {}
        for f in faults:
            if f.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {f.kind!r} "
                                 f"(one of {FAULT_KINDS})")
            self._by_site.setdefault(f.site, {})[f.index] = f
        self._counts: Dict[str, int] = {}
        self.fired: List[Fault] = []
        self._cleanups: List[Callable[[], None]] = []

    @property
    def has_faults(self) -> bool:
        """True when any fault is actually scheduled.  An empty plan is
        interleaving-safe (poll counters are per-site sums, and nothing
        fires), so the pipelined sweep scheduler only excludes armed
        plans for which this is True."""
        return bool(self._by_site)

    # ------------------------------------------------------------- build

    @classmethod
    def from_spec(cls, seed: int, spec: Dict[str, Dict[str, Any]],
                  clock: Optional[VirtualClock] = None) -> "FaultPlan":
        """Deterministic plan from ``(seed, spec)``.

        ``spec`` maps site -> rule; a rule combines:
        - ``indices``: {invocation index: kind} — explicit schedule;
        - ``rate`` + ``horizon`` + ``kinds``: each of the first ``horizon``
          invocations faults with probability ``rate``, kind drawn from
          ``kinds`` — sampled ONCE here from ``random.Random(seed)``, so
          the run itself contains no randomness;
        - ``delay_s`` / ``wave``: parameters applied to every fault of the
          rule.

        Sites are iterated sorted, so the same (seed, spec) dict produces
        the identical plan regardless of insertion order.
        """
        rng = random.Random(seed)
        faults: List[Fault] = []
        for site in sorted(spec):
            rule = spec[site]
            delay = float(rule.get("delay_s", 0.0))
            wave = int(rule.get("wave", 1))
            for idx in sorted(rule.get("indices", {})):
                faults.append(Fault(site, int(idx),
                                    rule["indices"][idx], delay, wave))
            rate = float(rule.get("rate", 0.0))
            if rate > 0.0:
                kinds = tuple(rule.get("kinds", ("error",)))
                for i in range(int(rule.get("horizon", 64))):
                    if rng.random() < rate:
                        faults.append(Fault(
                            site, i, kinds[rng.randrange(len(kinds))],
                            delay, wave))
        return cls(faults, seed=seed, clock=clock)

    # -------------------------------------------------------------- poll

    def poll(self, site: str) -> Optional[Fault]:
        """Count one invocation of ``site``; return its scheduled fault
        (logging it as fired) or None."""
        i = self._counts.get(site, 0)
        self._counts[site] = i + 1
        fault = self._by_site.get(site, {}).get(i)
        if fault is not None:
            self.fired.append(fault)
        return fault

    def reset(self) -> None:
        """Rewind every site counter and the fired log (re-arm the same
        schedule for a fresh run)."""
        self._counts.clear()
        self.fired.clear()

    # ---------------------------------------------------------- cleanups

    def add_cleanup(self, fn: Callable[[], None]) -> None:
        """Register state to undo at disarm time (e.g. the paged engine's
        stolen "oom" pages) — ``inject.disarm`` runs these."""
        self._cleanups.append(fn)

    def run_cleanups(self) -> None:
        while self._cleanups:
            self._cleanups.pop()()

    # ------------------------------------------------------------ report

    def snapshot(self) -> Dict[str, Any]:
        """Deterministic run summary for chaos reports."""
        return {
            "seed": self.seed,
            "polls": {s: self._counts[s] for s in sorted(self._counts)},
            "fired": [[f.site, f.index, f.kind] for f in self.fired],
        }
