"""Chaos soak driver: the multi-incident RCA sweep under a seeded
FaultPlan, reported deterministically.

``run_chaos_soak(seed=...)`` builds a fresh stack — engine (or oracle)
backend behind the assistants service, resilient graph executors, the
RCA pipeline with the degradation ladder armed — then drives every
incident with the fault plan armed and returns a report whose bytes are a
pure function of ``(seed, spec, config)``:

- the FaultPlan is sampled once from the seed (plan.from_spec);
- decode is greedy on a fresh engine with a fixed PRNG seed;
- retry backoff runs on the plan's VirtualClock (no real sleeps, no
  wall-clock dependence);
- the report carries only deterministic fields (statuses, degradation
  annotations, attempt counts, fault/retry counters) — wall-clock costs
  and windowed token usage are intentionally excluded.

Two calls with the same seed therefore produce byte-identical
``json.dumps(report, sort_keys=True)`` — the chaos soak test's acceptance
bar — while every incident completes either fully resolved or explicitly
degraded-and-annotated (the ladder's bottom rungs are infallible).
"""

from __future__ import annotations

import contextlib
import json
from typing import Any, Dict, List, Optional, Tuple

from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan, VirtualClock
from k8s_llm_rca_tpu.faults.policy import (
    ResiliencePolicy, ResilientExecutor, RetryPolicy,
)


def default_plan_spec() -> Dict[str, Dict[str, Any]]:
    """The standard chaos mix: Neo4j-shaped graph faults, backend run
    faults (incl. stalls the serve deadline must reap), and engine tick
    faults (preemption waves, allocator exhaustion, host stalls)."""
    return {
        inject.SITE_GRAPH: {
            "rate": 0.10, "horizon": 160, "delay_s": 0.01,
            "kinds": ("error", "timeout", "empty", "slow", "poison"),
        },
        inject.SITE_BACKEND: {
            "rate": 0.15, "horizon": 48,
            "kinds": ("error", "stall", "budget"),
        },
        inject.SITE_ENGINE_TICK: {
            "rate": 0.02, "horizon": 400, "delay_s": 0.01, "wave": 1,
            "kinds": ("preempt", "oom", "stall"),
        },
    }


def _build_engine_service(run_timeout_s: float, clock, journal=None,
                          engine_overrides=None):
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.serve.backend import EngineBackend
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    # sized for the tier-1 budget: ONE prefill bucket (one compile shape),
    # no prefix cache (prefix-hit admission has its own compile shapes and
    # its own tests), a cache just big enough for the stage prompts.
    # ``engine_overrides``: EngineConfig field overrides for the pipelined
    # sweep's composition matrix (prefix_cache, host_overlap, chunked
    # prefill, speculative decode ... — tests/test_sweep_sched.py).
    cfg = TINY.replace(max_seq_len=2560)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    ecfg = EngineConfig(max_batch=4, max_seq_len=2560,
                        prefill_buckets=(2560,),
                        max_new_tokens=96, temperature=0.0,
                        paged=True, page_size=64, num_pages=168,
                        prefix_cache=False, decode_chunk=16)
    if engine_overrides:
        import dataclasses as _dc

        ecfg = _dc.replace(ecfg, **engine_overrides)
    engine = make_engine(cfg, ecfg, params, tok, use_kernel=False)
    # deadlines on the soak's virtual clock, ARMED OR NOT: without this
    # the engine falls back to the armed plan's clock (same object) or —
    # in plan-free pipelined sweeps — to WALL time, where the first
    # compile alone blows the 1.5 s run deadline
    engine.clock = clock
    # the factory hands the SAME engine to a restarted backend: it stands
    # in for the restarted worker's recompiled engine (identical weights,
    # identical compile) without paying a per-crash recompile
    factory = lambda: EngineBackend(engine)        # noqa: E731
    return AssistantService(factory(), run_timeout_s=run_timeout_s,
                            clock=clock, journal=journal), engine, factory


def _build_oracle_service(run_timeout_s: float, clock, journal=None):
    from k8s_llm_rca_tpu.rca.oracle import OracleBackend
    from k8s_llm_rca_tpu.serve.api import AssistantService
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    factory = lambda: OracleBackend(get_tokenizer())   # noqa: E731
    return AssistantService(factory(), run_timeout_s=run_timeout_s,
                            clock=clock, journal=journal), None, factory


def _build_cluster_service(run_timeout_s: float, clock, journal=None,
                           n_replicas: int = 2, oracle: bool = False,
                           selfheal: bool = False, health_policy=None,
                           proc: bool = False, transport: str = "pipe",
                           tier_split: Optional[Tuple[int, int]] = None,
                           handoff_plan=None,
                           fleet_telemetry: bool = False):
    """N-replica serving behind a ClusterRouter (cluster/).  ``oracle``
    replicas are scripted backends — the cheap mode the 100-incident
    replica-kill soak runs on (tier-1 budget); engine replicas reuse the
    single-engine soak's TINY config, sharded onto disjoint submeshes.

    ``proc``: out-of-process replicas (cluster/proc.py) — each replica's
    scripted-oracle backend runs in its OWN interpreter behind the wire
    protocol, so a killer can deliver REAL SIGKILLs and the watchdog
    detects actual process death.  The workers poll no fault sites
    (exactly like the in-process OracleBackend) and the serving
    semantics are transport-invariant, which is why the proc soak's
    report is byte-identical to the in-process cluster-oracle run (the
    report even says ``cluster-oracle`` — transport is a deployment
    detail, not an outcome).  ``transport`` picks the wire ("pipe" or
    "socket", cluster/net.py): socket workers serve the same framed
    protocol over a loopback TCP link, which a NetKiller can partition
    and the router relink — the report stays byte-identical either way.

    ``selfheal``: arm the self-healing loop (cluster/health.py) — a
    HealthWatchdog on the soak's VirtualClock plus a restart-enabled
    ReplicaSupervisor, so wedged replicas are detected, failed over and
    rejoined in-tree with no external ``fail_replica`` call.

    ``tier_split``: ``(n_prefill, n_decode)`` — split the fleet into
    disaggregated prefill/decode tiers behind a TierRouter
    (cluster/disagg.py); every run admits on the prefill tier and its
    KV (for scripted workers: its placement) moves to a decode replica
    through the transactional EXPORT -> ADOPT -> RELEASE handoff.
    ``handoff_plan``: the TierRouter's own SITE_HANDOFF FaultPlan.

    ``fleet_telemetry``: opt proc workers into the fleet flight
    recorder (cluster/proc.py telemetry shipping) — each worker runs
    its own Tracer and ships spans/ticks back on reply frames.  OFF by
    default and deliberately NOT inferred from an active tracer, so a
    soak's spec (and therefore its worker argv) only changes when the
    caller asks; shipping polls no fault sites either way, which is the
    telemetry-on-vs-off report byte-identity bar
    (tests/test_fleet_obs.py).

    Returns ``(service, engines, factory, router)`` — ``engines`` is the
    per-replica engine list ([] for oracle replicas) so the caller can
    assert EVERY replica ends clean, and ``factory`` returns the SAME
    router (replica engines stand in for restarted workers, exactly like
    the single-engine soak's factory)."""
    from k8s_llm_rca_tpu.cluster import ClusterRouter, Replica
    from k8s_llm_rca_tpu.serve.api import AssistantService

    if proc:
        from k8s_llm_rca_tpu.cluster.proc import build_proc_replicas

        # telemetry-off keeps the spec (and worker argv) byte-identical
        # to the pre-flight-recorder fleet: the flag only exists when on
        replicas = build_proc_replicas(
            n_replicas, kind="oracle", transport=transport,
            **({"trace": True} if fleet_telemetry else {}))
        engines = []
    elif oracle:
        from k8s_llm_rca_tpu.rca.oracle import OracleBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer()
        replicas = [Replica(i, OracleBackend(tok),
                            rebuild=lambda tok=tok: OracleBackend(tok))
                    for i in range(n_replicas)]
        engines = []
    else:
        from k8s_llm_rca_tpu.cluster import build_replicas
        from k8s_llm_rca_tpu.config import TINY, EngineConfig

        cfg = TINY.replace(max_seq_len=2560)
        replicas = build_replicas(
            cfg,
            EngineConfig(max_batch=4, max_seq_len=2560,
                         prefill_buckets=(2560,),
                         max_new_tokens=96, temperature=0.0,
                         paged=True, page_size=64, num_pages=168,
                         prefix_cache=False, decode_chunk=16),
            n_replicas, seed=0, use_kernel=False)
        engines = [r.backend.engine for r in replicas]
        for eng in engines:
            # virtual-clock deadlines even without an armed plan (see
            # _build_engine_service)
            eng.clock = clock
    if tier_split is not None:
        from k8s_llm_rca_tpu.cluster import TierRouter

        n_prefill, n_decode = int(tier_split[0]), int(tier_split[1])
        if n_prefill + n_decode != n_replicas:
            raise ValueError(
                f"tier_split {tier_split} must sum to the fleet size "
                f"({n_replicas}): tiers partition the SAME replicas, "
                f"they do not add capacity")
        router = TierRouter(replicas[:n_prefill], replicas[n_prefill:],
                            handoff_plan=handoff_plan)
    else:
        router = ClusterRouter(replicas)
    if selfheal:
        from k8s_llm_rca_tpu.cluster import (
            HealthWatchdog, ReplicaSupervisor,
        )

        router.attach_health(HealthWatchdog(health_policy, clock=clock),
                             ReplicaSupervisor())
    factory = lambda: router                           # noqa: E731
    return (AssistantService(router, run_timeout_s=run_timeout_s,
                             clock=clock, journal=journal),
            engines, factory, router)


@contextlib.contextmanager
def _reaping_workers(router):
    """Close any out-of-process replica workers when the block exits —
    even on a sweep failure, a soak must never leak worker processes.
    ``ProcReplica.close`` runs the drain -> TERM -> KILL ladder and
    touches no replica flags, so the caller's post-soak fleet
    assertions (alive/restart counts) see the healed state."""
    try:
        yield
    finally:
        if router is not None:
            for r in router.replicas.values():
                close = getattr(r, "close", None)
                if close is not None:
                    close()


def _incident_row(message: str, result: Dict[str, Any]) -> Dict[str, Any]:
    """Deterministic report row for one completed incident — the fields
    every sweep report carries (wall-clock cost and windowed token usage
    intentionally excluded, see module docstring)."""
    row: Dict[str, Any] = {"error_message": message}
    degraded = result.get("degraded", [])
    row["status"] = "degraded" if degraded else "resolved"
    row["degraded"] = degraded
    row["locator_attempts"] = result.get("locator_attempts")
    if "flight" in result:    # traced soak: deterministic digest
        row["flight"] = result["flight"]
    row["analyses"] = [
        {"cypher_attempts": a.get("cypher_attempts"),
         "used_fallback": "human_cypher_query" in a,
         "n_statepaths": len(a.get("statepath", []))}
        for a in result.get("analysis", [])]
    return row


def run_chaos_soak(seed: int = 0, n_incidents: int = 3,
                   backend: str = "engine",
                   plan_spec: Optional[Dict[str, Any]] = None,
                   run_timeout_s: float = 1.5,
                   tracer: Optional[Any] = None,
                   durable_dir: Optional[str] = None,
                   supervisor: Optional[Any] = None,
                   cluster_replicas: int = 2,
                   killer: Optional[Any] = None,
                   selfheal: bool = False,
                   concurrency: int = 1,
                   tier_split: Optional[Tuple[int, int]] = None,
                   handoff_plan: Optional[FaultPlan] = None,
                   fleet_telemetry: bool = False,
                   store_fabric: Optional[Any] = None
                   ) -> Dict[str, Any]:
    """Drive ``n_incidents`` of the canned corpus through the resilient
    pipeline under an armed FaultPlan; return the deterministic report.

    ``backend``: "engine" (the real paged TINY engine — tick faults and
    stalls bite) or "oracle" (scripted backend — graph faults only; the
    cheap mode bench.py publishes alongside the engine soak), or their
    multi-replica forms "cluster" / "cluster-oracle" — ``cluster_replicas``
    engines (or scripted oracles) on disjoint submeshes behind a
    ClusterRouter (cluster/router.py).  "proc-cluster" runs the oracle
    replicas out-of-process over stdio pipes (cluster/proc.py);
    "net-cluster" runs them over loopback TCP sockets (cluster/net.py),
    the fleet a NetKiller can partition and the router relinks;
    "disagg-cluster" splits the proc-oracle fleet into disaggregated
    prefill/decode tiers behind a TierRouter (cluster/disagg.py,
    ``tier_split`` — default splits the fleet in half, prefill-heavy) —
    all three report as "cluster-oracle" (byte-identity is the
    acceptance bar; tiers and transports are deployment detail).

    ``killer``: optional faults.supervisor.ReplicaKiller — or a LIST of
    killers with pairwise-disjoint fault sites (e.g. a ProcKiller, a
    NetKiller and a HandoffKiller side by side; two killers on one site
    would double-count its plan per incident, a loud ValueError) —
    cluster modes only, each polled once at every incident boundary on
    its OWN FaultPlan; on a scheduled "crash" one replica dies and the
    router fails its work over to survivors.  A HandoffKiller
    (``backend="disagg-cluster"`` only) is instead bound to the
    TierRouter and fires inside the EXPORT -> ADOPT window of KV
    handoffs, never at boundaries.  Like the supervisor, kill stats
    live on the killer objects, never in the report — the kill-soak
    report must stay byte-identical to the unkilled run's (use a
    plan_spec without SITE_ENGINE_TICK for engine clusters: per-tick
    polls shift with the survivor's extra ticks, which is
    fault-schedule divergence, not nondeterminism).

    ``tracer``: optional obs.Tracer — activated for the whole soak with
    its clock REBOUND to the soak's VirtualClock, so every span/event
    timestamp is virtual and the exported Chrome trace is byte-identical
    run over run (the flight recorder's golden acceptance bar).  The
    report then carries a deterministic ``flight`` summary.

    ``fleet_telemetry`` (proc backends only): opt the out-of-process
    workers into the fleet flight recorder — each worker runs its own
    Tracer and ships spans/ticks back piggybacked on reply frames, so a
    traced soak's merged Chrome trace gains one pid track per worker
    incarnation.  Shipping polls NO fault sites and adds NO report
    fields: ``faults.polls`` and ``report_bytes`` stay byte-identical
    with telemetry on or off (tests/test_fleet_obs.py proves the bar).

    ``durable_dir``: optional directory for the write-ahead run journal
    (serve/journal.py) — every service mutation becomes a durable record.
    The report stays byte-identical with or without it (journaling adds
    no report fields and touches no virtual clock).

    ``supervisor``: optional faults.supervisor.CrashSupervisor (requires
    ``durable_dir``) polled at every incident boundary; on a scheduled
    "crash" fault the serving stack is torn down and rebuilt from the
    journal mid-sweep — the kill/restart chaos scenario.  The supervisor
    runs its OWN FaultPlan, so the armed plan's poll counters (and hence
    the report) match the uninterrupted run exactly; crash/recovery stats
    live on the supervisor object, not in the report.

    ``selfheal`` (cluster modes only): arm the self-healing loop
    (cluster/health.py).  A ``killer`` then *wedges* its victims
    instead of calling ``fail_replica`` — the watchdog detects the
    silence over subsequent pumps, fails the corpse over in-tree and
    the supervisor rejoins a fresh incarnation, so the fleet repeatedly
    returns to full strength (the kill-and-heal soak: report bytes
    still match the unkilled run, and heal stats live on
    ``router.health`` / ``router.supervisor``, never in the report).
    After the sweep the router is pumped a few extra (plan-free) times
    so a wedge landed at the last boundary still heals before the
    engine-clean check.

    ``store_fabric``: optional cluster.store.StoreFabric (build via
    ``build_store_fabric``) — attaches the cross-host prefix-store
    service to the soak.  Exercised exactly once per incident on both
    outcome paths (one put/get round trip through the live server);
    every outcome — hit, miss, dead store — lands ONLY in the fabric's
    own counters, never in the report, so ``report_bytes`` stays
    byte-identical to the store-less run (the cache-fabric acceptance
    bar).  A ``StoreKiller`` in ``killer`` is bound to this fabric and
    SIGKILLs/respawns the real store process at incident boundaries on
    its OWN plan; passing a StoreKiller WITHOUT a fabric, or putting
    SITE_STORE in the armed ``plan_spec`` (it belongs on the store's
    own plan), is refused loudly before any worker spawns.

    ``concurrency``: incidents in flight at once (rca/scheduler.py).  At
    1 (the default) the historical sequential loop runs unchanged.
    Above 1 the sweep is driven by the pipelined SweepScheduler — K slot
    pipelines over the one service — which is only legal without chaos
    machinery: a plan with scheduled faults (fault-to-incident
    attribution is interleaving-dependent), a supervisor/killer
    (boundary polls need a global incident order), or selfheal all raise
    loud ValueErrors.  An EMPTY plan stays armed, so the report's
    ``faults.polls`` counters (per-site sums, interleaving-invariant)
    match the sequential run's and report bytes stay comparable across
    concurrencies.
    """
    from k8s_llm_rca_tpu.config import RCAConfig
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline

    clock = VirtualClock()
    plan = FaultPlan.from_spec(seed, plan_spec or default_plan_spec(),
                               clock=clock)
    if concurrency < 1:
        raise ValueError(f"concurrency must be >= 1, got {concurrency}")
    if concurrency > 1:
        if plan.has_faults:
            raise ValueError(
                "chaos soak with concurrency > 1 is not supported: "
                "scheduled faults are attributed to incidents by poll "
                "order, which is interleaving-dependent — the report "
                "could never match the sequential run.  Run chaos at "
                "concurrency=1, or pass an empty plan_spec (plan-free "
                "pipelined sweeps: run_pipelined_sweep)")
        if supervisor is not None or killer is not None or selfheal:
            raise ValueError(
                "crash/kill/selfheal machinery polls once per incident "
                "BOUNDARY — a pipelined sweep has no global incident "
                "order, so the schedules could never match; concurrency "
                "> 1 requires supervisor=None, killer=None, "
                "selfheal=False")
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                          max_delay_s=0.1, deadline_s=5.0, seed=seed,
                          clock=clock),
        failure_threshold=4, reset_timeout_s=0.5, reduced_tokens=256)

    journal = None
    if durable_dir is not None:
        import os

        from k8s_llm_rca_tpu.serve.journal import RunJournal

        os.makedirs(durable_dir, exist_ok=True)
        journal = RunJournal(os.path.join(durable_dir, "serve.wal"))
    if supervisor is not None and journal is None:
        raise ValueError("supervisor requires durable_dir: the run "
                         "journal is the only recovery source a crash "
                         "leaves behind")

    if tier_split is not None and backend != "disagg-cluster":
        raise ValueError(
            f"tier_split only applies to backend='disagg-cluster' "
            f"(got backend={backend!r}): only a TierRouter has tiers "
            f"to split the fleet into")
    if handoff_plan is not None and backend != "disagg-cluster":
        raise ValueError(
            f"handoff_plan only applies to backend='disagg-cluster' "
            f"(got backend={backend!r}): SITE_HANDOFF is only polled "
            f"inside a TierRouter's transfer attempts")
    if fleet_telemetry and backend not in ("proc-cluster", "net-cluster",
                                           "disagg-cluster"):
        raise ValueError(
            f"fleet_telemetry only applies to out-of-process backends "
            f"('proc-cluster'/'net-cluster'/'disagg-cluster', got "
            f"backend={backend!r}): in-process replicas already share "
            f"the parent tracer — there is nothing to ship")
    if backend == "disagg-cluster" and tier_split is None:
        # prefill-heavy default: the RCA corpus is long-prompt/short-
        # verdict, so ceil(n/2) exporters feed floor(n/2) adopters
        n_prefill = max(1, (cluster_replicas + 1) // 2)
        tier_split = (n_prefill, cluster_replicas - n_prefill)

    # store-fabric validation BEFORE any worker spawns (same leak
    # discipline as the killer checks below): SITE_STORE belongs on the
    # STORE's own plan — an armed chaos plan polling it would shift the
    # armed plan's poll counters with every store op and the fabric run
    # could never settle byte-identical to the store-less run
    if plan_spec and inject.SITE_STORE in plan_spec:
        raise ValueError(
            f"plan_spec must not schedule {inject.SITE_STORE!r}: store "
            f"faults are polled from the RemoteStore's OWN plan "
            f"(cluster.store.RemoteStore(plan=...)), never from the "
            f"armed chaos plan — build the fabric with its own "
            f"FaultPlan and pass it as store_fabric")
    if store_fabric is not None and concurrency > 1:
        raise ValueError(
            "store_fabric is exercised once per incident BOUNDARY — a "
            "pipelined sweep has no global incident order, so the "
            "fabric's op schedule could never match the sequential "
            "run; concurrency > 1 requires store_fabric=None")

    # killer-list validation BEFORE any worker spawns: a ValueError here
    # must not leak subprocesses (_reaping_workers is not entered yet)
    killers: List[Any] = []
    if killer is not None:
        from k8s_llm_rca_tpu.faults.supervisor import HandoffKiller

        killers = (list(killer) if isinstance(killer, (list, tuple))
                   else [killer])
        sites = [k.site for k in killers]
        dup = sorted({s for s in sites if sites.count(s) > 1})
        if dup:
            raise ValueError(
                f"killers must poll pairwise-disjoint fault sites, but "
                f"{dup} appear on more than one killer: two killers on "
                f"one site would double-count its plan per incident and "
                f"the kill schedule could never match a single-killer "
                f"run")
        from k8s_llm_rca_tpu.faults.supervisor import StoreKiller
        for k in killers:
            if (isinstance(k, HandoffKiller)
                    and backend != "disagg-cluster"):
                raise ValueError(
                    f"HandoffKiller requires backend='disagg-cluster' "
                    f"(got {backend!r}): its kill window only opens "
                    f"between EXPORT and ADOPT of a TierRouter handoff")
            if isinstance(k, StoreKiller):
                if store_fabric is None:
                    raise ValueError(
                        "StoreKiller requires store_fabric: there is no "
                        "remote store process to SIGKILL — build one "
                        "with cluster.store.build_store_fabric and pass "
                        "it as store_fabric")
                k.store = store_fabric

    router = None
    if backend == "engine":
        service, engine, factory = _build_engine_service(
            run_timeout_s, clock, journal)
        engines = [engine]
    elif backend in ("cluster", "cluster-oracle", "proc-cluster",
                     "net-cluster", "disagg-cluster"):
        service, engines, factory, router = _build_cluster_service(
            run_timeout_s, clock, journal,
            n_replicas=cluster_replicas,
            oracle=(backend == "cluster-oracle"),
            proc=(backend in ("proc-cluster", "net-cluster",
                              "disagg-cluster")),
            # disagg workers sit on sockets so the mixed-fault soak can
            # point a NetKiller at a tier member (and a HandoffKiller
            # can partition mid-window) — the report is transport-
            # invariant either way
            transport=("socket" if backend in ("net-cluster",
                                               "disagg-cluster")
                       else "pipe"),
            selfheal=selfheal,
            tier_split=tier_split, handoff_plan=handoff_plan,
            fleet_telemetry=fleet_telemetry)
        engine = None   # "engine_clean" is per-replica below
    elif selfheal:
        raise ValueError("selfheal requires a cluster backend: the "
                         "watchdog/supervisor loop heals replicas, not "
                         "a single engine")
    else:
        service, engine, factory = _build_oracle_service(
            run_timeout_s, clock, journal)
        engines = []
    if killers:
        if router is None:
            raise ValueError("killer requires a cluster backend: replica "
                             "kills need a router to fail over through")
        from k8s_llm_rca_tpu.faults.supervisor import HandoffKiller
        for k in killers:
            k.router = router
            if isinstance(k, HandoffKiller):
                router.handoff_killer = k
    meta = ResilientExecutor(InMemoryGraphExecutor(build_metagraph()),
                             policy, dep="graph.meta")
    state = ResilientExecutor(InMemoryGraphExecutor(build_stategraph()),
                              policy, dep="graph.state")
    # construct (and seed) the pipeline BEFORE arming: the vocabulary
    # bootstrap queries are setup, not chaos surface
    pipeline = RCAPipeline(
        service, meta, state,
        RCAConfig(locator_max_new_tokens=192, cypher_max_new_tokens=96,
                  analyzer_max_new_tokens=96, fresh_threads=True),
        resilience=policy)
    pipelines: List[RCAPipeline] = [pipeline]
    if concurrency > 1:
        # K slot pipelines over the ONE service; slot 0 is the
        # already-seeded pipeline.  Built HERE, before arming, for the
        # same reason as slot 0: __post_init__'s vocabulary bootstrap
        # issues a graph.query, and counting K-1 extra setup polls in
        # ``faults.polls`` would make the report depend on concurrency.
        # Clones share cfg and executors but get their OWN ladder
        # policy (same constants, same shared retry object):
        # ResiliencePolicy.degradations is per-incident state reset by
        # begin_incident, so one shared instance across interleaved
        # machines would let machine A's reset wipe machine B's
        # accumulating annotations
        pipelines += [
            RCAPipeline(service, meta, state, pipeline.cfg,
                        resilience=ResiliencePolicy(
                            retry=policy.retry,
                            failure_threshold=policy.failure_threshold,
                            reset_timeout_s=policy.reset_timeout_s,
                            reduced_tokens=policy.reduced_tokens))
            for _ in range(concurrency - 1)]

    obs_ctx: Any = contextlib.nullcontext()
    if tracer is not None:
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        tracer.clock = clock          # virtual timestamps (see docstring)
        obs_ctx = obs_trace.tracing(tracer)

    incidents: List[Dict[str, Any]] = []
    n_resolved = n_degraded = n_failed = 0
    with inject.armed(plan), obs_ctx, _reaping_workers(
            router if backend in ("proc-cluster", "net-cluster",
                                  "disagg-cluster")
            else None):
        if concurrency > 1:
            from k8s_llm_rca_tpu.rca.scheduler import (
                IncidentFailure, SweepScheduler,
            )

            messages = [INCIDENTS[i % len(INCIDENTS)].message
                        for i in range(n_incidents)]
            for message, result in zip(
                    messages, SweepScheduler(pipelines).run(messages)):
                if isinstance(result, IncidentFailure):
                    incidents.append({"error_message": message,
                                      "status": "failed",
                                      "error": result.error})
                    n_failed += 1
                    continue
                row = _incident_row(message, result)
                if row["status"] == "degraded":
                    n_degraded += 1
                else:
                    n_resolved += 1
                incidents.append(row)
        else:
            for i in range(n_incidents):
                message = INCIDENTS[i % len(INCIDENTS)].message
                try:
                    result = pipeline.analyze_incident(message)
                except Exception as e:  # noqa: BLE001 — must never happen:
                    # the ladder's bottom rungs are infallible; a row here
                    # is a soak FAILURE the test asserts against
                    incidents.append({"error_message": message,
                                      "status": "failed",
                                      "error": f"{type(e).__name__}: {e}"})
                    n_failed += 1
                    if supervisor is not None:
                        # keep supervisor polls at exactly one per incident
                        # (both outcome paths), so its schedule is a pure
                        # function of (plan, n_incidents)
                        service = supervisor.checkpoint(
                            pipeline, service, factory, run_timeout_s,
                            clock)
                    for k in killers:
                        k.checkpoint()
                    if store_fabric is not None:
                        store_fabric.exercise(i)
                    continue
                row = _incident_row(message, result)
                if row["status"] == "degraded":
                    n_degraded += 1
                else:
                    n_resolved += 1
                incidents.append(row)
                if supervisor is not None:
                    # incident boundary: the supervisor's own plan decides
                    # whether the "process" dies here; on crash the
                    # recovered service replaces ours (pipeline rebound
                    # inside)
                    service = supervisor.checkpoint(
                        pipeline, service, factory, run_timeout_s, clock)
                # same discipline, replica granularity: exactly one poll
                # per incident per killer on both outcome paths (each
                # killer's own plan; the router fails the victim over in
                # place).  List order is the caller's — stable, so a
                # multi-killer schedule is a pure function of the plans
                for k in killers:
                    k.checkpoint()
                # fabric traffic AFTER the killer boundary, so a store
                # killed at boundary i is exercised dead during incident
                # i (counted cold misses on the fabric object) and a
                # heal at a later boundary restores hits — the report
                # never sees either (byte-identity bar)
                if store_fabric is not None:
                    store_fabric.exercise(i)

        if router is not None and router.health is not None:
            # kill-and-heal drain: a wedge landed at the LAST incident
            # boundary has not accrued its missed probes yet — keep
            # pumping (idle replicas: no armed-plan polls) until the
            # watchdog's verdict lands and the supervisor returns the
            # fleet to N.  Bounded: one wedge needs at most
            # hung_tick_threshold probes plus the healing pump.
            budget = router.health.policy.hung_tick_threshold + 2
            for _ in range(budget):
                # healthy(), not alive-and-not-wedged: a SIGKILLed proc
                # replica is alive-looking until the watchdog's verdict
                # (cluster/replica.py) — the old predicate would break
                # out with a corpse still in the fleet
                if all(r.healthy() for r in router.replicas.values()):
                    break
                router.pump()

    if journal is not None:
        # close the CURRENT journal (a supervised crash may have swapped
        # in a reopened one on the same path)
        live_journal = getattr(service, "_journal", None)
        if live_journal is not None:
            live_journal.close()

    report = {
        "seed": seed,
        # proc-cluster, net-cluster AND disagg-cluster report as
        # cluster-oracle ON PURPOSE: the workers run the same scripted
        # oracle over a different transport (pipe or socket) or tier
        # topology, and the acceptance bar is byte-identity against the
        # in-process run — a transport/tier tag would be the one
        # engineered difference
        "backend": ("cluster-oracle"
                    if backend in ("proc-cluster", "net-cluster",
                                   "disagg-cluster")
                    else backend),
        "n_incidents": n_incidents,
        "completed": n_resolved + n_degraded,
        "resolved": n_resolved,
        "degraded": n_degraded,
        "failed": n_failed,
        "retries": policy.counters["retries"],
        "policy": policy.snapshot(),
        "faults": plan.snapshot(),
        "virtual_elapsed_s": round(clock.time(), 6),
        "incidents": incidents,
    }
    if tracer is not None:
        report["flight"] = tracer.flight_summary()
    if router is not None and engines:
        # restarts swap fresh engines into the replicas; the clean check
        # must look at the CURRENT incarnations (the corpses were cancel-
        # drained through the failover path)
        engines = [r.backend.engine for r in router.replicas.values()
                   if getattr(r.backend, "engine", None) is not None]
    if engines:
        # the chaos run must leave EVERY engine clean — killed replicas
        # included (failover cancels through the normal retire path, so a
        # leaked page on a dead replica is a failover bug): drained,
        # allocator invariants intact, no pages beyond prefix residency
        clean = True
        for eng in engines:
            eng.allocator.check()
            resident = (eng.prefix_cache.n_resident
                        if eng.prefix_cache else 0)
            clean = clean and bool(
                not eng.has_work
                and eng.allocator.n_free + resident
                == eng.engine_cfg.num_pages - 1)
        report["engine_clean"] = clean
    if router is not None:
        report["cluster_replicas"] = cluster_replicas
    return report


def report_bytes(report: Dict[str, Any]) -> bytes:
    """Canonical bytes of a soak report (the byte-identity check)."""
    return json.dumps(report, sort_keys=True,
                      separators=(",", ":")).encode()


def run_pipelined_sweep(seed: int = 0, n_incidents: int = 10,
                        backend: str = "engine", concurrency: int = 4,
                        run_timeout_s: float = 1.5,
                        incidents: Optional[List[str]] = None,
                        tracer: Optional[Any] = None,
                        durable_dir: Optional[str] = None,
                        resilience: bool = False,
                        cluster_replicas: int = 2,
                        engine_overrides: Optional[Dict[str, Any]] = None,
                        rca_overrides: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
    """Plan-free pipelined RCA sweep: ``concurrency`` incidents in flight
    over one shared backend (rca/scheduler.py::SweepScheduler).

    This is the scheduling-parity and bench surface of ISSUE 11: the
    returned ``report`` carries only scheduling-INVARIANT fields — per-
    incident statuses, degradation annotations, attempt counts, the
    decoded cypher queries and audit report texts, and exact run-id-
    attributed token usage — so ``report_bytes(out["report"])`` must be
    byte-identical across concurrencies (1 vs 4 vs 16) under greedy
    decode.  Everything scheduling-DEPENDENT (pump counts, inflight
    samples, queue-wait spans, flight summaries, resilience counters)
    lives in ``out["stats"]`` instead.

    ``backend``: "engine" | "oracle" | "cluster" | "cluster-oracle" (the
    chaos soak's stacks, built plan-free).  ``incidents``: explicit
    message list (tests interleave retry-with-feedback and resilience-
    ladder incidents); default is the canned corpus cycled
    ``n_incidents`` times.  ``resilience``: arm the degradation ladder
    (identical policy constants to the chaos soak).
    ``engine_overrides`` / ``rca_overrides``: EngineConfig / RCAConfig
    field overrides for the composition matrix (prefix cache, host
    overlap, chunked prefill, speculative decode, concurrent audits).

    Returns ``{"report", "stats", "service", "engines", "router"}`` —
    the live handles let tests run the journal/recovery agreement and
    engine-clean checks against the exact stack the sweep used.
    """
    from k8s_llm_rca_tpu.config import RCAConfig
    from k8s_llm_rca_tpu.graph import InMemoryGraphExecutor
    from k8s_llm_rca_tpu.graph.fixtures import (
        INCIDENTS, build_metagraph, build_stategraph,
    )
    from k8s_llm_rca_tpu.rca import RCAPipeline
    from k8s_llm_rca_tpu.rca.scheduler import (
        IncidentFailure, SweepScheduler,
    )

    if concurrency < 1:
        raise ValueError(f"concurrency must be >= 1, got {concurrency}")

    clock = VirtualClock()
    journal = None
    if durable_dir is not None:
        import os

        from k8s_llm_rca_tpu.serve.journal import RunJournal

        os.makedirs(durable_dir, exist_ok=True)
        journal = RunJournal(os.path.join(durable_dir, "serve.wal"))

    router = None
    if backend == "engine":
        service, engine, _factory = _build_engine_service(
            run_timeout_s, clock, journal,
            engine_overrides=engine_overrides)
        engines = [engine]
    elif backend in ("cluster", "cluster-oracle"):
        if engine_overrides:
            raise ValueError("engine_overrides applies to the single-"
                             "engine backend only (cluster replicas pin "
                             "the soak's TINY config)")
        service, engines, _factory, router = _build_cluster_service(
            run_timeout_s, clock, journal, n_replicas=cluster_replicas,
            oracle=(backend == "cluster-oracle"))
    elif backend == "oracle":
        if engine_overrides:
            raise ValueError("engine_overrides applies to the single-"
                             "engine backend only")
        service, _engine, _factory = _build_oracle_service(
            run_timeout_s, clock, journal)
        engines = []
    elif backend in ("proc-cluster", "net-cluster", "disagg-cluster"):
        raise ValueError(
            f"backend={backend!r} is chaos-soak-only (run_chaos_soak): "
            "the pipelined sweep returns live run handles that would "
            "outlive the worker processes (and a mid-handoff run has no "
            "stable home for a live handle) — use "
            "backend='cluster-oracle' here, or run_chaos_soak for the "
            "out-of-process / disaggregated fleet")
    else:
        raise ValueError(f"unknown backend {backend!r}")

    policy = None
    slot_policies: List[Optional[ResiliencePolicy]] = [None] * concurrency
    meta: Any = InMemoryGraphExecutor(build_metagraph())
    state: Any = InMemoryGraphExecutor(build_stategraph())
    if resilience:
        policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                              max_delay_s=0.1, deadline_s=5.0, seed=seed,
                              clock=clock),
            failure_threshold=4, reset_timeout_s=0.5, reduced_tokens=256)
        meta = ResilientExecutor(meta, policy, dep="graph.meta")
        state = ResilientExecutor(state, policy, dep="graph.state")
        # each slot gets its OWN ladder policy (same constants, shared
        # retry): degradations is per-incident state reset by
        # begin_incident — one shared instance across interleaved
        # machines would cross-wipe annotations (see run_chaos_soak)
        slot_policies = [policy] + [
            ResiliencePolicy(retry=policy.retry,
                             failure_threshold=policy.failure_threshold,
                             reset_timeout_s=policy.reset_timeout_s,
                             reduced_tokens=policy.reduced_tokens)
            for _ in range(concurrency - 1)]

    cfg = RCAConfig(locator_max_new_tokens=192, cypher_max_new_tokens=96,
                    analyzer_max_new_tokens=96, fresh_threads=True)
    if rca_overrides:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, **rca_overrides)
    if not cfg.fresh_threads:
        # refused even at concurrency=1: the K=1 leg is the parity
        # BASELINE, so it must run the same scheduling-invariant prompt
        # regime the K>1 legs are held to
        raise ValueError("run_pipelined_sweep requires fresh_threads="
                         "True: persistent stage threads make prompts "
                         "depend on incident completion order")

    pipelines = [RCAPipeline(service, meta, state, cfg,
                             resilience=slot_policies[i])
                 for i in range(concurrency)]

    obs_ctx: Any = contextlib.nullcontext()
    if tracer is not None:
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        tracer.clock = clock          # virtual timestamps, like the soak
        obs_ctx = obs_trace.tracing(tracer)

    messages = (list(incidents) if incidents is not None
                else [INCIDENTS[i % len(INCIDENTS)].message
                      for i in range(n_incidents)])

    sched = SweepScheduler(pipelines)
    with obs_ctx:
        results = sched.run(messages)

    if journal is not None:
        live_journal = getattr(service, "_journal", None)
        if live_journal is not None:
            live_journal.close()

    rows: List[Dict[str, Any]] = []
    n_resolved = n_degraded = n_failed = 0
    for message, result in zip(messages, results):
        if isinstance(result, IncidentFailure):
            rows.append({"error_message": message, "status": "failed",
                         "error": result.error})
            n_failed += 1
            continue
        row = _incident_row(message, result)
        # the per-incident flight digest is scheduling-dependent (it sees
        # the tracer mid-sweep) — stats territory, never report territory
        row.pop("flight", None)
        # carry the decoded artifacts too: byte-identity then attests
        # actual greedy decode parity, not just structural agreement
        row["token_usage"] = result.get("token_usage")
        for ra, a in zip(row["analyses"], result.get("analysis", [])):
            ra["cypher_query"] = a.get("human_cypher_query",
                                       a.get("cypher_query"))
            ra["reports"] = [sp.get("report")
                             for sp in a.get("statepath", [])]
        if row["status"] == "degraded":
            n_degraded += 1
        else:
            n_resolved += 1
        rows.append(row)

    report: Dict[str, Any] = {
        "seed": seed,
        "backend": backend,
        "n_incidents": len(messages),
        "completed": n_resolved + n_degraded,
        "resolved": n_resolved,
        "degraded": n_degraded,
        "failed": n_failed,
        "incidents": rows,
    }
    if router is not None and engines:
        engines = [r.backend.engine for r in router.replicas.values()
                   if getattr(r.backend, "engine", None) is not None]
    if engines:
        # same bar as the chaos soak: the sweep must leave every engine
        # drained with allocator invariants intact
        clean = True
        for eng in engines:
            eng.allocator.check()
            resident = (eng.prefix_cache.n_resident
                        if eng.prefix_cache else 0)
            clean = clean and bool(
                not eng.has_work
                and eng.allocator.n_free + resident
                == eng.engine_cfg.num_pages - 1)
        report["engine_clean"] = clean
    if router is not None:
        report["cluster_replicas"] = cluster_replicas

    stats: Dict[str, Any] = dict(sched.stats.snapshot())
    stats["concurrency"] = concurrency
    if policy is not None:
        # ladder counters accumulate per SLOT policy; the sums are
        # interleaving-invariant even though the split across slots isn't
        snap = policy.snapshot()
        for p in slot_policies[1:]:
            for k, v in p.counters.items():
                snap["counters"][k] = snap["counters"].get(k, 0) + v
        stats["policy"] = snap
    if tracer is not None:
        stats["flight"] = tracer.flight_summary()
        # per-run latency decomposition (obs/critical_path.py): like the
        # flight digest it reads the tracer, so it is stats territory —
        # scheduling changes queue-wait shares, never report bytes
        from k8s_llm_rca_tpu.obs import critical_path_stats
        stats["critical_path"] = critical_path_stats(tracer)
    return {"report": report, "stats": stats, "service": service,
            "engines": engines, "router": router}


def run_overload_soak(seed: int = 0, n_runs: int = 100, spill: bool = True,
                      max_spilled_pages: int = 96,
                      max_new_tokens: int = 32) -> Dict[str, Any]:
    """Mixed-priority overload soak on the paged TINY engine: ``n_runs``
    incident prompts submitted up front (priorities cycling CRITICAL /
    NORMAL / BATCH) under a scheduled preempt/oom tick-fault schedule, so
    preemption waves bite while the queue is deep.

    Returns ``{"report": ..., "stats": ...}``.  ``report`` is the
    byte-identity surface: its bytes are IDENTICAL with ``spill`` on or
    off, because greedy decode is path-independent — a preemption (KV
    spill/restore OR free/re-prefill) never changes what any sequence
    generates, only WHEN ticks happen (a restore admission samples no
    token, so the spilled run's tick count shifts by one per resume).
    The report therefore carries only per-run outcomes (priority, finish
    reason, text, token counts) and NO tick-sensitive data — no fault
    polls, no tick totals, and not the spill knob itself.  ``stats``
    holds the tick-sensitive numbers (spilled/restored pages,
    preemptions, engine_clean) for assertions OUTSIDE the identity
    check."""
    import jax

    from k8s_llm_rca_tpu.config import TINY, EngineConfig
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.faults.plan import Fault
    from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.serve.backend import Priority
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cfg = TINY.replace(max_seq_len=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tok = get_tokenizer(vocab_size=cfg.vocab_size)
    engine = make_engine(
        cfg, EngineConfig(max_batch=4, max_seq_len=256,
                          prefill_buckets=(256,),
                          max_new_tokens=max_new_tokens, temperature=0.0,
                          paged=True, page_size=16, num_pages=96,
                          prefix_cache=False, decode_chunk=8,
                          max_spilled_pages=(max_spilled_pages if spill
                                             else 0)),
        params, tok, use_kernel=False)
    # explicit tick-fault schedule (indices, not rate-sampled): the two
    # runs' tick counts drift once a spill lands, so a shared RATE plan
    # would fire on different ticks — which is fine for byte-identity
    # (outputs are path-independent) but explicit waves guarantee the
    # spill path is actually exercised early, while the queue is deep
    waves = [Fault(inject.SITE_ENGINE_TICK, i, kind, 0.0, wave=2)
             for i, kind in ((6, "preempt"), (14, "oom"), (22, "preempt"),
                             (30, "oom"), (45, "preempt"), (70, "preempt"))]
    plan = FaultPlan(waves, seed=seed, clock=VirtualClock())
    classes = (Priority.CRITICAL, Priority.NORMAL, Priority.BATCH)
    order: List[int] = []
    priorities: Dict[int, int] = {}
    with inject.armed(plan):
        for i in range(n_runs):
            msg = INCIDENTS[i % len(INCIDENTS)].message
            pri = classes[i % len(classes)]
            sid = engine.submit(tok.encode(f"[inc {i}] {msg}")[:128],
                                priority=pri)
            order.append(sid)
            priorities[sid] = pri
        results = {}
        while engine.has_work:
            for r in engine.step():
                results[r.seq_id] = r
    runs = [{"priority": priorities[sid],
             "finish": results[sid].finish_reason,
             "text": results[sid].text,
             "completion_tokens": results[sid].completion_tokens}
            for sid in order]
    report = {
        "seed": seed, "n_runs": n_runs,
        "runs": runs,
        "by_status": {
            s: sum(1 for r in runs if r["finish"] == s)
            for s in sorted({r["finish"] for r in runs})},
    }
    engine.allocator.check()
    counts = engine._counts or {}
    stats = {
        "spill_enabled": spill,
        "spilled_pages": counts.get("engine.spilled_pages", 0.0),
        "restored_pages": counts.get("engine.restored_pages", 0.0),
        "spill_budget_fallbacks": counts.get(
            "engine.spill_budget_fallbacks", 0.0),
        "preemptions": counts.get("engine.preemptions", 0.0),
        "engine_clean": bool(not engine.has_work
                             and engine.allocator.n_free
                             == engine.engine_cfg.num_pages - 1),
    }
    return {"report": report, "stats": stats}


def run_saturation_scenario(n_replicas: int = 2, max_inflight: int = 2,
                            n_requests: int = 12) -> Dict[str, Any]:
    """Priority-tiered backpressure under saturation: a mixed-priority
    burst against a small EchoBackend cluster WITHOUT pumping between
    starts, so queue depths only grow.  CRITICAL is cap-exempt (always
    admits while a replica is alive), NORMAL fills to the inflight cap,
    BATCH stops one slot short — so the shed order is strictly BATCH
    before NORMAL and never CRITICAL, each shed surfacing as the typed
    ``RouterAdmissionError``.  Every admitted run then pumps to
    completion (CRITICAL always completes)."""
    from k8s_llm_rca_tpu.cluster import ClusterRouter, Replica
    from k8s_llm_rca_tpu.cluster.router import RouterAdmissionError
    from k8s_llm_rca_tpu.serve.backend import (
        EchoBackend, GenOptions, Priority,
    )
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    tok = get_tokenizer()
    router = ClusterRouter(
        [Replica(i, EchoBackend(tok)) for i in range(n_replicas)],
        max_inflight_per_replica=max_inflight)
    classes = (Priority.CRITICAL, Priority.NORMAL, Priority.BATCH)
    outcomes: List[Dict[str, Any]] = []
    handles: Dict[int, int] = {}
    for i in range(n_requests):
        pri = classes[i % len(classes)]
        row: Dict[str, Any] = {"i": i, "priority": pri}
        try:
            handles[i] = router.start(f"incident {i}",
                                      GenOptions(max_new_tokens=4,
                                                 priority=pri))
            row["admitted"] = True
        except RouterAdmissionError as e:
            row["admitted"] = False
            row["error"] = type(e).__name__
            row["detail"] = str(e)
        outcomes.append(row)
    results = {}
    while any(router.busy(h) for h in handles.values()):
        results.update(router.pump())
    admitted = {p: sum(1 for o in outcomes
                       if o["priority"] == p and o["admitted"])
                for p in classes}
    shed = {p: sum(1 for o in outcomes
                   if o["priority"] == p and not o["admitted"])
            for p in classes}
    return {
        "n_replicas": n_replicas, "max_inflight": max_inflight,
        "outcomes": outcomes,
        "admitted_by_class": admitted, "shed_by_class": shed,
        "completed": sum(1 for i, h in handles.items()
                         if results.get(h) is not None
                         and results[h].error is None),
    }


def poisson_arrivals(seed: int, rate_per_s: float, n: int) -> List[float]:
    """Seeded exponential inter-arrival gaps, cumulated to absolute
    arrival offsets — the open-loop schedule (arrivals never wait on
    completions, ROADMAP item 4).  Pure function of ``(seed,
    rate_per_s, n)``; stdlib Mersenne, so byte-stable across hosts."""
    if rate_per_s <= 0.0:
        raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    import random

    rng = random.Random(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate_per_s)
        out.append(round(t, 9))
    return out


def run_open_loop_soak(seed: int = 0, rate_per_s: float = 200.0,
                       n_runs: int = 24, n_replicas: int = 2,
                       selfheal: bool = False,
                       killer: Optional[Any] = None,
                       run_timeout_s: float = 30.0,
                       tick_s: float = 0.005,
                       durable_dir: Optional[str] = None) -> Dict[str, Any]:
    """Open-loop Poisson traffic through serve/api.py: seeded
    exponential inter-arrivals feed ``create_run`` at ``rate_per_s``
    regardless of completions, and the report carries p50/p99
    time-to-report on the VirtualClock (each pump advances ``tick_s``,
    so latency is a deterministic function of pump counts — the
    measured-wall twin lives in bench.py).

    Composable with the kill-and-heal machinery for the SRE-storm
    scenario: ``killer`` (faults.supervisor.ReplicaKiller) is polled
    exactly once per ARRIVAL on its own FaultPlan — with ``selfheal``
    the victims are wedged and the watchdog/supervisor loop heals the
    fleet while the storm keeps arriving.  Kill/heal stats stay on the
    killer/router objects; the report is a pure function of its
    arguments.
    """
    clock = VirtualClock()
    journal = None
    if durable_dir is not None:
        import os

        from k8s_llm_rca_tpu.serve.journal import RunJournal

        os.makedirs(durable_dir, exist_ok=True)
        journal = RunJournal(os.path.join(durable_dir, "openloop.wal"))
    service, _, _, router = _build_cluster_service(
        run_timeout_s, clock, journal, n_replicas=n_replicas,
        oracle=True, selfheal=selfheal)
    if killer is not None:
        killer.router = router
    from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS
    from k8s_llm_rca_tpu.serve.api import RunStatus
    from k8s_llm_rca_tpu.serve.backend import GenOptions

    asst = service.create_assistant(
        "You are an SRE root-cause analyst.", "openloop",
        gen=GenOptions(max_new_tokens=64))
    arrivals = poisson_arrivals(seed, rate_per_s, n_runs)
    pending = list(enumerate(arrivals))
    live: Dict[str, tuple] = {}               # run id -> (i, arrival_t)
    rows: List[Dict[str, Any]] = []
    while pending or live:
        now = clock.time()
        if pending and pending[0][1] <= now:
            i, t_arr = pending.pop(0)
            thread = service.create_thread()
            service.add_message(
                thread.id, INCIDENTS[i % len(INCIDENTS)].message)
            run = service.create_run(thread.id, asst.id)
            live[run.id] = (i, t_arr)
            if killer is not None:
                # arrival boundary: the kill schedule is a pure function
                # of (killer plan, arrival index) — same discipline as
                # the incident-boundary poll in run_chaos_soak
                killer.checkpoint()
            continue
        service._pump()
        now = clock.time()
        for run_id in [r for r in live
                       if service.runs[r].status in RunStatus.TERMINAL]:
            i, t_arr = live.pop(run_id)
            run = service.runs[run_id]
            rows.append({"i": i, "status": run.status,
                         "ttr_s": round(now - t_arr, 9)})
        if pending and not live:
            clock.sleep(max(0.0, pending[0][1] - now))  # idle: jump ahead
        else:
            clock.sleep(tick_s)
    if router.health is not None:
        budget = router.health.policy.hung_tick_threshold + 2
        for _ in range(budget):      # heal a storm-tail wedge (see
            if all(r.healthy()       # run_chaos_soak drain)
                   for r in router.replicas.values()):
                break
            router.pump()
    if journal is not None:
        live_journal = getattr(service, "_journal", None)
        if live_journal is not None:
            live_journal.close()
    rows.sort(key=lambda r: r["i"])
    ttrs = sorted(r["ttr_s"] for r in rows)

    def _pct(q: float) -> Optional[float]:
        if not ttrs:
            return None
        return round(ttrs[min(len(ttrs) - 1, int(q * len(ttrs)))], 9)

    return {
        "seed": seed, "rate_per_s": rate_per_s, "n_runs": n_runs,
        "n_replicas": n_replicas, "selfheal": bool(selfheal),
        "outcomes": rows,
        "completed": sum(1 for r in rows
                         if r["status"] == RunStatus.COMPLETED),
        "failed": sum(1 for r in rows
                      if r["status"] == RunStatus.FAILED),
        "p50_ttr_s": _pct(0.50),
        "p99_ttr_s": _pct(0.99),
        "virtual_elapsed_s": round(clock.time(), 6),
        "fleet_alive": len(router.alive_ids()),
    }


_METERED_ECHO_CLS = None


def metered_echo_class():
    """``_MeteredEcho``: an EchoBackend with FINITE per-pump service
    capacity — it settles at most ``settle_per_pump`` ready runs per
    pump, FIFO by handle.  The plain Echo/Oracle backends settle EVERY
    ready run each pump (infinite parallelism), so fleet size would
    never move time-to-report and an elastic-vs-static comparison would
    be vacuous; metering makes queue depth the latency driver, which is
    exactly the gauge the autoscaler watches.  Built lazily (soak
    convention: serve-layer imports stay inside functions)."""
    global _METERED_ECHO_CLS
    if _METERED_ECHO_CLS is not None:
        return _METERED_ECHO_CLS

    from k8s_llm_rca_tpu.serve.backend import BackendResult, EchoBackend

    class _MeteredEcho(EchoBackend):
        def __init__(self, tokenizer, settle_per_pump: int = 1, **kw):
            if settle_per_pump < 1:
                raise ValueError(
                    f"settle_per_pump must be >= 1 (a backend that "
                    f"settles nothing never drains), got "
                    f"{settle_per_pump}")
            super().__init__(tokenizer, **kw)
            self.settle_per_pump = settle_per_pump

        def pump(self):
            results = {}
            settled = 0
            for handle in sorted(self._inflight):
                if settled >= self.settle_per_pump:
                    break
                prompt, opts, remaining = self._inflight[handle]
                if remaining > 0:
                    self._inflight[handle] = (prompt, opts, remaining - 1)
                    continue
                del self._inflight[handle]
                if self.fail:
                    results[handle] = BackendResult(
                        "", 0, error="echo backend failure")
                    settled += 1
                    continue
                text = (self.reply if self.reply is not None
                        else f"echo: {prompt[-64:]}")
                text = opts.forced_prefix + text + opts.suffix
                results[handle] = BackendResult(
                    text=text,
                    completion_tokens=self.tokenizer.count(text))
                settled += 1
            return results

    _METERED_ECHO_CLS = _MeteredEcho
    return _MeteredEcho


def diurnal_arrivals(seed: int, rate_low_per_s: float,
                     rate_high_per_s: float, period_s: float,
                     n: int) -> List[float]:
    """Seeded non-homogeneous Poisson arrivals under a sinusoidal
    diurnal rate ramp: rate(t) = low + (high - low)·(1 - cos(2πt/T))/2
    — the night trough at t=0, the midday peak at t=T/2.  Sampled by
    thinning against the ``rate_high_per_s`` majorant, so it is a pure
    function of ``(seed, rates, period, n)`` on the stdlib Mersenne
    generator (byte-stable across hosts, like ``poisson_arrivals``)."""
    if rate_low_per_s <= 0.0 or rate_high_per_s < rate_low_per_s:
        raise ValueError(
            f"need 0 < rate_low_per_s <= rate_high_per_s, got "
            f"low={rate_low_per_s}, high={rate_high_per_s}")
    if period_s <= 0.0:
        raise ValueError(f"period_s must be > 0, got {period_s}")
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    import math
    import random

    rng = random.Random(seed)
    t, out = 0.0, []
    while len(out) < n:
        t += rng.expovariate(rate_high_per_s)
        lam = rate_low_per_s + (rate_high_per_s - rate_low_per_s) * 0.5 \
            * (1.0 - math.cos(2.0 * math.pi * t / period_s))
        if rng.random() * rate_high_per_s <= lam:
            out.append(round(t, 9))
    return out


def run_elastic_soak(seed: int = 0, rate_low_per_s: float = 60.0,
                     rate_high_per_s: float = 1500.0,
                     period_s: float = 0.6, n_runs: int = 520,
                     n_min: int = 1, n_max: int = 4,
                     elastic: bool = True,
                     policy: Optional[Any] = None,
                     killer: Optional[Any] = None,
                     settle_per_pump: int = 1,
                     run_timeout_s: float = 30.0,
                     tick_s: float = 0.005) -> Dict[str, Any]:
    """Open-loop diurnal-ramp soak over an ELASTIC fleet — the
    acceptance surface of the autoscaler (cluster/autoscale.py):

    - ``elastic=True``: the router starts with ``n_min`` metered-echo
      replicas; the remaining ``n_max - n_min`` are parked on the
      Autoscaler's reserve (free submeshes).  ``evaluate()`` runs once
      per idle loop iteration, so the fleet grows into the ramp and
      drains back down the far side.
    - ``elastic=False``: the static twin — all ``n_max`` replicas
      serve from t=0, no autoscaler.

    Both modes integrate ``chip_seconds`` identically (alive replicas ×
    every virtual-clock advance), so the bar "elastic p99 time-to-report
    <= static with strictly fewer chip-seconds" compares like with like.
    ``killer`` is polled once per ARRIVAL (run_open_loop_soak
    discipline) — with killers armed DURING scale events the report must
    still come out byte-identical run over run: scale/kill/heal stats
    live on the autoscaler/killer/router objects, never in the report.

    Returns ``{"report": ..., "stats": ...}`` — byte-identity is
    ``report_bytes(out["report"])``; ``stats`` carries the scale/kill
    counters (deterministic too, but harness-side by convention).
    """
    if not 1 <= n_min < n_max:
        raise ValueError(
            f"need 1 <= n_min < n_max (an elastic band), got "
            f"n_min={n_min}, n_max={n_max}")
    clock = VirtualClock()
    from k8s_llm_rca_tpu.cluster import (ClusterRouter, HealthWatchdog,
                                         Replica, ReplicaSupervisor)
    from k8s_llm_rca_tpu.cluster.autoscale import Autoscaler, ScalePolicy
    from k8s_llm_rca_tpu.serve.api import AssistantService, RunStatus
    from k8s_llm_rca_tpu.serve.backend import GenOptions
    from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

    cls = metered_echo_class()
    tok = get_tokenizer()
    replicas = [
        Replica(i, cls(tok, settle_per_pump),
                rebuild=lambda t=tok, c=cls, k=settle_per_pump: c(t, k))
        for i in range(n_max)]
    router = ClusterRouter(replicas[:n_min] if elastic else replicas)
    router.attach_health(HealthWatchdog(None, clock=clock),
                         ReplicaSupervisor())
    scaler = None
    if elastic:
        pol = policy or ScalePolicy(
            high_water=0.5, low_water=0.15, depth_capacity=2,
            sustain_ticks=2, cooldown_ticks=2,
            min_replicas=n_min, max_replicas=n_max)
        scaler = Autoscaler(router, pol, reserve=replicas[n_min:],
                            clock=clock)
    if killer is not None:
        killer.router = router
    service = AssistantService(router, run_timeout_s=run_timeout_s,
                               clock=clock)
    asst = service.create_assistant(
        "You are an SRE root-cause analyst.", "elastic",
        gen=GenOptions(max_new_tokens=16))
    arrivals = diurnal_arrivals(seed, rate_low_per_s, rate_high_per_s,
                                period_s, n_runs)
    from k8s_llm_rca_tpu.graph.fixtures import INCIDENTS

    pending = list(enumerate(arrivals))
    live: Dict[str, tuple] = {}               # run id -> (i, arrival_t)
    rows: List[Dict[str, Any]] = []
    chip_seconds = 0.0

    def _advance(dt: float) -> None:
        # chips burn whenever virtual time passes, busy or idle — the
        # like-with-like integral both fleet modes share
        nonlocal chip_seconds
        if dt <= 0.0:
            return
        chip_seconds += len(router.alive_ids()) * dt
        clock.sleep(dt)

    while pending or live:
        now = clock.time()
        if pending and pending[0][1] <= now:
            i, t_arr = pending.pop(0)
            thread = service.create_thread()
            service.add_message(
                thread.id, INCIDENTS[i % len(INCIDENTS)].message)
            run = service.create_run(thread.id, asst.id)
            live[run.id] = (i, t_arr)
            if killer is not None:
                killer.checkpoint()     # arrival-boundary discipline
            continue
        if pending and not live:
            if scaler is not None:
                scaler.evaluate()       # troughs are where drain-down
            _advance(max(tick_s, pending[0][1] - now))  # fires; idle jump
            continue
        # one service tick: the pump COSTS tick_s of virtual time BEFORE
        # results land, so a replica serves settle_per_pump/tick_s runs
        # per second — finite service capacity is what lets the diurnal
        # peak build the queue the autoscaler watches (a free pump would
        # model an infinitely fast server and the elastic-vs-static
        # comparison would be vacuous)
        if scaler is not None:
            scaler.evaluate()           # one control tick per loop tick
        _advance(tick_s)
        service._pump()
        now = clock.time()
        for run_id in [r for r in live
                       if service.runs[r].status in RunStatus.TERMINAL]:
            i, t_arr = live.pop(run_id)
            run = service.runs[run_id]
            rows.append({"i": i, "status": run.status,
                         "ttr_s": round(now - t_arr, 9)})
    if router.health is not None:
        budget = router.health.policy.hung_tick_threshold + 2
        for _ in range(budget):          # heal a storm-tail wedge
            if all(r.healthy() for r in router.replicas.values()):
                break
            router.pump()
    rows.sort(key=lambda r: r["i"])
    ttrs = sorted(r["ttr_s"] for r in rows)

    def _pct(q: float) -> Optional[float]:
        if not ttrs:
            return None
        return round(ttrs[min(len(ttrs) - 1, int(q * len(ttrs)))], 9)

    report = {
        "seed": seed, "rate_low_per_s": rate_low_per_s,
        "rate_high_per_s": rate_high_per_s, "period_s": period_s,
        "n_runs": n_runs, "n_min": n_min, "n_max": n_max,
        "elastic": bool(elastic), "settle_per_pump": settle_per_pump,
        "outcomes": rows,
        "completed": sum(1 for r in rows
                         if r["status"] == RunStatus.COMPLETED),
        "failed": sum(1 for r in rows
                      if r["status"] == RunStatus.FAILED),
        "p50_ttr_s": _pct(0.50),
        "p99_ttr_s": _pct(0.99),
        "chip_seconds": round(chip_seconds, 9),
        "virtual_elapsed_s": round(clock.time(), 6),
        "fleet_alive": len(router.alive_ids()),
    }
    stats = {
        "scale_ups": scaler.scale_ups if scaler else 0,
        "scale_downs": scaler.scale_downs if scaler else 0,
        "rebalances": scaler.rebalances if scaler else 0,
        "decisions": len(scaler.decisions) if scaler else 0,
        "reserve_free": len(scaler.reserve) if scaler else 0,
        "kills": len(killer.kills) if killer is not None else 0,
    }
    return {"report": report, "stats": stats, "router": router,
            "autoscaler": scaler}
