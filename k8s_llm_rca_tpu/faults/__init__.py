"""Deterministic fault injection + resilience for the serving/RCA stack.

- ``faults.plan``   — seeded `FaultPlan` schedules + `VirtualClock`;
- ``faults.inject`` — arming and the call-site injection points
  (graph executors, EngineBackend, engine tick loops);
- ``faults.policy`` — RetryPolicy / CircuitBreaker / degradation ladder;
- ``faults.soak``   — the chaos soak driver (imported lazily: it pulls in
  the whole rca pipeline, which itself imports the injection points);
- ``faults.supervisor`` — supervised process-crash/restart harness (the
  "crash" kind at ``inject.SITE_PROCESS``, recovery via the serve run
  journal; imported lazily for the same reason as ``soak``).
"""

from k8s_llm_rca_tpu.faults.plan import (  # noqa: F401
    FAULT_KINDS, Fault, FaultPlan, VirtualClock,
)
from k8s_llm_rca_tpu.faults.inject import (  # noqa: F401
    SITE_BACKEND, SITE_ENGINE_TICK, SITE_GRAPH, SITE_PROCESS,
    InjectedFault, InjectedTimeout, arm, armed, disarm,
)
from k8s_llm_rca_tpu.faults.policy import (  # noqa: F401
    CircuitBreaker, CircuitOpen, ResiliencePolicy, ResilientExecutor,
    RetriesExhausted, RetryPolicy, StageDegradation,
)
