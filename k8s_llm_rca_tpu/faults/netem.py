"""Deterministic in-process network-fault proxy for the cluster's
socket transport (cluster/net.py) — the ``tc netem`` of this repo,
minus the kernel and the nondeterminism.

``NetemTransport`` wraps any Transport (socket or pipe) and applies the
link faults a real network can produce, drawn from a seeded
``FaultPlan`` at ``SITE_NET`` so every soak replays byte-identically:

- ``partition``: the link dies in BOTH directions (sends and recvs
  raise ``WireTimeout``) until a ``heal`` draw;
- ``halfopen``: ONE direction dies — sends still flow, replies never
  arrive (recv raises ``WireTimeout``) until a ``heal`` draw;
- ``delay``: the next turn pays ``delay_s`` on the plan's clock (the
  VirtualClock in soaks — no wall time, no flakes);
- ``trickle``: the next frame goes out in ``TRICKLE_SEGMENTS`` tiny
  unaligned writes — the FrameReader's single-deadline assembly must
  reassemble it;
- ``duplicate``: the next reply is delivered twice — the parent's
  stale-id discard must drop the second copy;
- ``corrupt``: the next recv surfaces a bit-flipped frame
  (``WireCorrupt``) — link evidence, not process death;
- ``heal``: clears any sticky partition/halfopen state.

Poll discipline (the soak byte-identity contract, same as
``ReplicaKiller``): the proxy polls its OWN plan — never the armed
chaos plan — once per ``send`` (one RPC turn), so link faults cannot
perturb ``SITE_BACKEND``/``SITE_ENGINE_TICK`` poll counters.

Composition: the unit tests wrap a raw ``SocketTransport`` over a
``socket.socketpair``; the chaos soak instead rides ``NetKiller``
(faults/supervisor.py), which severs the REAL loopback link of a live
worker so the full detect -> relink -> replay path is exercised.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from k8s_llm_rca_tpu.cluster.wire import (
    WireCorrupt, WireTimeout, pack_frame,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.faults.plan import FaultPlan
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

# default virtual-clock cost of a "delay" draw with no delay_s
DEFAULT_DELAY_S = 0.05

# a trickled frame goes out in this many unaligned segments — splits
# the header/payload boundary (headers are 12 bytes) without the
# per-write skb-accounting blowup of literal byte-at-a-time sends
TRICKLE_SEGMENTS = 16


class NetemTransport:
    """Transport wrapper applying seeded ``SITE_NET`` faults per turn.

    Presents the exact Transport surface (send/recv/pending/close plus
    kind/relinkable/nonce passthroughs), so it drops into any caller of
    cluster/net.py transports unchanged.
    """

    def __init__(self, inner, plan: Optional[FaultPlan] = None):
        self.inner = inner
        self.plan = plan
        self._down = False            # sticky: partition (both ways)
        self._half = False            # sticky: halfopen (recv only)
        self._trickle_next = False
        self._dup_next = False
        self._corrupt_next = False
        self._dup_frame: Optional[Dict[str, Any]] = None
        self.faults_applied: Dict[str, int] = {}

    # --------------------------------------------------------- passthrough

    @property
    def kind(self) -> str:
        return self.inner.kind

    @property
    def relinkable(self) -> bool:
        return self.inner.relinkable

    @property
    def nonce(self) -> int:
        return getattr(self.inner, "nonce", 0)

    def pending(self) -> Optional[Dict[str, Any]]:
        return self.inner.pending()

    def fileno(self) -> int:
        return self.inner.fileno()

    def close(self) -> None:
        self.inner.close()

    # ------------------------------------------------------------- faults

    def _clock_sleep(self, seconds: float) -> None:
        clock = getattr(self.plan, "clock", None)
        (clock.sleep if clock is not None else time.sleep)(seconds)

    def _apply(self, fault) -> None:
        if fault is None:
            return
        kind = fault.kind
        self.faults_applied[kind] = self.faults_applied.get(kind, 0) + 1
        METRICS.inc("faults.netem_applied")
        log.warning("netem: %s at %s[%d]", kind, fault.site, fault.index)
        if kind == "partition":
            self._down = True
        elif kind == "halfopen":
            self._half = True
        elif kind == "heal":
            self._down = False
            self._half = False
        elif kind == "delay":
            self._clock_sleep(fault.delay_s or DEFAULT_DELAY_S)
        elif kind == "trickle":
            self._trickle_next = True
        elif kind == "duplicate":
            self._dup_next = True
        elif kind == "corrupt":
            self._corrupt_next = True
        else:
            raise ValueError(
                f"netem cannot apply fault kind {kind!r}: SITE_NET "
                f"draws from partition/halfopen/delay/trickle/"
                f"duplicate/corrupt/heal")

    # -------------------------------------------------------------- wire

    def send(self, msg: Dict[str, Any],
             timeout_s: Optional[float] = None) -> None:
        # one poll per send = one poll per RPC turn, own plan only
        if self.plan is not None:
            self._apply(self.plan.poll(inject.SITE_NET))
        if self._down:
            raise WireTimeout("netem: link partitioned (awaiting heal)")
        if self._trickle_next:
            self._trickle_next = False
            data = pack_frame(msg)
            # small unaligned segments: enough to split every frame
            # boundary the reader cares about, few enough that per-send
            # skb accounting (AF_UNIX charges full truesize per write)
            # cannot wedge the sender before the peer starts reading
            step = max(1, -(-len(data) // TRICKLE_SEGMENTS))
            for i in range(0, len(data), step):
                self.inner.send_raw(data[i:i + step], timeout_s=timeout_s)
            return
        self.inner.send(msg, timeout_s=timeout_s)

    def recv(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        if self._down:
            raise WireTimeout("netem: link partitioned (awaiting heal)")
        if self._half:
            raise WireTimeout(
                "netem: link half-open (sends flow, replies dropped)")
        if self._corrupt_next:
            self._corrupt_next = False
            raise WireCorrupt(
                "netem: injected bit-flip — frame CRC mismatch")
        if self._dup_frame is not None:
            frame, self._dup_frame = self._dup_frame, None
            return frame
        resp = self.inner.recv(timeout_s=timeout_s)
        if self._dup_next:
            self._dup_next = False
            self._dup_frame = dict(resp)
        return resp

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> None:
        if self._down:
            raise WireTimeout("netem: link partitioned (awaiting heal)")
        self.inner.send_raw(data, timeout_s=timeout_s)
