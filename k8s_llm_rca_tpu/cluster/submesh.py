"""Carving disjoint replica submeshes from the global device list.

The reference serves every request through one OpenAI deployment; the
multi-replica equivalent here carves the devices JAX enumerates into N
contiguous groups and builds one dp×tp mesh per group — the same
world-size → dp×mp factoring shape as the mesh helpers surveyed in
SNIPPETS.md [3] (``get_mesh``), specialized to replicas: the slowest
"axis" is the replica index itself (no collectives cross it), and each
group keeps its devices adjacent so the per-replica TP collectives stay
on ICI neighbors exactly like a single-engine mesh would
(runtime/mesh.py device-order note).

Compositions that would need collectives to span replicas (CP, PP, EP)
are rejected loudly by ``engine.validate_replica_mesh``; device overlap
between replicas is rejected by ``engine.validate_disjoint_submeshes``.
On the 8-virtual-device CPU test mesh the supported configurations are
2 replicas × tp4 and 4 replicas × tp2 (each with an exact greedy-parity
test against the plain single-engine path, per repo convention).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
from jax.sharding import Mesh

from k8s_llm_rca_tpu.config import MeshConfig
from k8s_llm_rca_tpu.engine.engine import validate_disjoint_submeshes
from k8s_llm_rca_tpu.runtime.mesh import build_mesh


def carve_replica_meshes(n_replicas: int,
                         devices: Optional[Sequence[jax.Device]] = None,
                         data: int = 1, fsdp: int = 1) -> List[Mesh]:
    """Split the device list into ``n_replicas`` contiguous groups and
    build one dp×fsdp×tp mesh per group.

    ``data``: DP width inside each replica (default 1 — replicas ARE the
    data parallelism); ``fsdp``: parameter-sharding width (all-gather on
    use, runtime/rules.py FSDP_LAYOUT); the model axis takes the rest of
    the group.  Raises loudly when the device count does not divide.
    """
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % n_replicas:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_replicas} "
            f"replica submeshes; pick a replica count dividing the "
            f"device count")
    per = len(devices) // n_replicas
    if per % (data * fsdp):
        raise ValueError(
            f"replica submesh of {per} devices does not carry a data "
            f"axis of {data} times an fsdp axis of {fsdp}")
    cfg = MeshConfig(data=data, fsdp=fsdp, model=per // (data * fsdp))
    meshes = [build_mesh(cfg, devices=devices[i * per:(i + 1) * per])
              for i in range(n_replicas)]
    validate_disjoint_submeshes(meshes)
    return meshes
