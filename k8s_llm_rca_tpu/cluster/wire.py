"""Length-prefixed, CRC-framed JSON message codec for the out-of-process
replica protocol (cluster/proc.py).

One frame = one protocol message, using EXACTLY the WAL's record framing
(utils/wal.py: ``[4-byte BE length][4-byte CRC32][payload]``) so the
corruption semantics are shared with the journal: a torn or bit-flipped
frame is detected by length/CRC validation, never parsed.  The payload is
canonical JSON (sorted keys) — the protocol carries only JSON-safe state
by design (GenOptions ride serve/journal.py's ``encode_gen``; engine
snapshots are the JSON-safe ``snapshot_sequences`` export; the fleet
flight recorder's optional ``trace`` propagation context on requests and
``tel`` telemetry payload on replies are plain JSON fields that ride the
same framing untouched — the codec neither knows nor cares).

The crucial difference from the WAL is the FAILURE CONTRACT.  The WAL
reader stops at the first bad frame and keeps the clean prefix (a torn
tail is normal after a crash).  A torn or corrupt frame on a LIVE pipe is
a protocol breach — the peer process is dying, dead, or compromised — so
the reader raises loudly (``WireCorrupt``) and the caller declares the
worker dead (ProcBackend marks its transport down; the health watchdog
turns that evidence into SUSPECT -> DEAD, never a hang).  Blocking reads
take a timeout (``select`` on the pipe fd): a peer that stops answering
misses its protocol heartbeat and raises ``WireTimeout`` instead of
wedging the parent.

``FrameReader`` buffers partial reads across calls, so it works over
pipes (non-blocking-ish via select + ``read1``), over sockets
(cluster/net.py wraps a socket in an unbuffered ``makefile`` so the fd
stays select-accurate), and over in-memory streams (io.BytesIO) for the
codec unit tests.  A frame split across many arrivals consumes ONE
deadline: ``read_frame`` fixes the deadline on entry and every
``_fill`` select gets only the remaining slice, so a trickling peer can
never stretch a single read past ``timeout_s`` total.  ``timeout_s <=
0`` is rejected loudly (a zero deadline is ambiguous between "poll
once" and "already expired"; callers that want a non-blocking look use
``pending()``), and ``max_buffered_bytes`` bounds the staging buffer so
a garbage-spewing peer is declared corrupt instead of growing ``_buf``
without limit.
"""

from __future__ import annotations

import io
import json
import select
import zlib
from typing import Any, Dict, Optional

from k8s_llm_rca_tpu.utils import wal

HEADER = wal.HEADER                     # (length, crc32) — THE shared header
HEADER_SIZE = wal.HEADER_SIZE
MAX_FRAME_SIZE = wal.MAX_RECORD_SIZE
_CHUNK = 65536


class WireError(RuntimeError):
    """Base class: the frame stream to a worker is unusable."""


class WireEOF(WireError):
    """Clean EOF at a frame boundary — the peer closed its end (a worker
    that drained and exited, or a parent that went away)."""


class WireCorrupt(WireError):
    """Torn frame (EOF mid-frame), CRC mismatch, oversized length, or
    unparseable payload — protocol breach; declare the peer dead."""


class WireTimeout(WireError):
    """No complete frame within the deadline — the peer missed its
    protocol heartbeat; declare it dead rather than hang."""


def pack_frame(msg: Dict[str, Any]) -> bytes:
    """One message -> wire bytes (WAL framing over canonical JSON)."""
    payload = json.dumps(msg, sort_keys=True,
                         separators=(",", ":")).encode()
    return wal.pack_record(payload)


def write_frame(stream, msg: Dict[str, Any]) -> None:
    """Write one frame and flush (a frame is an RPC turn — it must not
    sit in a userspace buffer while the peer blocks on it).  Raises the
    stream's own error (BrokenPipeError and friends) when the peer is
    gone; the caller owns declaring the transport dead."""
    stream.write(pack_frame(msg))
    stream.flush()


class FrameReader:
    """Incremental frame decoder over a readable binary stream.

    ``read_frame(timeout_s)`` returns the next decoded message dict, or
    raises ``WireEOF`` / ``WireCorrupt`` / ``WireTimeout`` per the module
    contract.  Partial bytes are buffered across calls.  ``timeout_s``
    needs a real file descriptor (select); in-memory streams are always
    "ready" and simply read to exhaustion.

    ``max_buffered_bytes`` bounds the staging buffer: a peer spewing
    bytes that never complete a decodable frame (e.g. a plausible header
    whose payload never arrives intact) is declared ``WireCorrupt`` once
    the buffer exceeds the bound, instead of accumulating memory until
    the oversize-header check happens to trigger.  The default admits
    any legal frame plus one read chunk of lookahead.
    """

    # one maximal frame, fully buffered, plus a chunk of the next one —
    # anything beyond this cannot be a legal frame still assembling
    DEFAULT_MAX_BUFFERED = MAX_FRAME_SIZE + HEADER_SIZE + _CHUNK

    def __init__(self, stream, max_buffered_bytes: int = 0):
        self._stream = stream
        self._buf = bytearray()
        self._max_buffered = (max_buffered_bytes
                              if max_buffered_bytes > 0
                              else self.DEFAULT_MAX_BUFFERED)
        try:
            self._fd: Optional[int] = stream.fileno()
        except (AttributeError, OSError, io.UnsupportedOperation):
            self._fd = None

    def _try_decode(self) -> Optional[Dict[str, Any]]:
        buf = self._buf
        if len(buf) < HEADER_SIZE:
            return None
        length, crc = HEADER.unpack(bytes(buf[:HEADER_SIZE]))
        if length > MAX_FRAME_SIZE:
            raise WireCorrupt(
                f"frame length {length} exceeds MAX_FRAME_SIZE "
                f"{MAX_FRAME_SIZE} (corrupt header)")
        if len(buf) < HEADER_SIZE + length:
            return None
        payload = bytes(buf[HEADER_SIZE:HEADER_SIZE + length])
        if zlib.crc32(payload) != crc:
            raise WireCorrupt(
                f"frame CRC mismatch (length {length}): the pipe carried "
                f"corrupted bytes")
        del buf[:HEADER_SIZE + length]
        try:
            msg = json.loads(payload.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise WireCorrupt(
                f"frame payload passed CRC but is not JSON: {e}") from e
        if not isinstance(msg, dict):
            raise WireCorrupt(
                f"frame payload must be a JSON object, got "
                f"{type(msg).__name__}")
        return msg

    def pending(self) -> Optional[Dict[str, Any]]:
        """Decode the next frame from ALREADY-buffered bytes only — never
        touches the stream, never blocks.  Returns None when the buffer
        holds no complete frame.  This is the non-blocking look callers
        used to fake with a tiny timeout: the worker's socket serve loop
        drains every frame a single select wakeup delivered, and the
        parent uses it to sweep stale-nonce replies out of the buffer."""
        return self._try_decode()

    def _fill(self, timeout_s: Optional[float]) -> None:
        """Read at least one more byte into the buffer, honoring the
        timeout when the stream has a pollable fd.  ``timeout_s`` here is
        a remaining-deadline SLICE computed by ``read_frame`` — a frame
        split across arrivals spends one shared deadline, not a fresh
        ``timeout_s`` per fill."""
        if self._fd is not None and timeout_s is not None:
            ready, _, _ = select.select([self._fd], [], [], timeout_s)
            if not ready:
                raise WireTimeout(
                    f"no frame within {timeout_s:.6g}s: peer missed its "
                    f"protocol heartbeat")
        read1 = getattr(self._stream, "read1", None)
        chunk = read1(_CHUNK) if read1 is not None \
            else self._stream.read(_CHUNK)
        if not chunk:
            if self._buf:
                raise WireCorrupt(
                    f"torn frame: EOF with {len(self._buf)} buffered "
                    f"byte(s) mid-frame")
            raise WireEOF("peer closed the stream at a frame boundary")
        self._buf.extend(chunk)
        if len(self._buf) > self._max_buffered:
            raise WireCorrupt(
                f"{len(self._buf)} buffered bytes exceed "
                f"max_buffered_bytes {self._max_buffered} without a "
                f"decodable frame: peer is spewing garbage")

    def read_frame(self, timeout_s: Optional[float] = None
                   ) -> Dict[str, Any]:
        import time as _time

        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(
                f"timeout_s must be > 0, got {timeout_s}: a zero/negative "
                f"deadline is ambiguous (use pending() for a non-blocking "
                f"buffered look, None to block)")
        deadline = (None if timeout_s is None
                    else _time.monotonic() + timeout_s)
        while True:
            msg = self._try_decode()
            if msg is not None:
                return msg
            remaining = None
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise WireTimeout(
                        f"no frame within {timeout_s}s: peer missed its "
                        f"protocol heartbeat")
            self._fill(remaining)
