"""Elastic fleet autoscaler: occupancy-driven scale-up/drain-down and
prefill<->decode tier rebalancing as a deterministic control loop.

The RCA sweep serves a fixed incident batch on fixed silicon, but the
ROADMAP north star is production traffic — bursty and diurnal, not
flat.  PRs 9-14 built every actuator an elastic fleet needs (supervisor
spawn/respawn, per-replica occupancy/queue-depth gauges, live drain
migration, TierRouter KV handoff); ``Autoscaler`` composes them into
the control plane:

- **scale-up** — when a tier's load (max of mean occupancy and mean
  queue depth normalized by ``depth_capacity``) holds at or above
  ``high_water`` for ``sustain_ticks`` consecutive ``evaluate()``
  calls, pop the lowest-id parked replica off the reserve (a free
  submesh with a ``rebuild`` recipe), admit it through
  ``ClusterRouter.add_replica``, and spawn its worker through the
  existing ``ReplicaSupervisor.restart`` rebuild-recipe path — the
  same incarnation counting, health re-arm, and obs re-tag a healed
  replica gets.  ``scale_up`` refuses loudly when no submesh is free.
- **scale-down** — when the tier idles at or below ``low_water`` that
  long, drain the least-loaded worker: engine replicas through
  ``drain_replica`` (live sequences migrate WITH their KV), scripted
  replicas through the deterministic re-start migration (the
  ``fail_replica`` journal contract under ``inject.readmission``,
  minus the failover counters — nothing died).  The worker is then
  retired through its staged ``close()`` (ProcReplica's
  drain→TERM→KILL ladder) and parked back on the reserve, freeing its
  submesh.
- **tier rebalance** — on a ``TierRouter``, when the prefill/decode
  load split shifts past ``rebalance_band`` for
  ``rebalance_sustain_ticks`` evaluations, drain a worker from the fat
  tier within its own tier and re-admit it to the starved tier via
  ``reassign_tier`` — the worker never dies, its warm engine state
  rides along, and queued EXPORT→ADOPT→RELEASE handoffs simply re-look
  up their source next pump, so no in-flight run is lost.

Determinism contract (the health-watchdog contract): ``evaluate()`` is
a pure function of the gauge sequence — no wall clock, no randomness;
under a frozen ``VirtualClock`` the same gauge history yields the same
decision list, and the chaos soak variant with killers armed DURING
scale events settles ``report_bytes`` byte-identical run over run
(faults/soak.py ``run_elastic_soak``).  Scale stats (``scale_ups`` /
``scale_downs`` / ``rebalances`` / ``decisions``) live HERE, never in
reports.

While a replica is mid-drain or mid-retire it is flagged
(``Replica.draining`` / ``Replica.retiring``) and every fault killer
REFUSES to target it (faults/supervisor.py) — a kill inside that
window would orphan the drain snapshot.

Exclusions (loud ValueError, repo convention): un-attached health or a
non-restarting supervisor, reserve replicas without rebuild recipes or
with colliding ids/overlapping submeshes, watermark/hysteresis/
cooldown nonsense in ``ScalePolicy``, scale-up past ``max_replicas``
or with an empty reserve, scale-down below ``min_replicas`` (or a
tier's last member).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from k8s_llm_rca_tpu.cluster.replica import Replica
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

_ALL = "all"   # the single pseudo-tier of an untiered ClusterRouter


@dataclass(frozen=True)
class ScalePolicy:
    """Watermarks, hysteresis, and cooldown for the elastic loop.

    ``high_water`` / ``low_water``: tier-load thresholds for scale-up /
    scale-down, where load = max(mean occupancy, mean queue depth /
    ``depth_capacity``) over the tier's healthy members.  The gap
    between them IS the hysteresis band — a fleet sized so load sits
    inside it takes no action.

    ``sustain_ticks``: consecutive ``evaluate()`` calls a threshold
    must hold before the actuator fires (one noisy gauge sample must
    not flap the fleet).  ``cooldown_ticks``: evaluations to sit out
    after ANY action, so the previous action's effect reaches the
    gauges before the next is judged.

    ``rebalance_band`` / ``rebalance_sustain_ticks``: the prefill vs
    decode load DIFFERENCE (TierRouter only) that must persist before
    a worker migrates from the fat tier to the starved one.
    """

    high_water: float = 0.75
    low_water: float = 0.25
    depth_capacity: int = 4
    sustain_ticks: int = 3
    cooldown_ticks: int = 5
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    rebalance_band: float = 0.25
    rebalance_sustain_ticks: int = 3

    def __post_init__(self):
        if self.high_water <= 0.0:
            raise ValueError(
                f"high_water must be positive (it is a load threshold), "
                f"got {self.high_water}")
        if not 0.0 <= self.low_water < self.high_water:
            raise ValueError(
                f"low_water must sit in [0, high_water) — the gap is the "
                f"hysteresis band that keeps the fleet from flapping — "
                f"got low_water={self.low_water}, "
                f"high_water={self.high_water}")
        if self.depth_capacity < 1:
            raise ValueError(
                f"depth_capacity must be >= 1 (queue depth is normalized "
                f"by it), got {self.depth_capacity}")
        if self.sustain_ticks < 1:
            raise ValueError(
                f"sustain_ticks must be >= 1 (a threshold crossing must "
                f"hold at least one evaluation), got {self.sustain_ticks}")
        if self.cooldown_ticks < 0:
            raise ValueError(
                f"cooldown_ticks must be >= 0, got {self.cooldown_ticks}")
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1 (a fleet of zero cannot "
                f"serve), got {self.min_replicas}")
        if (self.max_replicas is not None
                and self.max_replicas <= self.min_replicas):
            raise ValueError(
                f"max_replicas must exceed min_replicas (an elastic band "
                f"needs room to move), got max_replicas="
                f"{self.max_replicas} <= min_replicas={self.min_replicas}")
        if not 0.0 < self.rebalance_band < 1.0:
            raise ValueError(
                f"rebalance_band must sit in (0, 1) — it is a load "
                f"DIFFERENCE with hysteresis, got {self.rebalance_band}")
        if self.rebalance_sustain_ticks < 1:
            raise ValueError(
                f"rebalance_sustain_ticks must be >= 1, got "
                f"{self.rebalance_sustain_ticks}")


class Autoscaler:
    """The elastic control loop over a (Tier)ClusterRouter.

    ``reserve``: parked ``Replica`` objects — the free submeshes.  Each
    must carry a ``rebuild`` recipe (how a free submesh spawns a
    worker); they are parked ``alive=False`` and revived through the
    supervisor on scale-up.  Retired workers return here, so the
    reserve IS the free-submesh ledger.

    Call ``evaluate()`` once per control tick (the soak drivers call it
    once per loop iteration).  At most ONE action fires per tick,
    preference order scale-up > rebalance > scale-down — capacity
    before savings.  All decisions land in ``self.decisions`` and as
    ``cluster.scale`` trace events; the router gets an ``autoscaler``
    backref so obs/export.py can render fleet-size gauges and
    scale-event counters.
    """

    def __init__(self, router, policy: Optional[ScalePolicy] = None,
                 reserve: Sequence[Replica] = (), clock=None):
        if getattr(router, "health", None) is None:
            raise ValueError(
                "Autoscaler needs a health-attached router "
                "(ClusterRouter.attach_health with a HealthWatchdog): "
                "the control loop reads the watchdog-probed fleet and "
                "scale events re-arm through its register/reset path")
        sup = getattr(router, "supervisor", None)
        if sup is None or not sup.restart_enabled:
            raise ValueError(
                "Autoscaler needs a restart-enabled ReplicaSupervisor "
                "on the router: scale-up spawns workers through the "
                "rebuild-recipe restart path")
        self.router = router
        self.policy = policy or ScalePolicy()
        self.clock = clock
        reserve = sorted(reserve, key=lambda r: r.replica_id)
        seen = set(router.replicas)
        for r in reserve:
            if r.rebuild is None:
                raise ValueError(
                    f"reserve replica {r.replica_id} has no rebuild "
                    f"recipe: a free submesh must know how to spawn a "
                    f"worker (build_replicas records one per engine "
                    f"replica)")
            if r.replica_id in seen:
                raise ValueError(
                    f"reserve replica id {r.replica_id} collides with "
                    f"the fleet/reserve (ids must be unique across both)")
            seen.add(r.replica_id)
            r.alive = False            # parked: not serving, not probed
        meshes = ([x.mesh for x in router.replicas.values()
                   if x.mesh is not None]
                  + [x.mesh for x in reserve if x.mesh is not None])
        if meshes:
            from k8s_llm_rca_tpu.engine.engine import (
                validate_disjoint_submeshes,
            )

            validate_disjoint_submeshes(meshes)
        self.reserve: List[Replica] = reserve
        self._tick = 0
        self._cooldown = 0
        self._over: Dict[str, int] = {}     # tier -> ticks at/above high
        self._under: Dict[str, int] = {}    # tier -> ticks at/below low
        self._skew: Dict[str, int] = {}     # hot tier -> ticks past band
        self.scale_ups = 0
        self.scale_downs = 0
        self.rebalances = 0
        self.decisions: List[Dict[str, Any]] = []
        router.autoscaler = self            # obs backref (export.py)

    # ------------------------------------------------------------- gauges

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.time()
        if inject._ARMED is not None:
            return inject._ARMED.clock.time()
        import time

        return time.time()

    def _tiered(self) -> bool:
        return hasattr(self.router, "tier")

    def _tiers(self) -> List[str]:
        if self._tiered():
            from k8s_llm_rca_tpu.cluster.disagg import (TIER_DECODE,
                                                        TIER_PREFILL)

            return [TIER_PREFILL, TIER_DECODE]
        return [_ALL]

    def _members(self, tier: str) -> List[int]:
        """Healthy, probe-trusted, not-mid-scale members of ``tier`` —
        the population the gauges average over AND the scale-down
        victim pool."""
        router = self.router
        tmap = getattr(router, "tier", None)
        out = []
        for rid, r in router.replicas.items():
            if tier != _ALL and (tmap or {}).get(rid) != tier:
                continue
            if not r.healthy() or r.draining or r.retiring:
                continue
            if router.health.is_suspect(rid):
                continue
            out.append(rid)
        return out

    def load(self, tier: str) -> float:
        """Tier load in [0, inf): max of mean occupancy and mean queue
        depth over ``depth_capacity``.  Scripted replicas report 0.0
        occupancy (cluster/replica.py), so queue depth drives them;
        engine replicas contribute whichever signal is hotter."""
        members = self._members(tier)
        if not members:
            return 0.0
        reps = self.router.replicas
        occ = sum(reps[r].occupancy() for r in members) / len(members)
        depth = (sum(reps[r].queue_depth() for r in members)
                 / len(members) / self.policy.depth_capacity)
        return max(occ, depth)

    def fleet_sizes(self) -> Dict[str, int]:
        """Alive replicas per tier (``{"all": n}`` untiered) — the
        ``cluster_fleet_size{tier=}`` gauge source."""
        router = self.router
        if not self._tiered():
            return {_ALL: len(router.alive_ids())}
        sizes: Dict[str, int] = {t: 0 for t in self._tiers()}
        for rid in router.alive_ids():
            t = router.tier.get(rid)
            if t in sizes:
                sizes[t] += 1
        return sizes

    # ----------------------------------------------------------- the loop

    def evaluate(self) -> Optional[Dict[str, Any]]:
        """One control tick: fold the current gauges into the sustain
        counters and fire at most one actuator.  Returns the decision
        record (also appended to ``self.decisions``) or None."""
        self._tick += 1
        p = self.policy
        tiers = self._tiers()
        loads = {t: self.load(t) for t in tiers}
        for t in tiers:
            self._over[t] = self._over.get(t, 0) + 1 \
                if loads[t] >= p.high_water else 0
            self._under[t] = self._under.get(t, 0) + 1 \
                if loads[t] <= p.low_water else 0
        if self._tiered():
            from k8s_llm_rca_tpu.cluster.disagg import (TIER_DECODE,
                                                        TIER_PREFILL)

            diff = loads[TIER_PREFILL] - loads[TIER_DECODE]
            hot = (TIER_PREFILL if diff >= p.rebalance_band
                   else TIER_DECODE if -diff >= p.rebalance_band
                   else None)
            for t in (TIER_PREFILL, TIER_DECODE):
                self._skew[t] = self._skew.get(t, 0) + 1 \
                    if t == hot else 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        decision = None
        for t in tiers:
            if (self._over[t] >= p.sustain_ticks
                    and self._can_scale_up()):
                decision = self.scale_up(t if self._tiered() else None)
                self._over[t] = 0
                break
        if decision is None and self._tiered():
            from k8s_llm_rca_tpu.cluster.disagg import (TIER_DECODE,
                                                        TIER_PREFILL)

            for hot, fat in ((TIER_PREFILL, TIER_DECODE),
                             (TIER_DECODE, TIER_PREFILL)):
                if (self._skew.get(hot, 0) >= p.rebalance_sustain_ticks
                        and len(self._members(fat)) >= 2):
                    decision = self.rebalance(fat, hot)
                    self._skew[hot] = 0
                    break
        if decision is None:
            for t in tiers:
                if (self._under[t] >= p.sustain_ticks
                        and self._can_scale_down(t)):
                    decision = self.scale_down(
                        t if self._tiered() else None)
                    self._under[t] = 0
                    break
        if decision is not None:
            self._cooldown = p.cooldown_ticks
        return decision

    def _can_scale_up(self) -> bool:
        p = self.policy
        if not self.reserve:
            return False    # at capacity: evaluate() waits, never raises
        return (p.max_replicas is None
                or len(self.router.replicas) < p.max_replicas)

    def _can_scale_down(self, tier: str) -> bool:
        members = self._members(tier)
        floor_ok = len(self.router.alive_ids()) > self.policy.min_replicas
        if tier != _ALL:
            floor_ok = floor_ok and len(members) > 1
        return floor_ok and bool(members)

    # ---------------------------------------------------------- actuators

    def scale_up(self, tier: Optional[str] = None) -> Dict[str, Any]:
        """Spawn one worker onto a free submesh via the supervisor's
        rebuild-recipe path.  Refuses loudly when no submesh is free or
        the fleet is already at ``max_replicas``."""
        router = self.router
        p = self.policy
        if self._tiered() and tier is None:
            raise ValueError(
                "scale_up on a TierRouter needs the tier to grow "
                "('prefill' or 'decode')")
        if (p.max_replicas is not None
                and len(router.replicas) >= p.max_replicas):
            raise ValueError(
                f"refusing to scale up: fleet already at max_replicas="
                f"{p.max_replicas} (ids: {sorted(router.replicas)})")
        if not self.reserve:
            raise ValueError(
                f"no free submesh: the reserve is empty (fleet: "
                f"{sorted(router.replicas)}) — scale-up needs a parked "
                f"Replica with a rebuild recipe to spawn onto")
        replica = self.reserve.pop(0)
        rid = replica.replica_id
        if self._tiered():
            router.add_replica(replica, tier=tier)
        else:
            router.add_replica(replica)
        # the ReplicaSupervisor rebuild-recipe spawn: fresh backend
        # incarnation, obs re-tag, health re-arm — identical to a heal
        router.supervisor.restart(rid)
        self.scale_ups += 1
        return self._record("up", tier or _ALL, rid)

    def scale_down(self, tier: Optional[str] = None) -> Dict[str, Any]:
        """Drain the least-loaded worker of ``tier`` and retire it:
        live sequences migrate (KV snapshot/adopt for engine replicas,
        journal-contract re-start for scripted ones), the staged
        ``close()`` runs if the replica has one, and the worker parks
        back on the reserve as a free submesh."""
        router = self.router
        if self._tiered() and tier is None:
            raise ValueError(
                "scale_down on a TierRouter needs the tier to shrink "
                "('prefill' or 'decode')")
        t = tier or _ALL
        members = self._members(t)
        if not members:
            raise ValueError(
                f"refusing to scale down: no healthy non-draining "
                f"{t} replica to retire")
        if len(router.alive_ids()) <= self.policy.min_replicas:
            raise ValueError(
                f"refusing to scale down: fleet at min_replicas="
                f"{self.policy.min_replicas}")
        if t != _ALL and len(members) <= 1:
            raise ValueError(
                f"refusing to scale down: replica {members[0]} is the "
                f"last healthy {t} tier member")
        rid = min(members,
                  key=lambda r: (router.replicas[r].queue_depth(), r))
        replica = router.replicas[rid]
        migrated = self._drain_out(replica)
        replica.retiring = True
        try:
            close = getattr(replica, "close", None)
            if close is not None:
                close()            # staged drain->TERM->KILL ladder
            router.remove_replica(rid)
        finally:
            replica.retiring = False
        replica.alive = False
        self.reserve.append(replica)
        self.reserve.sort(key=lambda r: r.replica_id)
        self.scale_downs += 1
        return self._record("down", t, rid, migrated=migrated)

    def rebalance(self, fat: str, starved: str) -> Dict[str, Any]:
        """Move one worker from the ``fat`` tier to the ``starved``
        tier without killing it: drain its sequences within its own
        tier, flip its tier via ``reassign_tier`` (warm engine state
        rides along; queued handoffs re-look up their source next
        pump), and revive it with a fresh health baseline."""
        router = self.router
        if not self._tiered():
            raise ValueError(
                "rebalance needs a TierRouter (plain ClusterRouter "
                "fleets have no prefill/decode split to rebalance)")
        members = self._members(fat)
        if len(members) < 2:
            raise ValueError(
                f"refusing to rebalance: the {fat} tier has "
                f"{len(members)} healthy member(s) and must keep one")
        rid = min(members,
                  key=lambda r: (router.replicas[r].queue_depth(), r))
        replica = router.replicas[rid]
        migrated = self._drain_out(replica)
        router.reassign_tier(rid, starved)
        replica.alive = True
        replica.wedged = False
        router.health.reset(rid)   # fresh baseline in the new tier
        self.rebalances += 1
        return self._record("rebalance", starved, rid, migrated=migrated,
                            src_tier=fat)

    # ------------------------------------------------------------ internals

    def _drain_out(self, replica: Replica) -> int:
        """Empty ``replica`` under the mid-drain killer shield: engine
        replicas through ``drain_replica`` (sequences move WITH their
        KV), scripted ones through the re-start migration.  Leaves the
        replica not-alive with zero in-flight runs."""
        router = self.router
        rid = replica.replica_id
        replica.draining = True
        try:
            if router._orphans(rid):
                if hasattr(replica.backend, "snapshot_sequences"):
                    migrated = len(router.drain_replica(rid))
                else:
                    migrated = self._migrate_scripted(rid)
            else:
                migrated = 0
                replica.alive = False
                for session in [s for s, r in router._affinity.items()
                                if r == rid]:
                    del router._affinity[session]
        finally:
            replica.draining = False
        return migrated

    def _migrate_scripted(self, rid: int) -> int:
        """Scripted drain-down: scripted backends have no KV snapshot
        seam (``drain_replica`` refuses them by design), so the live
        runs migrate by deterministic re-start on the survivors under
        their existing global handles — the ``fail_replica`` journal
        contract under ``inject.readmission``, minus the failover
        counters, because nothing died."""
        router = self.router
        replica = router.replicas[rid]
        replica.alive = False
        orphans = router._orphans(rid)
        for ghandle in orphans:
            _, lhandle = router._handle_map[ghandle]
            router._local.pop((rid, lhandle), None)
            replica.backend.cancel(lhandle)
        for session in [s for s, r in router._affinity.items()
                        if r == rid]:
            del router._affinity[session]
        tiered = self._tiered()
        prev = router._route_tier if tiered else None
        if tiered:
            router._route_tier = router.tier.get(rid)
        try:
            for ghandle in orphans:
                prompt, opts = router._runs[ghandle]
                new_rid = router._pick(opts.session, admit=False)
                with inject.readmission():
                    nl = router.replicas[new_rid].backend.start(prompt,
                                                                opts)
                router._handle_map[ghandle] = (new_rid, nl)
                router._local[(new_rid, nl)] = ghandle
        finally:
            if tiered:
                router._route_tier = prev
        if orphans:
            router.migrated_runs += len(orphans)
            METRICS.inc("cluster.migrated_runs", len(orphans))
        return len(orphans)

    def _record(self, kind: str, tier: str, rid: int,
                **extra: Any) -> Dict[str, Any]:
        sizes = self.fleet_sizes()
        decision = {"tick": self._tick, "kind": kind, "tier": tier,
                    "replica": rid, "fleet": sum(sizes.values()),
                    **extra}
        self.decisions.append(decision)
        obs_trace.event("cluster.scale", kind=kind, tier=tier,
                        replica=rid, fleet=decision["fleet"],
                        reserve=len(self.reserve), **extra)
        log.info("autoscale %s: replica %d (%s tier), fleet now %s, "
                 "%d submesh(es) free", kind, rid, tier, sizes,
                 len(self.reserve))
        return decision
