"""Transport seam for the out-of-process replica protocol: the SAME
framed codec (cluster/wire.py) over a stdio pipe pair or a TCP socket.

PR 12's ``ProcBackend`` hardcoded its Popen stdin/stdout pair; this
module extracts that into a two-method ``Transport`` (``send``/``recv``)
so the parent<->worker protocol is deployment-agnostic:

- ``PipeTransport``: the existing behavior, byte-identical — blocking
  ``write_frame`` into the worker's stdin, select-deadlined
  ``FrameReader`` off its stdout.  A pipe to a child process cannot
  partition: every failure IS process death, so pipe transports are not
  relinkable and the PR 12 evidence semantics are unchanged.
- ``SocketTransport``: the cross-host shape (locally provable over
  ``socket.socketpair``/loopback).  Reads ride the same ``FrameReader``
  (an unbuffered ``makefile`` keeps the fd select-accurate); writes gain
  the bounded select-based deadline pipes never needed — a zero-window
  or trickle-reading peer raises ``WireTimeout`` instead of wedging the
  parent in a blocking ``flush()``.  A socket CAN die while the worker
  lives (partition, half-open link, peer reset), so socket transports
  are ``relinkable``: the owner may replace a failed link with a fresh
  connection to the same incarnation (cluster/proc.py's relink path).

Link fencing (the ``hello``/``ready`` handshake, cluster/proc.py): every
connection to a socket worker opens with a parent->worker ``hello``
carrying a monotonic per-connection **session nonce**; the worker adopts
the connection only for a nonce STRICTLY greater than the one it is
serving (dropping the old link — at most one live link per worker, no
split-brain), refuses stale nonces on the new connection, and tags every
reply with the adopted nonce so the parent can discard frames from a
link it already abandoned.  ``client_handshake`` implements the parent
half; the worker half lives in ``cluster/proc.py``'s ``--listen`` serve
loop.

Fleet telemetry (cluster/proc.py) is transport-transparent by design:
the ``trace`` propagation context and piggybacked ``tel`` payloads are
ordinary JSON fields inside ordinary frames, so both transports carry
them unchanged — and the nonce fencing above is what lets the parent
trust that a telemetry payload came from the incarnation it is
attributed to (a stale link's frames, telemetry included, are
discarded before ingestion).
"""

from __future__ import annotations

import select
import socket
import time
from typing import Any, Dict, Optional

from k8s_llm_rca_tpu.cluster.wire import (
    FrameReader, WireEOF, WireTimeout, pack_frame, write_frame,
)

# a frame is one RPC turn on an idle-ish loopback/LAN link: if the peer
# cannot accept 16 MiB in this window its receive path is wedged, which
# is link evidence, not patience territory
DEFAULT_WRITE_TIMEOUT_S = 30.0

# the hello->ready turn of a freshly-accepted connection: the worker is
# already up (it answered the bootstrap frame), so only link latency and
# its select loop are in the window
DEFAULT_HANDSHAKE_TIMEOUT_S = 10.0


def send_with_deadline(sock: socket.socket, data: bytes,
                       timeout_s: float) -> None:
    """Write ``data`` to a connected socket under one overall deadline.

    ``select``-gates every ``send`` so a peer advertising a zero TCP
    window (or reading a byte an hour) raises ``WireTimeout`` instead of
    blocking forever; a reset/closed peer raises its ``OSError``
    (BrokenPipeError/ConnectionResetError) for the caller to classify.

    The socket is switched non-blocking for the duration of the loop
    (and restored after): a BLOCKING ``send`` of a large frame queues
    the WHOLE remainder in the kernel and sleeps when the peer's window
    fills — the select gate only proves the first byte won't block.
    Non-blocking sends return the partial count (or EAGAIN, folded back
    into the select wait), so the deadline actually binds.
    """
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
    deadline = time.monotonic() + timeout_s
    view = memoryview(data)
    sent = 0
    prior_timeout = sock.gettimeout()
    sock.setblocking(False)
    try:
        while sent < len(view):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise WireTimeout(
                    f"peer accepted {sent}/{len(view)} frame byte(s) "
                    f"within {timeout_s}s: send window wedged "
                    f"(zero-window or trickle-reading peer)")
            _, writable, _ = select.select([], [sock], [], remaining)
            if not writable:
                raise WireTimeout(
                    f"peer accepted {sent}/{len(view)} frame byte(s) "
                    f"within {timeout_s}s: send window wedged "
                    f"(socket never became writable again)")
            try:
                sent += sock.send(view[sent:])
            except (BlockingIOError, InterruptedError):
                continue      # spurious wakeup: re-select
    finally:
        sock.settimeout(prior_timeout)


def send_frame_socket(sock: socket.socket, msg: Dict[str, Any],
                      timeout_s: float = DEFAULT_WRITE_TIMEOUT_S) -> None:
    """One message onto a socket under the bounded write deadline."""
    send_with_deadline(sock, pack_frame(msg), timeout_s)


class PipeTransport:
    """The PR 12 stdio pair behind the Transport surface — byte-identical
    behavior: blocking frame write + flush into ``wstream``, deadlined
    frame reads off ``rstream``.  Not relinkable: a broken pipe to a
    child means the child (or its stdio) is gone, which is process-death
    evidence by definition."""

    kind = "pipe"
    relinkable = False

    def __init__(self, wstream, rstream):
        self._wstream = wstream
        self._reader = FrameReader(rstream)
        self._rstream = rstream

    def send(self, msg: Dict[str, Any],
             timeout_s: Optional[float] = None) -> None:
        # a pipe write blocks only while the child is alive-and-reading;
        # the deadline parameter exists for surface parity with sockets
        write_frame(self._wstream, msg)

    def recv(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        return self._reader.read_frame(timeout_s=timeout_s)

    def pending(self) -> Optional[Dict[str, Any]]:
        return self._reader.pending()

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> None:
        """Raw bytes onto the wire (fault-injection seam: netem trickle
        sends a packed frame one byte per call; chaos corruption sends
        bytes that are not a frame at all)."""
        self._wstream.write(data)
        self._wstream.flush()

    def close(self) -> None:
        for stream in (self._wstream, self._rstream):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass


class SocketTransport:
    """One connected socket behind the Transport surface.

    Reads: ``FrameReader`` over an unbuffered ``makefile("rb")`` — no
    userspace buffering between the fd and the reader, so the reader's
    select deadline sees exactly what the kernel holds.  Writes:
    ``send_with_deadline`` — the bounded select-gated write that turns a
    wedged peer into ``WireTimeout``.  ``nonce`` is the session nonce
    this link was fenced with at handshake time (0 for raw/unfenced
    links, e.g. socketpair codec tests)."""

    kind = "socket"
    relinkable = True

    def __init__(self, sock: socket.socket, nonce: int = 0,
                 write_timeout_s: float = DEFAULT_WRITE_TIMEOUT_S):
        self._sock = sock
        self.nonce = nonce
        self.write_timeout_s = write_timeout_s
        self._rfile = sock.makefile("rb", buffering=0)
        self._reader = FrameReader(self._rfile)
        self._closed = False
        self._rx_shut = False

    def send(self, msg: Dict[str, Any],
             timeout_s: Optional[float] = None) -> None:
        if self._closed:
            raise WireEOF("socket transport already closed")
        send_with_deadline(self._sock, pack_frame(msg),
                           timeout_s if timeout_s is not None
                           else self.write_timeout_s)

    def recv(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        if self._closed:
            raise WireEOF("socket transport already closed")
        if self._rx_shut:
            raise WireTimeout(
                "socket receive direction shut (half-open link): the "
                "reply never arrives")
        return self._reader.read_frame(timeout_s=timeout_s)

    def pending(self) -> Optional[Dict[str, Any]]:
        return self._reader.pending()

    def send_raw(self, data: bytes,
                 timeout_s: Optional[float] = None) -> None:
        """Raw bytes under the write deadline (fault-injection seam —
        see PipeTransport.send_raw)."""
        if self._closed:
            raise WireEOF("socket transport already closed")
        send_with_deadline(self._sock, data,
                           timeout_s if timeout_s is not None
                           else self.write_timeout_s)

    def fileno(self) -> int:
        return self._sock.fileno()

    def shutdown_read(self) -> None:
        """Half-open the link: our receive direction dies, sends still
        flow — the netem "halfopen" fault shape (one direction only).

        The transport-level ``_rx_shut`` flag makes the cut
        deterministic: Linux TCP still delivers data that reached the
        kernel buffer before (or even after) ``SHUT_RD``, so a reply
        racing the shutdown would sometimes be readable and sometimes
        surface EOF.  Marking the receive direction dead here means
        every subsequent ``recv`` is ``WireTimeout``, regardless of
        what the kernel buffered."""
        self._rx_shut = True
        try:
            self._sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for closer in (self._rfile.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass


def client_handshake(sock: socket.socket, incarnation: int, nonce: int,
                     timeout_s: float = DEFAULT_HANDSHAKE_TIMEOUT_S,
                     write_timeout_s: float = DEFAULT_WRITE_TIMEOUT_S,
                     ) -> tuple:
    """Fence a fresh connection: send ``hello`` (incarnation + session
    nonce), await the worker's ``ready``.  Returns ``(transport, ready)``
    with the transport tagged by the adopted nonce.  Raises WireError on
    a refused/garbled handshake — the caller owns retry/evidence."""
    transport = SocketTransport(sock, nonce=nonce,
                                write_timeout_s=write_timeout_s)
    transport.send({"op": "hello", "inc": incarnation, "nonce": nonce},
                   timeout_s=timeout_s)
    ready = transport.recv(timeout_s=timeout_s)
    if (ready.get("op") != "ready" or ready.get("inc") != incarnation
            or ready.get("nonce") != nonce):
        transport.close()
        raise WireEOF(
            f"handshake refused: expected ready(inc={incarnation}, "
            f"nonce={nonce}), got {ready!r}")
    return transport, ready


def connect_transport(host: str, port: int, incarnation: int, nonce: int,
                      timeout_s: float = DEFAULT_HANDSHAKE_TIMEOUT_S,
                      write_timeout_s: float = DEFAULT_WRITE_TIMEOUT_S,
                      ) -> tuple:
    """Dial a listening socket worker and fence the link.  Returns
    ``(transport, ready)``; any socket error propagates as OSError for
    the caller to fold into link evidence."""
    sock = socket.create_connection((host, port), timeout=timeout_s)
    sock.settimeout(None)          # back to blocking; select owns waits
    try:
        return client_handshake(sock, incarnation, nonce,
                                timeout_s=timeout_s,
                                write_timeout_s=write_timeout_s)
    except BaseException:
        try:
            sock.close()
        except OSError:
            pass
        raise
