"""Disaggregated prefill/decode tiers with transactional KV handoff.

The RCA pipeline is prefill-heavy (long Cypher-result and state-audit
prompts) and decode-light (short JSON verdicts), so one homogeneous
fleet leaves whichever phase is off-ratio idle (BENCH_r05's 0.41 sweep
occupancy; ROADMAP item 1 move (b)).  ``TierRouter`` splits the fleet:
a run ADMITS on the prefill tier, and once its prompt is computed its
KV moves to a decode replica as the host-safe page records
``utils/pages.py`` already gathers/restores byte-identically.

The handoff is an explicit two-phase commit over the per-run seam
(serve/backend.py ``export_run``/``adopt_run``, spoken over the proc
wire as the ``export_run``/``adopt_run`` ops):

- **EXPORT** — the prefill side freezes the run through the preemption
  path and gathers its pages into one wire frame; the source sequence
  STAYS pinned (pending queue + spill record) — export is idempotent;
- **ADOPT** — the decode side validates the ENTIRE frame before any
  engine state moves, then re-admits the run under a fresh handle; the
  ack rides the proc protocol's incarnation(+nonce) fence, so a stale
  incarnation can never acknowledge;
- **RELEASE** — only after the ack does the prefill side cancel its
  pinned copy (pages freed through the normal retire path).

Every partial-failure mode therefore resolves deterministically:

- prefill death before ADOPT-ack: the pinned source is gone WITH its
  replica; the health watchdog's ordinary failover re-prefills the run
  on a surviving prefill replica (prefix store makes it mostly-HIT),
  and the transfer retries from there;
- decode death after ADOPT: the run is ordinary in-flight work on the
  decode tier; failover re-starts it on another decode replica;
- torn/corrupt/stale-fenced frame: the adopter discards the transfer
  WHOLE (nothing was registered), the source stays pinned, the router
  counts a retried handoff and tries again — never a half-adopted
  sequence.

Fault surface: ``faults.inject.SITE_HANDOFF`` (drop / corrupt / delay /
stale-fence), polled ONCE per transfer attempt from the router's own
``handoff_plan`` — never from the armed chaos plan, so existing poll
counters stay byte-identical.  ``faults.supervisor.HandoffKiller``
opens its kill window exactly between EXPORT and ADOPT.

Scripted tiers (OracleBackend / proc oracle workers) have no KV: the
handoff degrades to a deterministic re-start on the decode side under
``inject.readmission`` (no armed-plan polls), so the seeded chaos soak
(faults/soak.py ``backend="disagg-cluster"``) stays byte-identical to
the single-tier run.

Exclusions (loud ValueError): empty tiers, overlapping tier ids, mixed
seam/scripted tiers, cp/pp meshes on any tier member (a page record is
ONE engine's pool layout — context/pipeline-sharded KV has no host-safe
per-page image), and cross-tier drain targets.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from k8s_llm_rca_tpu.cluster.replica import Replica
from k8s_llm_rca_tpu.cluster.router import ClusterRouter
from k8s_llm_rca_tpu.cluster.wire import WireError
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.runtime.rules import validate_layout
from k8s_llm_rca_tpu.serve.backend import GenOptions
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

TIER_PREFILL = "prefill"
TIER_DECODE = "decode"


def _worker_error():
    # WorkerError lives in cluster/proc.py, which imports nothing from
    # here; resolved lazily so scripted-only stacks never pay the import
    from k8s_llm_rca_tpu.cluster.proc import WorkerError

    return WorkerError


class TierRouter(ClusterRouter):
    """ClusterRouter over a prefill tier and a decode tier.  See the
    module docstring for the handoff protocol and failure semantics.

    Admission routes to the prefill tier; failover re-starts stay
    within the dead replica's OWN tier (pre-handoff runs belong to
    prefill, post-handoff runs to decode); a whole-tier outage drops
    the tier filter and keeps serving on the survivors (degraded but
    alive — the base router's keep-serving bias).

    ``handoff_plan``: the router's OWN FaultPlan for SITE_HANDOFF frame
    faults.  ``handoff_killer``: a ``faults.supervisor.HandoffKiller``
    whose ``window()`` is opened between EXPORT and ADOPT of every
    transfer attempt.
    """

    def __init__(self, prefill: Sequence[Replica],
                 decode: Sequence[Replica],
                 max_inflight_per_replica: Optional[int] = None,
                 quarantine_after: int = 2,
                 handoff_plan=None, handoff_killer=None):
        prefill, decode = list(prefill), list(decode)
        if not prefill or not decode:
            raise ValueError(
                f"TierRouter needs at least one replica per tier, got "
                f"{len(prefill)} prefill / {len(decode)} decode")
        p_ids = {r.replica_id for r in prefill}
        d_ids = {r.replica_id for r in decode}
        if p_ids & d_ids:
            raise ValueError(
                f"prefill and decode tiers must be disjoint; replicas "
                f"{sorted(p_ids & d_ids)} appear in both")
        for r in prefill + decode:
            axes = tuple(getattr(getattr(r, "mesh", None),
                                 "axis_names", ()) or ())
            bad = [a for a in axes if a in ("cp", "pp")]
            if bad:
                raise ValueError(
                    f"TierRouter refuses replica {r.replica_id} with "
                    f"mesh axes {axes}: a handoff page record is ONE "
                    f"engine's pool layout, and {bad[0]!r}-sharded KV "
                    f"has no host-safe per-page image to move between "
                    f"tiers — use dp/tp-only replica meshes")
        seam = [hasattr(r.backend, "export_run") for r in prefill + decode]
        if any(seam) and not all(seam):
            mixed = sorted(r.replica_id for r, s in
                           zip(prefill + decode, seam) if not s)
            raise ValueError(
                f"TierRouter needs every tier member on the same handoff "
                f"seam: replicas {mixed} are scripted (no export_run/"
                f"adopt_run) while others are engine-backed — a KV frame "
                f"one side produces, the other cannot adopt")
        self._kv_seam = all(seam)
        # per-tier weight-layout pre-flight: engine replicas stamped with
        # kv_layout/layout metadata (cluster/replica.build_replicas) must
        # be handoff-compatible across tiers and sit on disjoint devices;
        # scripted replicas (no metadata) skip both checks.
        self._kv_ref: Optional[Tuple[int, Dict[str, Any]]] = None
        members = prefill + decode
        for r in members:
            self._check_kv_member(r)
            self._check_layout_member(r, members)
        super().__init__(prefill + decode,
                         max_inflight_per_replica=max_inflight_per_replica,
                         quarantine_after=quarantine_after)
        self.tier: Dict[int, str] = {}
        for r in prefill:
            self.tier[r.replica_id] = TIER_PREFILL
        for r in decode:
            self.tier[r.replica_id] = TIER_DECODE
        self.prefill_ids = sorted(p_ids)
        self.decode_ids = sorted(d_ids)
        self.handoff_plan = handoff_plan
        self.handoff_killer = handoff_killer
        if handoff_killer is not None and handoff_killer.router is None:
            handoff_killer.router = self
        self.handoffs = 0                    # committed (RELEASEd)
        self.handoffs_retried = 0            # attempts discarded whole
        # ghandle -> retry count; every admitted run enters at 0 and
        # leaves at RELEASE (or when it settles/fails over onto decode)
        self._handoff_queue: Dict[int, int] = {}
        # failover tier context: _pick routes new admissions to prefill
        # (None) and failover re-starts to the dead replica's own tier
        self._route_tier: Optional[str] = None

    # -------------------------------------------------------------- routing

    def _pick(self, session: str, admit: bool = True, priority: int = 1,
              among: Optional[List[int]] = None) -> int:
        if among is None:
            among = (self.decode_ids
                     if self._route_tier == TIER_DECODE
                     else self.prefill_ids)
        return super()._pick(session, admit=admit, priority=priority,
                             among=among)

    def start(self, prompt: str, opts: GenOptions) -> int:
        ghandle = super().start(prompt, opts)
        self._handoff_queue[ghandle] = 0
        return ghandle

    def cancel(self, handle: int) -> None:
        self._handoff_queue.pop(handle, None)
        super().cancel(handle)

    # ------------------------------------------------------------- failover

    def fail_replica(self, rid: int) -> List[int]:
        prev = self._route_tier
        self._route_tier = self.tier.get(rid)
        try:
            return super().fail_replica(rid)
        finally:
            self._route_tier = prev

    def _restart_in_place(self, rid: int) -> None:
        prev = self._route_tier
        self._route_tier = self.tier.get(rid)
        try:
            super()._restart_in_place(rid)
        finally:
            self._route_tier = prev

    def drain_replica(self, rid: int,
                      target: Optional[int] = None) -> List[int]:
        tier = self.tier.get(rid)
        peers = [r for r in self.alive_ids()
                 if r != rid and self.tier.get(r) == tier]
        if target is None:
            if not peers:
                raise ValueError(
                    f"refusing to drain replica {rid}: no surviving "
                    f"{tier} peer, and a cross-tier drain would move "
                    f"sequences into the wrong tier (kill it instead — "
                    f"fail_replica keeps tier placement via the "
                    f"failover path)")
            target = min(peers,
                         key=lambda r: (self.replicas[r].queue_depth(),
                                        r))
        elif self.tier.get(target) != tier:
            raise ValueError(
                f"drain target {target} ({self.tier.get(target)} tier) "
                f"must sit in replica {rid}'s own tier ({tier}): a "
                f"cross-tier drain would move sequences into the wrong "
                f"tier")
        prev = self._route_tier
        self._route_tier = tier
        try:
            return super().drain_replica(rid, target=target)
        finally:
            self._route_tier = prev

    # ----------------------------------------------------- fleet membership

    def _check_kv_member(self, replica: Replica) -> None:
        """Cross-tier KV-record compatibility: the FIRST replica carrying
        ``kv_layout`` metadata becomes the fleet reference; every later
        one must match it on kv_dtype / kv_dim / n_layers (frame geometry
        a page record cannot cross) and on cache kind.  ``page_size`` MAY
        differ between tiers — the adopting paged engine re-chunks the
        record deterministically (engine/paged.py ``adopt_run``,
        ``engine.handoff_kv_relayout``).  Replicas without the metadata
        (scripted echo/oracle tiers) skip."""
        kv = getattr(replica, "kv_layout", None)
        if kv is None:
            return
        if self._kv_ref is None:
            self._kv_ref = (replica.replica_id, dict(kv))
            return
        ref_rid, ref = self._kv_ref
        for field in ("kv_dtype", "kv_dim", "n_layers"):
            if kv.get(field) != ref.get(field):
                raise ValueError(
                    f"TierRouter refuses replica {replica.replica_id}: its "
                    f"KV layout {field}={kv.get(field)!r} does not match "
                    f"replica {ref_rid}'s {field}={ref.get(field)!r} — a "
                    f"handoff frame crossing this pair can neither be "
                    f"adopted nor deterministically converted (page_size "
                    f"may differ between tiers; dtype/width/depth may not)")
        mode = "paged" if kv.get("page_size") is not None else "contiguous"
        ref_mode = ("paged" if ref.get("page_size") is not None
                    else "contiguous")
        if mode != ref_mode:
            raise ValueError(
                f"TierRouter refuses replica {replica.replica_id}: its "
                f"{mode} KV cache cannot hand off against replica "
                f"{ref_rid}'s {ref_mode} one — both tiers must run the "
                f"same cache kind (only page_size may differ)")

    @staticmethod
    def _check_layout_member(replica: Replica, others) -> None:
        """Layout pre-flight for a tier member that carries a SpecLayout
        and a real mesh: re-run ``runtime.rules.validate_layout`` with
        every OTHER member's mesh as a peer, so per-tier submeshes that
        overlap on a device are a named ValueError at construction."""
        layout = getattr(replica, "layout", None)
        mesh = getattr(replica, "mesh", None)
        if layout is None or not hasattr(getattr(mesh, "devices", None),
                                         "flat"):
            return
        peers = [m for m in (getattr(o, "mesh", None) for o in others
                             if o is not replica)
                 if hasattr(getattr(m, "devices", None), "flat")]
        validate_layout(layout, mesh, peers=peers)

    def _check_tier_member(self, replica: Replica) -> None:
        """The __init__ member exclusions, applied to a late admission:
        no cp/pp mesh axes, the newcomer must sit on the SAME handoff
        seam as the incumbent fleet, its KV record geometry must be
        adoptable, and its (layout, mesh) must pass pre-flight against
        the incumbents' meshes."""
        axes = tuple(getattr(getattr(replica, "mesh", None),
                             "axis_names", ()) or ())
        bad = [a for a in axes if a in ("cp", "pp")]
        if bad:
            raise ValueError(
                f"TierRouter refuses replica {replica.replica_id} with "
                f"mesh axes {axes}: {bad[0]!r}-sharded KV has no "
                f"host-safe per-page image to move between tiers")
        seam = hasattr(replica.backend, "export_run")
        if seam != self._kv_seam:
            kind = "scripted" if not seam else "engine-backed"
            fleet = "engine-backed" if self._kv_seam else "scripted"
            raise ValueError(
                f"TierRouter refuses replica {replica.replica_id}: it is "
                f"{kind} while the fleet is {fleet} — every tier member "
                f"must sit on the same handoff seam")
        self._check_kv_member(replica)
        self._check_layout_member(replica, list(self.replicas.values()))

    def add_replica(self, replica: Replica,
                    tier: Optional[str] = None) -> None:
        """Tiered admission (the elastic scale-up seam): the newcomer
        must name its tier, pass the same member exclusions as
        ``__init__``, and lands in the sorted tier id lists."""
        if tier not in (TIER_PREFILL, TIER_DECODE):
            raise ValueError(
                f"add_replica on a TierRouter needs tier="
                f"{TIER_PREFILL!r} or {TIER_DECODE!r}, got {tier!r}")
        self._check_tier_member(replica)
        self._admit_replica(replica)
        self.tier[replica.replica_id] = tier
        self._rebuild_tier_ids()

    def remove_replica(self, rid: int) -> Replica:
        """Tiered retirement: refuses to empty a tier (the __init__
        invariant — a TierRouter without a prefill or decode tier
        cannot serve)."""
        tier = self.tier.get(rid)
        if tier is not None:
            peers = [r for r in self.replicas
                     if r != rid and self.tier.get(r) == tier]
            if not peers:
                raise ValueError(
                    f"refusing to remove replica {rid}: it is the last "
                    f"{tier} tier member (an empty tier cannot serve — "
                    f"add or reassign a peer first)")
        replica = super().remove_replica(rid)
        self.tier.pop(rid, None)
        self._rebuild_tier_ids()
        return replica

    def reassign_tier(self, rid: int, tier: str) -> None:
        """Move ``rid`` to the other tier in place (the rebalance seam,
        cluster/autoscale.py): the worker never dies, its warm engine
        state rides along.  Refuses while the replica still owns
        in-flight runs — pre-handoff sequences would silently change
        phase — and when leaving would empty its current tier."""
        if tier not in (TIER_PREFILL, TIER_DECODE):
            raise ValueError(
                f"reassign_tier needs tier={TIER_PREFILL!r} or "
                f"{TIER_DECODE!r}, got {tier!r}")
        cur = self.tier.get(rid)
        if cur is None:
            raise ValueError(
                f"replica {rid} is not in the fleet "
                f"(ids: {sorted(self.replicas)})")
        if cur == tier:
            raise ValueError(
                f"replica {rid} already sits in the {tier} tier")
        orphans = self._orphans(rid)
        if orphans:
            raise ValueError(
                f"refusing to reassign replica {rid} to the {tier} "
                f"tier: it still owns {len(orphans)} in-flight run(s) "
                f"whose phase would silently change — drain it first")
        peers = [r for r in self.replicas
                 if r != rid and self.tier.get(r) == cur]
        if not peers:
            raise ValueError(
                f"refusing to reassign replica {rid}: it is the last "
                f"{cur} tier member (an empty tier cannot serve)")
        self.tier[rid] = tier
        self._rebuild_tier_ids()
        log.info("replica %d reassigned %s -> %s tier", rid, cur, tier)

    def _rebuild_tier_ids(self) -> None:
        self.prefill_ids = sorted(
            r for r, t in self.tier.items() if t == TIER_PREFILL)
        self.decode_ids = sorted(
            r for r, t in self.tier.items() if t == TIER_DECODE)

    # -------------------------------------------------------------- handoff

    @staticmethod
    def _dead_proc(replica: Replica) -> bool:
        liveness = getattr(replica, "proc_liveness", None)
        return liveness is not None and liveness() is not None

    @staticmethod
    def _down_link(replica: Replica) -> bool:
        link = getattr(replica, "link_liveness", None)
        return link is not None and link() is not None

    def _serving(self, rid: int) -> bool:
        r = self.replicas[rid]
        return (r.healthy() and not self._dead_proc(r)
                and not self._down_link(r))

    def pump(self):
        self._advance_handoffs()
        return super().pump()

    def _advance_handoffs(self) -> None:
        """One transfer attempt per queued run per pump.  Runs that
        settled, were cancelled, or already live on the decode tier
        (whole-prefill-tier failover fallback) self-clean here."""
        if not self._handoff_queue:
            return
        for ghandle in sorted(self._handoff_queue):
            loc = self._handle_map.get(ghandle)
            if loc is None:
                del self._handoff_queue[ghandle]       # settled/cancelled
                continue
            src_rid, src_lh = loc
            if self.tier.get(src_rid) == TIER_DECODE:
                del self._handoff_queue[ghandle]       # already there
                continue
            if not self._serving(src_rid):
                continue       # the heal path owns this replica first
            dst = [rid for rid in self.decode_ids if self._serving(rid)]
            if not dst:
                return         # decode tier down: runs settle on prefill
            dst_rid = min(dst, key=lambda r:
                          (self.replicas[r].queue_depth(), r))
            self._attempt_handoff(ghandle, src_rid, src_lh, dst_rid)

    def _attempt_handoff(self, ghandle: int, src_rid: int, src_lh: int,
                         dst_rid: int) -> None:
        src = self.replicas[src_rid]
        dst = self.replicas[dst_rid]
        prompt, opts = self._runs[ghandle]
        wire_errors = (WireError, OSError, _worker_error())
        fault = None
        if self.handoff_plan is not None:
            fault = self.handoff_plan.poll(inject.SITE_HANDOFF)
        if fault is not None and fault.kind == "delay":
            # virtual transfer latency on the handoff plan's OWN clock
            # (never the soak clock — byte-identity)
            self.handoff_plan.clock.sleep(fault.delay_s or 0.05)
            fault = None
        elif fault is not None and fault.kind not in (
                "drop", "corrupt", "stale-fence"):
            log.warning("handoff fault %r ignored: frame kinds are "
                        "drop/corrupt/delay/stale-fence (kill kinds "
                        "belong on a HandoffKiller plan)", fault.kind)
            fault = None
        # ---- EXPORT: freeze on the prefill side, source stays pinned
        # (phase spans feed the critical-path pass, obs/critical_path.py:
        # zero duration under a VirtualClock, real wire time otherwise)
        with obs_trace.span("cluster.handoff.export", cat="handoff",
                            run=ghandle, src=src_rid, dst=dst_rid):
            if self._kv_seam:
                try:
                    frame = src.backend.export_run(src_lh)
                except wire_errors as e:
                    self._retry(ghandle, "export",
                                f"{type(e).__name__}: {e}")
                    return
                if frame is None:
                    return     # not exportable THIS pump — not a retry
            else:
                # scripted tiers carry no KV: a synthetic frame keeps
                # the 2PC (and its fault/kill surface) identical
                frame = {"seq": {"scripted": True, "run": ghandle},
                         "kv": None}
        if fault is not None and fault.kind == "drop":
            self._retry(ghandle, "export", "injected frame drop")
            return
        if fault is not None and fault.kind == "corrupt":
            frame = self._corrupt_frame(frame)
        # ---- the kill window: a HandoffKiller death lands exactly here,
        # between EXPORT and ADOPT, with the frame in flight
        if self.handoff_killer is not None:
            self.handoff_killer.window(self, ghandle, src_rid, dst_rid)
            loc = self._handle_map.get(ghandle)
            if loc != (src_rid, src_lh) or not self._serving(src_rid):
                # source died (or its runs were already failed over)
                # mid-window: the pinned copy is authoritative and rides
                # ordinary failover back onto the prefill tier — this
                # attempt is discarded whole
                self._retry(ghandle, "window",
                            "prefill side died before ADOPT-ack")
                return
            if not self._serving(dst_rid):
                self._retry(ghandle, "window",
                            "decode side died before ADOPT")
                return
        # ---- ADOPT: all-or-nothing on the decode side
        with obs_trace.span("cluster.handoff.adopt", cat="handoff",
                            run=ghandle, src=src_rid, dst=dst_rid):
            if self._kv_seam:
                try:
                    new_lh = dst.backend.adopt_run(frame, opts)
                except wire_errors as e:
                    # the ack never arrived; the adopter MAY hold a
                    # twin, but the incarnation(+nonce) fence discards
                    # any late reply and an orphan twin's result is
                    # dropped by the parent mirror (proc.py pump) —
                    # retry from the source
                    self._retry(ghandle, "adopt",
                                f"ack lost ({type(e).__name__}): {e}")
                    return
                except ValueError as e:
                    # torn frame: discarded whole before any engine
                    # state moved on the adopter
                    self._retry(ghandle, "adopt", f"torn frame: {e}")
                    return
            else:
                try:
                    self._scripted_frame_check(frame)
                except ValueError as e:
                    self._retry(ghandle, "adopt", f"torn frame: {e}")
                    return
                # deterministic re-start stands in for ADOPT: a
                # re-admission of an already-admitted run (no
                # armed-plan polls)
                with inject.readmission():
                    new_lh = dst.backend.start(prompt, opts)
        if fault is not None and fault.kind == "stale-fence":
            # the ack lost the fencing race (a newer incarnation/nonce
            # took over mid-transfer): the adopted twin must die, the
            # transfer retries whole
            try:
                dst.backend.cancel(new_lh)
            except (WireError, OSError):
                pass
            self._retry(ghandle, "fence", "stale-fenced ADOPT-ack "
                        "discarded; adopted twin cancelled")
            return
        # ---- RELEASE: the adopter acked — free the pinned source copy
        with obs_trace.span("cluster.handoff.release", cat="handoff",
                            run=ghandle, src=src_rid, dst=dst_rid):
            self._local.pop((src_rid, src_lh), None)
            try:
                src.backend.cancel(src_lh)
            except (WireError, OSError):
                pass           # dying source: its state is gone anyway
            self._handle_map[ghandle] = (dst_rid, new_lh)
            self._local[(dst_rid, new_lh)] = ghandle
            retries = self._handoff_queue.pop(ghandle, 0)
            self.handoffs += 1
            METRICS.inc("cluster.handoffs")
            obs_trace.event("cluster.handoff", run=ghandle, src=src_rid,
                            dst=dst_rid, retries=retries,
                            kv=bool(frame.get("kv")))

    def _retry(self, ghandle: int, stage: str, why: str) -> None:
        """Record one discarded transfer attempt; the run stays whole
        wherever it lives and the queue retries next pump."""
        self._handoff_queue[ghandle] = (
            self._handoff_queue.get(ghandle, 0) + 1)
        self.handoffs_retried += 1
        METRICS.inc("cluster.handoff_retries")
        obs_trace.event("cluster.handoff", run=ghandle, stage=stage,
                        retried=True, reason=why)
        log.warning("handoff of run %d discarded whole at %s: %s "
                    "(attempt %d)", ghandle, stage, why,
                    self._handoff_queue[ghandle])

    def _corrupt_frame(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Deterministically tear a frame in flight: the adopter must
        reject it whole (CRC for kv frames, entry validation for
        entry-only and scripted frames)."""
        frame = dict(frame)
        if not self._kv_seam:
            frame["torn"] = True
            return frame
        kv = frame.get("kv")
        if kv:
            kv = dict(kv)
            b64 = kv["b64"]
            # flip the first base64 symbol: still valid base64, but the
            # decoded bytes fail the frame CRC deterministically
            kv["b64"] = ("B" if b64[:1] == "A" else "A") + b64[1:]
            frame["kv"] = kv
        else:
            frame["seq"] = {"torn": True}
        return frame

    @staticmethod
    def _scripted_frame_check(frame: Dict[str, Any]) -> None:
        entry = frame.get("seq")
        if (frame.get("torn") or not isinstance(entry, dict)
                or not entry.get("scripted")):
            raise ValueError("torn handoff frame: malformed scripted "
                             "sequence entry")

    # ------------------------------------------------------------ reporting

    def tier_stats(self) -> Dict[str, Any]:
        """Handoff counters for bench/obs (measured, never derived)."""
        return {"prefill_replicas": len(self.prefill_ids),
                "decode_replicas": len(self.decode_ids),
                "handoffs": self.handoffs,
                "handoffs_retried": self.handoffs_retried,
                "pending_handoffs": len(self._handoff_queue)}
