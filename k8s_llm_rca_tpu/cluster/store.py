"""Fleet-scale cache fabric: a cross-host PrefixStore service.

PR 10's tiered prefix cache made the RCA sweep's shared metagraph/
stategraph preambles nearly free — but only within one process.  Once the
fleet went multi-process (PR 12), cross-host (PR 13) and disaggregated
(PR 14), every crash-restart, drain migration and prefill-death fallback
re-prefilled from scratch: the store each engine demoted into died with
the engine.  This module moves the store out of the engine process:

- ``StoreServer`` — parent-side handle for a store worker subprocess
  serving ``put``/``get``/``probe``/``stats`` over the CRC-framed wire
  codec (cluster/wire.py), over the PR 12 stdio pipes or a PR 13 TCP
  socket that any number of engine workers dial concurrently.
- ``RemoteStore`` — a client presenting the exact ``PrefixStore``
  surface (``contains``/``put``/``get``/``n_host``/``n_disk``), so
  ``build_replicas``, ``build_proc_replicas``, supervisor ``rebuild()``
  and ``TierRouter`` plug it in unchanged.
- ``StoreFabric`` — the soak-facing bundle (server + client + exercise
  bookkeeping) that faults/soak.py attaches to a chaos run.

The one wire/disk format
    The payload of every store op is the page-record frame produced by
    ``utils/pages.py:encode_page_record`` — byte-for-byte the content of
    a ``PrefixStore`` L2 ``<hex>.page`` file (engine/prefix.py:_to_disk)
    and a legal ``utils/wal.py`` record, because all three layers share
    ``wal.HEADER``/``wal.MAX_RECORD_SIZE``.  A record written by L2 disk
    is servable verbatim over the wire; the server persists exactly the
    bytes it was shipped and never decodes them (it runs without JAX or
    numpy — pages are opaque checksummed blobs to it).

The failure contract — the third tier of the tree's three
    The WAL *recovers* a clean prefix (torn tails are normal); the wire
    *raises* (a torn frame means the peer is gone).  A shared cache is
    neither: it is an optimization, so every failure mode here — torn or
    corrupt frame, ``WireTimeout``, dead server, version-mismatched
    record, fault-plan drop/partition — degrades to a *silent cold miss
    plus a counted metric* (``engine.prefix_store_misses_remote``),
    never an engine error.  A dead store turns the fleet local-only; it
    cannot become a new single point of failure.

Faultability
    ``RemoteStore`` polls its OWN seeded plan once per store op at
    ``inject.SITE_STORE`` (kinds drop/corrupt/delay/partition/heal),
    mirroring the netem link discipline; ``faults/supervisor.py``'s
    ``StoreKiller`` SIGKILLs and heals the server process between
    incidents.  Both compose with the existing killers because
    SITE_STORE is a new, disjoint site.

The reference's cache story is an in-process ``functools.lru_cache`` on
the metagraph loader (graph_loader.py:41-44 in /root/reference); it has
no notion of cross-process reuse, which is exactly the gap the paper's
100-incident sweep makes expensive.
"""

from __future__ import annotations

import base64
import binascii
import json
import os
import select
import signal
import socket
import subprocess
import sys
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from k8s_llm_rca_tpu.cluster.wire import (
    FrameReader, WireEOF, WireError, WireTimeout, pack_frame, write_frame,
)
from k8s_llm_rca_tpu.utils import wal
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

STORE_TRANSPORTS = ("pipe", "socket")

# the store worker answers from RAM/disk with no model in the loop, so
# RPCs are fast; a short deadline keeps a wedged server from stalling an
# engine tick for longer than a cold prefill would have cost anyway
DEFAULT_STORE_RPC_TIMEOUT_S = 5.0
DEFAULT_STORE_SPAWN_TIMEOUT_S = 60.0

_LEASH_CHUNK = 4096


def _store_env() -> Dict[str, str]:
    """Spawn environment for the store worker.  Replaces PYTHONPATH with
    the repo root (the axon sitecustomize on the parent's path would
    force the tunnel platform inside the worker — CLAUDE.md host rule)
    and pins JAX_PLATFORMS defensively even though the store worker
    never imports jax: pages are opaque bytes to it."""
    import k8s_llm_rca_tpu

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(k8s_llm_rca_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _valid_frame(data: bytes) -> bool:
    """True iff ``data`` is exactly one well-formed CRC frame — the same
    check ``decode_page_record`` starts with, minus the numpy decode the
    server cannot (and need not) perform."""
    for _payload, end in wal.iter_records(data):
        return end == len(data)
    return False


# ---------------------------------------------------------------------------
# worker side (no jax, no numpy: pages are opaque checksummed blobs)
# ---------------------------------------------------------------------------


class _FrameStore:
    """The server's two-tier byte store: L1 host RAM (OrderedDict, LRU),
    L2 disk (``<hex>.page`` files, the PrefixStore on-disk format and
    atomic temp+fsync+``os.replace`` recipe — engine/prefix.py:207-226),
    both capped by entry count.  Mirrors ``PrefixStore`` semantics
    exactly so local and remote tiers are interchangeable: L1-first
    insert, LRU overflow demotes to disk, corrupt disk entries are
    dropped on read (cold miss, never an error)."""

    def __init__(self, host_pages: int = 0, disk_dir: Optional[str] = None,
                 disk_pages: int = 0):
        if host_pages < 0 or disk_pages < 0:
            raise ValueError("store tier capacities must be >= 0, got "
                             f"host_pages={host_pages} disk_pages={disk_pages}")
        if disk_pages > 0 and disk_dir is None:
            raise ValueError("disk_pages > 0 requires disk_dir")
        self.host_pages = int(host_pages)
        self.disk_dir = disk_dir
        self.disk_pages = int(disk_pages)
        self._l1: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._l2: "OrderedDict[bytes, str]" = OrderedDict()
        if disk_dir is not None:
            os.makedirs(disk_dir, exist_ok=True)
            # re-index what a previous incarnation persisted: sorted for
            # determinism (the LRU order of a dead process is gone)
            for name in sorted(os.listdir(disk_dir)):
                if not name.endswith(".page"):
                    continue
                try:
                    key = bytes.fromhex(name[:-5])
                except ValueError:
                    continue
                self._l2[key] = os.path.join(disk_dir, name)

    def _path(self, key: bytes) -> str:
        return os.path.join(self.disk_dir, key.hex() + ".page")

    def _to_disk(self, key: bytes, frame: bytes) -> None:
        if self.disk_pages <= 0:
            return                      # no disk tier: LRU overflow drops
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._l2[key] = path
        self._l2.move_to_end(key)
        while len(self._l2) > self.disk_pages:
            old, old_path = self._l2.popitem(last=False)
            try:
                os.unlink(old_path)
            except OSError:
                pass

    def put(self, key: bytes, frame: bytes) -> bool:
        """Store one validated frame; returns False when the frame fails
        the CRC check or every tier is full-and-capped to zero."""
        if not _valid_frame(frame):
            return False
        if self.host_pages <= 0 and self.disk_pages <= 0:
            return False
        if key in self._l1:
            self._l1.move_to_end(key)
            return True
        self._l1[key] = frame
        while len(self._l1) > max(0, self.host_pages):
            old, old_frame = self._l1.popitem(last=False)
            self._to_disk(old, old_frame)
        return True

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        frame = self._l1.get(key)
        if frame is not None:
            self._l1.move_to_end(key)
            return frame, 1
        path = self._l2.get(key)
        if path is not None:
            self._l2.move_to_end(key)
            try:
                with open(path, "rb") as f:
                    frame = f.read()
            except OSError:
                frame = None
            if frame is not None and _valid_frame(frame):
                return frame, 2
            # corrupt/torn disk entry: drop it — cold miss, never an error
            self._l2.pop(key, None)
            try:
                os.unlink(path)
            except OSError:
                pass
        return None

    def contains(self, key: bytes) -> bool:
        return key in self._l1 or key in self._l2

    @property
    def n_host(self) -> int:
        return len(self._l1)

    @property
    def n_disk(self) -> int:
        return len(self._l2)


def _handle_store_op(msg: Dict[str, Any], store: _FrameStore,
                     stats: Dict[str, float],
                     inc: int) -> Tuple[Dict[str, Any], bool]:
    """One decoded request -> ``(reply, drain)`` — shared by the pipe
    loop and the socket loop so both transports speak the identical op
    surface.  Malformed requests get ``ok: False`` replies (which the
    client degrades to a cold miss); they never kill the server."""
    op = msg.get("op")
    reply: Dict[str, Any] = {"id": msg.get("id"), "inc": inc, "ok": True}
    if op == "drain":
        reply["drain"] = True
        return reply, True
    if op == "stats":
        reply["stats"] = dict(stats, n_host=store.n_host,
                              n_disk=store.n_disk, pid=os.getpid())
        return reply, False
    try:
        key = bytes.fromhex(msg["key"])
    except (KeyError, TypeError, ValueError):
        return {"id": msg.get("id"), "inc": inc, "ok": False,
                "err": "bad key"}, False
    if op == "put":
        try:
            frame = base64.b64decode(msg["page"], validate=True)
        except (KeyError, TypeError, binascii.Error):
            stats["rejected"] += 1
            return {"id": msg.get("id"), "inc": inc, "ok": False,
                    "err": "bad page"}, False
        stats["puts"] += 1
        if store.put(key, frame):
            return reply, False
        stats["rejected"] += 1
        return {"id": msg.get("id"), "inc": inc, "ok": False,
                "err": "rejected"}, False
    if op == "get":
        stats["gets"] += 1
        hit = store.get(key)
        if hit is None:
            stats["misses"] += 1
            reply["hit"] = False
        else:
            frame, tier = hit
            stats[f"hits_l{tier}"] += 1
            reply["hit"] = True
            reply["tier"] = tier
            reply["page"] = base64.b64encode(frame).decode("ascii")
        return reply, False
    if op == "probe":
        reply["hit"] = store.contains(key)
        return reply, False
    return {"id": msg.get("id"), "inc": inc, "ok": False,
            "err": f"unknown op {op!r}"}, False


def _fresh_stats() -> Dict[str, float]:
    return {"puts": 0.0, "gets": 0.0, "hits_l1": 0.0, "hits_l2": 0.0,
            "misses": 0.0, "rejected": 0.0}


def _serve_store_pipe(out, store: _FrameStore, inc: int) -> int:
    """Stdio-pipe mode: ready frame, then one reply per request until
    drain or stdin EOF (the store never outlives its parent)."""
    write_frame(out, {"op": "ready", "id": -1, "inc": inc,
                      "pid": os.getpid()})
    stats = _fresh_stats()
    reader = FrameReader(sys.stdin.buffer)
    while True:
        try:
            msg = reader.read_frame()
        except WireEOF:
            return 0
        reply, drain = _handle_store_op(msg, store, stats, inc)
        write_frame(out, reply)
        if drain:
            return 0


def _serve_store_listen(spec: Dict[str, Any], out, store: _FrameStore,
                        inc: int) -> int:
    """``--listen`` socket mode: announce the port in a ``listening``
    bootstrap frame on stdout, then serve ANY number of concurrent
    client links — unlike the proc worker's single fenced link, store
    ops are content-addressed and idempotent, so there is no split-brain
    to fence against and every engine in the fleet may dial in.  stdin
    is the lifetime leash (proc.py:_serve_listen discipline): EOF there
    means the parent is gone and the store exits 0."""
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind((spec.get("listen_host", "127.0.0.1"),
                   int(spec.get("listen_port", 0))))
    listener.listen(16)
    port = listener.getsockname()[1]
    write_frame(out, {"op": "listening", "id": -1, "inc": inc,
                      "pid": os.getpid(), "port": port})
    stats = _fresh_stats()
    leash = sys.stdin.buffer
    conns: Dict[socket.socket, FrameReader] = {}
    try:
        while True:
            rlist = [leash, listener] + list(conns)
            readable, _, _ = select.select(rlist, [], [])
            if leash in readable:
                if not os.read(leash.fileno(), _LEASH_CHUNK):
                    return 0          # parent went away
            if listener in readable:
                fresh, _addr = listener.accept()
                conns[fresh] = FrameReader(fresh.makefile("rb", buffering=0))
            for conn in [c for c in readable
                         if isinstance(c, socket.socket) and c in conns]:
                reader = conns[conn]
                first = True
                while True:
                    try:
                        if first:
                            # short deadline: a partial frame parks until
                            # the rest of its bytes arrive (the reader
                            # buffers what it got)
                            msg = reader.read_frame(timeout_s=0.05)
                            first = False
                        else:
                            # drain every complete frame this wakeup
                            # delivered without touching the stream again
                            msg = reader.pending()
                            if msg is None:
                                break
                    except WireTimeout:
                        break
                    except (WireError, OSError):
                        conns.pop(conn, None)
                        conn.close()
                        break
                    reply, drain = _handle_store_op(msg, store, stats, inc)
                    try:
                        conn.sendall(pack_frame(reply))
                    except OSError:
                        conns.pop(conn, None)
                        conn.close()
                        break
                    if drain:
                        return 0
    finally:
        for conn in conns:
            conn.close()
        listener.close()


def store_main(argv) -> int:
    """Store worker entry (``python -m k8s_llm_rca_tpu.cluster.store``).
    Claims the real stdout fd for frames first and repoints
    ``sys.stdout`` at stderr (proc.py:worker_main discipline), so a
    stray print garbles a log line instead of a frame."""
    out = sys.stdout.buffer
    sys.stdout = sys.stderr
    args = list(argv)
    mode = "pipe"
    if args and args[0] == "--listen":
        mode = "listen"
        args = args[1:]
    if len(args) != 1:
        raise SystemExit("usage: python -m k8s_llm_rca_tpu.cluster.store "
                         "[--listen] '<spec-json>'")
    spec = json.loads(args[0])
    inc = int(spec.get("incarnation", 0))
    store = _FrameStore(host_pages=int(spec.get("host_pages", 0)),
                        disk_dir=spec.get("disk_dir"),
                        disk_pages=int(spec.get("disk_pages", 0)))
    if mode == "listen":
        return _serve_store_listen(spec, out, store, inc)
    return _serve_store_pipe(out, store, inc)


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------


class StoreServer:
    """Parent-side handle for one store worker process.

    Spawns the worker, waits for its bootstrap frame, and exposes a
    synchronous ``rpc`` (raising ``WireError``/``OSError`` on any
    transport failure — the RemoteStore above it is what degrades those
    to cold misses).  ``kill``/``respawn`` are the ``StoreKiller``'s
    levers: SIGKILL loses L1 (host RAM) but a respawned incarnation
    re-indexes the surviving L2 ``.page`` files from ``disk_dir``, so a
    healed store is disk-warm — the same asymmetry a real host reboot
    has."""

    def __init__(self, host_pages: int = 64, disk_dir: Optional[str] = None,
                 disk_pages: int = 0, transport: str = "pipe",
                 listen_host: str = "127.0.0.1",
                 spawn_timeout_s: float = DEFAULT_STORE_SPAWN_TIMEOUT_S,
                 rpc_timeout_s: float = DEFAULT_STORE_RPC_TIMEOUT_S):
        if transport not in STORE_TRANSPORTS:
            raise ValueError(f"unknown store transport {transport!r}: "
                             f"expected one of {STORE_TRANSPORTS}")
        if host_pages < 0 or disk_pages < 0:
            raise ValueError("store tier capacities must be >= 0, got "
                             f"host_pages={host_pages} "
                             f"disk_pages={disk_pages}")
        if disk_pages > 0 and disk_dir is None:
            raise ValueError("disk_pages > 0 requires disk_dir")
        if host_pages == 0 and disk_pages == 0:
            raise ValueError("a store with zero host AND disk capacity "
                             "can never serve a hit; give it at least "
                             "one tier")
        self.host_pages = int(host_pages)
        self.disk_dir = disk_dir
        self.disk_pages = int(disk_pages)
        self.transport = transport
        self.listen_host = listen_host
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.incarnation = 0
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[FrameReader] = None
        self._sock: Optional[socket.socket] = None
        self._sock_reader: Optional[FrameReader] = None
        self._next_id = 0
        self._spawn()

    # ------------------------------------------------------------ spawn

    def _spawn(self) -> None:
        spec: Dict[str, Any] = {"host_pages": self.host_pages,
                                "disk_dir": self.disk_dir,
                                "disk_pages": self.disk_pages,
                                "incarnation": self.incarnation}
        argv = [sys.executable, "-m", "k8s_llm_rca_tpu.cluster.store"]
        if self.transport == "socket":
            spec["listen_host"] = self.listen_host
            if self.port is not None:
                # a healed store keeps its address so addr-mode clients
                # (engine workers holding only host:port) recover too
                spec["listen_port"] = self.port
            argv.append("--listen")
        argv.append(json.dumps(spec, sort_keys=True))
        self._proc = subprocess.Popen(argv, stdin=subprocess.PIPE,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.DEVNULL,
                                      env=_store_env())
        self._reader = FrameReader(self._proc.stdout)
        try:
            boot = self._reader.read_frame(timeout_s=self.spawn_timeout_s)
        except WireError:
            if (self.transport == "socket"
                    and spec.get("listen_port") is not None):
                # the old port was taken while the store was dead: give
                # up on address stability rather than on the heal
                self._reap()
                self.port = None
                return self._spawn()
            self._reap()
            raise
        self.pid = int(boot.get("pid", -1))
        if self.transport == "socket":
            self.port = int(boot["port"])
        METRICS.inc("cluster.store_spawns")
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        obs_trace.event("cluster.store.serve", pid=self.pid,
                        inc=self.incarnation, transport=self.transport,
                        port=self.port if self.port is not None else -1)

    @property
    def addr(self) -> Tuple[str, int]:
        if self.transport != "socket" or self.port is None:
            raise ValueError("addr is only meaningful for a socket-"
                             "transport store server")
        return (self.listen_host, self.port)

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    # -------------------------------------------------------------- rpc

    def _socket_link(self) -> Tuple[socket.socket, FrameReader]:
        if self._sock is None:
            sock = socket.create_connection(self.addr, timeout=2.0)
            sock.settimeout(None)
            self._sock = sock
            self._sock_reader = FrameReader(sock.makefile("rb", buffering=0))
        return self._sock, self._sock_reader

    def _drop_socket_link(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._sock_reader = None

    def rpc(self, msg: Dict[str, Any],
            timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """One request/reply over whichever transport the server runs.
        Raises ``WireError``/``OSError`` on ANY failure; callers that
        want the cold-miss contract go through ``RemoteStore``."""
        deadline = (timeout_s if timeout_s is not None
                    else self.rpc_timeout_s)
        self._next_id += 1
        rid = self._next_id
        msg = dict(msg, id=rid)
        if self.transport == "pipe":
            if not self.alive():
                raise WireEOF("store server is dead")
            write_frame(self._proc.stdin, msg)
            reader = self._reader
        else:
            try:
                sock, reader = self._socket_link()
                sock.sendall(pack_frame(msg))
            except OSError:
                # one re-dial per op: the server may have healed since
                # the link died
                self._drop_socket_link()
                sock, reader = self._socket_link()
                sock.sendall(pack_frame(msg))
        t0 = time.monotonic()
        while True:
            left = deadline - (time.monotonic() - t0)
            if left <= 0:
                raise WireTimeout(f"store rpc {msg.get('op')!r} timed out "
                                  f"after {deadline:.1f}s")
            try:
                reply = reader.read_frame(timeout_s=left)
            except WireError:
                if self.transport == "socket":
                    self._drop_socket_link()
                raise
            if reply.get("id") == rid:
                return reply
            # stale reply from an op that timed out earlier: discard

    # ------------------------------------------------------- lifecycle

    def kill(self) -> None:
        """SIGKILL, as a crash does it: no drain, L1 lost, L2 survives."""
        if self._proc is not None and self._proc.poll() is None:
            try:
                os.kill(self._proc.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
            self._proc.wait()
        self._drop_socket_link()

    def respawn(self) -> None:
        """Heal: next incarnation, same spec (and same port when it can
        be rebound), disk tier re-indexed by the fresh process."""
        self.kill()
        self.incarnation += 1
        self._spawn()

    def _reap(self) -> None:
        if self._proc is not None:
            if self._proc.poll() is None:
                try:
                    os.kill(self._proc.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
            self._proc.wait()

    def close(self) -> None:
        """Polite shutdown: close stdin (the leash — the worker exits 0
        on EOF), escalate to TERM/KILL if it lingers."""
        self._drop_socket_link()
        if self._proc is None:
            return
        if self._proc.poll() is None:
            try:
                self._proc.stdin.close()
            except OSError:
                pass
            try:
                self._proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    os.kill(self._proc.pid, signal.SIGKILL)
                    self._proc.wait()
        else:
            self._proc.wait()


class RemoteStore:
    """Client half: the exact ``PrefixStore`` surface, served remotely.

    ``PrefixCache`` (engine/prefix.py) talks to its store through three
    calls — ``contains``/``put``/``get`` — plus the capacity attributes;
    this class implements that surface over ``StoreServer.rpc`` (or a
    bare ``addr`` for engine workers that dialed in from another
    process) and enforces the fabric's failure contract: EVERY failure
    is a silent cold miss counted as ``engine.prefix_store_misses_remote``
    (through the engine's own ``count`` hook once the paged engine binds
    it, METRICS otherwise), never an exception out of a cache call.

    ``plan`` is the store's OWN seeded FaultPlan, polled exactly once
    per store op at ``inject.SITE_STORE``:

    - ``drop``      — the op silently never happens (miss);
    - ``corrupt``   — one payload byte is flipped, so the CRC/decoder
                      rejects it downstream (put poisons nothing: the
                      server's frame check refuses it; get returns an
                      undecodable record — both land as cold misses);
    - ``delay``     — virtual-clock sleep (plan.clock), then proceed;
    - ``partition`` — the link is severed and STAYS severed (every op
                      misses) until a scheduled ``heal`` fault or
                      ``heal_partition()`` clears it.
    """

    def __init__(self, server: Optional[StoreServer] = None,
                 addr: Optional[Tuple[str, int]] = None,
                 plan=None,
                 rpc_timeout_s: float = DEFAULT_STORE_RPC_TIMEOUT_S,
                 count=None):
        if (server is None) == (addr is None):
            raise ValueError("RemoteStore needs exactly one of server= "
                             "(in-parent handle) or addr= (dial a socket "
                             "store from another process)")
        self._server = server
        self._addr = (str(addr[0]), int(addr[1])) if addr is not None else None
        self._sock: Optional[socket.socket] = None
        self._sock_reader: Optional[FrameReader] = None
        self._next_id = 0
        self.plan = plan
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.count = count if count is not None else METRICS.inc
        self._partitioned = False
        # PrefixStore duck attributes: capacity lives server-side; the
        # local view advertises none so nothing double-budgets it
        self.host_pages = server.host_pages if server is not None else 0
        self.disk_dir = None
        self.disk_pages = server.disk_pages if server is not None else 0

    # ------------------------------------------------------- transport

    def bind_count(self, count) -> None:
        """The paged engine rebinds miss-counting onto its per-tick
        ``_count`` hook so misses flow into TickSample/Chrome/Prometheus
        alongside the other prefix counters."""
        self.count = count

    def _dial_rpc(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        if self._sock is None:
            sock = socket.create_connection(self._addr, timeout=2.0)
            sock.settimeout(None)
            self._sock = sock
            self._sock_reader = FrameReader(sock.makefile("rb", buffering=0))
        self._next_id += 1
        rid = self._next_id
        msg = dict(msg, id=rid)
        self._sock.sendall(pack_frame(msg))
        t0 = time.monotonic()
        while True:
            left = self.rpc_timeout_s - (time.monotonic() - t0)
            if left <= 0:
                raise WireTimeout("store rpc timed out")
            reply = self._sock_reader.read_frame(timeout_s=left)
            if reply.get("id") == rid:
                return reply

    def _sever(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._sock_reader = None
        if self._server is not None:
            self._server._drop_socket_link()

    def heal_partition(self) -> None:
        self._partitioned = False

    def _rpc(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Raises on failure; ``_op`` turns raises into misses."""
        if self._server is not None:
            return self._server.rpc(msg, timeout_s=self.rpc_timeout_s)
        try:
            return self._dial_rpc(msg)
        except (WireError, OSError):
            # one re-dial per op: the server may have healed in place
            self._sever()
            return self._dial_rpc(msg)

    def _poll_fault(self):
        """One SITE_STORE poll per store op — the seam's own plan, so
        its draws never perturb any other site's schedule."""
        if self.plan is None:
            return None
        from k8s_llm_rca_tpu.faults import inject

        fault = self.plan.poll(inject.SITE_STORE)
        if fault is None:
            return None
        if fault.kind == "heal":
            self._partitioned = False
            return None
        if fault.kind == "delay":
            self.plan.clock.sleep(fault.delay_s)
            return None
        if fault.kind == "partition":
            self._partitioned = True
            self._sever()
            return fault
        return fault                    # drop / corrupt

    def _miss(self, op: str, n: float = 1.0) -> None:
        self.count("engine.prefix_store_misses_remote", n)
        METRICS.inc(f"cluster.store_degraded_{op}")

    # ------------------------------------------------- PrefixStore API

    def contains(self, key: bytes) -> bool:
        fault = self._poll_fault()
        if self._partitioned or (fault is not None
                                 and fault.kind in ("drop", "partition")):
            self._miss("probe")
            return False
        try:
            reply = self._rpc({"op": "probe", "key": key.hex()})
        except (WireError, OSError):
            self._miss("probe")
            return False
        if not reply.get("ok"):
            self._miss("probe")
            return False
        return bool(reply.get("hit"))

    def put(self, key: bytes, rec: Dict[str, Any]) -> None:
        from k8s_llm_rca_tpu.obs import trace as obs_trace
        from k8s_llm_rca_tpu.utils import pages

        fault = self._poll_fault()
        if self._partitioned or (fault is not None
                                 and fault.kind in ("drop", "partition")):
            self._miss("put")
            return
        try:
            frame = pages.encode_page_record(rec)
        except ValueError:
            self._miss("put")           # oversized record: local drop
            return
        if fault is not None and fault.kind == "corrupt":
            # flip one payload byte: the server's CRC check refuses the
            # frame, so a corrupt put can never poison the store
            pos = wal.HEADER_SIZE
            frame = frame[:pos] + bytes([frame[pos] ^ 0xFF]) + frame[pos + 1:]
        try:
            reply = self._rpc({"op": "put", "key": key.hex(),
                               "page": base64.b64encode(frame)
                               .decode("ascii")})
        except (WireError, OSError):
            self._miss("put")
            return
        if not reply.get("ok"):
            self._miss("put")
            return
        obs_trace.event("cluster.store.put", key=key.hex()[:12],
                        nbytes=len(frame))

    def get(self, key: bytes) -> Optional[Tuple[Dict[str, Any], int]]:
        from k8s_llm_rca_tpu.obs import trace as obs_trace
        from k8s_llm_rca_tpu.utils import pages

        fault = self._poll_fault()
        if self._partitioned or (fault is not None
                                 and fault.kind in ("drop", "partition")):
            self._miss("get")
            return None
        try:
            reply = self._rpc({"op": "get", "key": key.hex()})
        except (WireError, OSError):
            self._miss("get")
            return None
        if not reply.get("ok"):
            self._miss("get")
            return None
        if not reply.get("hit"):
            return None                 # honest miss: not a degradation
        try:
            frame = base64.b64decode(reply["page"], validate=True)
        except (KeyError, TypeError, binascii.Error):
            self._miss("get")
            return None
        if fault is not None and fault.kind == "corrupt":
            pos = wal.HEADER_SIZE
            frame = frame[:pos] + bytes([frame[pos] ^ 0xFF]) + frame[pos + 1:]
        rec = pages.decode_page_record(frame)
        if rec is None:
            # torn/corrupt/version-mismatched record: identical cold miss
            self._miss("get")
            return None
        tier = int(reply.get("tier", 1))
        obs_trace.event("cluster.store.get", key=key.hex()[:12], tier=tier)
        return rec, tier

    # ---------------------------------------------------- introspection

    def stats(self) -> Dict[str, Any]:
        try:
            reply = self._rpc({"op": "stats"})
        except (WireError, OSError):
            return {}
        return reply.get("stats", {}) if reply.get("ok") else {}

    @property
    def n_host(self) -> int:
        return int(self.stats().get("n_host", 0))

    @property
    def n_disk(self) -> int:
        return int(self.stats().get("n_disk", 0))


# ---------------------------------------------------------------------------
# soak-facing bundle
# ---------------------------------------------------------------------------


class StoreFabric:
    """Server + client + exercise bookkeeping for a chaos soak.

    ``run_chaos_soak(store_fabric=...)`` drives ``exercise(i)`` once per
    incident: a deterministic synthetic page record round-trips through
    the remote store, and the outcome lands ONLY in this object's
    counters — never in the soak report — which is exactly how the
    byte-identity bar is honest: the store is genuinely exercised across
    every kill/heal the ``StoreKiller`` schedules, and the report bytes
    cannot know whether a fabric was attached."""

    def __init__(self, server: StoreServer, remote: RemoteStore):
        self.server = server
        self.remote = remote
        self.exercised = 0
        self.put_ok = 0
        self.hits = 0
        self.misses = 0

    def _synthetic_record(self, i: int) -> Tuple[bytes, Dict[str, Any]]:
        import hashlib

        import numpy as np

        key = hashlib.sha1(b"store-fabric-%d" % i).digest()
        rng = np.random.default_rng(i)
        rec = {"n_pages": 1,
               "k": rng.standard_normal((1, 1, 4, 8), dtype=np.float32),
               "v": rng.standard_normal((1, 1, 4, 8), dtype=np.float32)}
        return key, rec

    def exercise(self, i: int) -> bool:
        """One put+get round trip keyed by incident index; True on hit.
        Failures are the fabric's own business (counted here), invisible
        to the report."""
        import numpy as np

        key, rec = self._synthetic_record(i)
        self.exercised += 1
        self.remote.put(key, rec)
        got = self.remote.get(key)
        if got is None:
            self.misses += 1
            return False
        back, _tier = got
        if not all(np.array_equal(back[f], rec[f]) for f in rec):
            self.misses += 1
            return False
        self.put_ok += 1
        self.hits += 1
        return True

    def close(self) -> None:
        self.server.close()


def build_store_fabric(transport: str = "socket", host_pages: int = 64,
                       disk_dir: Optional[str] = None, disk_pages: int = 0,
                       plan=None,
                       rpc_timeout_s: float = DEFAULT_STORE_RPC_TIMEOUT_S
                       ) -> StoreFabric:
    """The one-call soak/test recipe: spawn a store server and wrap it
    with a parent-handle RemoteStore (which survives kill/heal because
    it reaches the server through the handle, not a frozen address)."""
    server = StoreServer(host_pages=host_pages, disk_dir=disk_dir,
                         disk_pages=disk_pages, transport=transport,
                         rpc_timeout_s=rpc_timeout_s)
    remote = RemoteStore(server=server, plan=plan,
                         rpc_timeout_s=rpc_timeout_s)
    return StoreFabric(server, remote)


if __name__ == "__main__":
    raise SystemExit(store_main(sys.argv[1:]))
