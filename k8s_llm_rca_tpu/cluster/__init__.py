"""Multi-replica serving cluster: disjoint submesh replicas, an
affinity/load-balancing router, and journal-consistent failover.

Layer map (PARITY.md §cluster, docs/cluster.md):

- ``submesh.carve_replica_meshes`` — carve the device list into N
  disjoint dp×tp submeshes (loud ValueError on indivisibility/overlap);
- ``replica.build_replicas`` / ``Replica`` — one engine per submesh,
  params initialized once and sharded per replica;
- ``router.ClusterRouter`` — the LMBackend facade the assistants
  service talks to: session affinity on thread id, queue-depth
  balancing, ``RouterAdmissionError`` backpressure, ``fail_replica``
  (kill + re-start on survivors) and ``drain_replica``
  (snapshot/adopt migration with decode position);
- ``health.HealthWatchdog`` / ``HealthPolicy`` /
  ``ReplicaSupervisor`` — the self-healing loop
  (``router.attach_health``): deterministic ALIVE -> SUSPECT -> DEAD
  liveness from tick/pump heartbeats, in-tree failover on DEAD,
  restart-and-rejoin on the original submesh, and poison-run
  quarantine after ``quarantine_after`` fatal incarnations;
- ``proc.ProcReplica`` / ``proc.build_proc_replicas`` — out-of-process
  replicas: each backend runs in its own OS process (spawned with the
  bench.py per-leg env recipe) behind the length-prefixed CRC-framed
  wire protocol (``wire.py``); the watchdog's liveness verdicts gain
  hard OS evidence (pipe EOF / exit codes) and the supervisor's
  ``rebuild`` restarts the actual process;
- ``disagg.TierRouter`` — disaggregated prefill/decode tiers over any
  of the above replica shapes, with a transactional (EXPORT -> ADOPT ->
  RELEASE) per-run KV handoff between the tiers that survives
  mid-handoff kills (``faults.supervisor.HandoffKiller``);
- ``autoscale.Autoscaler`` / ``ScalePolicy`` — the elastic control
  loop: watermark-driven scale-up (supervisor rebuild-recipe spawn
  onto a free submesh), drain-down (live sequences migrate, staged
  ``close()``, submesh parked back on the reserve), and prefill<->
  decode tier rebalancing via ``TierRouter.reassign_tier`` — all a
  pure function of the gauge sequence under a frozen VirtualClock.
"""

from k8s_llm_rca_tpu.cluster.autoscale import Autoscaler, ScalePolicy
from k8s_llm_rca_tpu.cluster.disagg import (TIER_DECODE, TIER_PREFILL,
                                            TierRouter)
from k8s_llm_rca_tpu.cluster.health import (ALIVE, DEAD, SUSPECT,
                                            HealthPolicy, HealthWatchdog,
                                            ReplicaSupervisor)
from k8s_llm_rca_tpu.cluster.proc import ProcReplica, build_proc_replicas
from k8s_llm_rca_tpu.cluster.replica import (EngineReplica, Replica,
                                             build_replicas)
from k8s_llm_rca_tpu.cluster.router import (ClusterRouter,
                                            RouterAdmissionError)
from k8s_llm_rca_tpu.cluster.submesh import carve_replica_meshes

__all__ = [
    "carve_replica_meshes", "build_replicas", "Replica", "EngineReplica",
    "ClusterRouter", "RouterAdmissionError",
    "HealthPolicy", "HealthWatchdog", "ReplicaSupervisor",
    "ALIVE", "SUSPECT", "DEAD",
    "ProcReplica", "build_proc_replicas",
    "TierRouter", "TIER_PREFILL", "TIER_DECODE",
    "Autoscaler", "ScalePolicy",
]
