"""Self-healing cluster layer: health watchdog, replica supervisor, and
the poison-run quarantine contract.

The reference pipeline survives incidents only because a human reruns
it (the operator re-invokes the sweep after an OpenAI failure); PR 6's
cluster made failover *possible* but still human-triggered —
``ClusterRouter.fail_replica`` must be called by someone, and a killed
replica never rejoins, so every chaos event permanently shrinks the
fleet.  This module closes the loop in-process:

- ``HealthPolicy`` / ``HealthWatchdog``: deterministic per-replica
  liveness.  The watchdog never pings a replica (a dead process cannot
  answer); it watches two *passive* signals the serving loop already
  produces — the engine's monotonic tick heartbeat
  (``EngineBase.step`` stamps ``heartbeat``/``heartbeat_t``; scripted
  replicas have no engine and contribute ``None``) and the router's
  pump-completion beat (``ClusterRouter.pump`` stamps
  ``HealthWatchdog.beat`` after each replica's successful pump).  A
  probe that observes NO fresh signal counts one miss; ``miss_budget``
  misses make the replica SUSPECT (the router routes new work around
  it), ``hung_tick_threshold`` misses make it DEAD (the router fails it
  over and — when a supervisor is attached — restarts it).  Misses are
  counted per *probe evaluation*, not per wall second, so the state
  machine is a pure function of the pump sequence and stays
  deterministic under a frozen VirtualClock (the PR 1 chaos-soak
  discipline: byte-identical reports).

- ``ReplicaSupervisor``: restart-and-rejoin.  A dead replica's engine
  is rebuilt on its ORIGINAL submesh from the recipe ``build_replicas``
  recorded (re-sharding the already-initialized params — the
  identical-replica invariant), re-registered with the router, and the
  fleet returns to N.  The supervisor validates at bind time that the
  replica submeshes are disjoint (a rebuild onto an overlapping mesh
  would race the survivors' collectives — loud ValueError, repo
  convention).

- Poison-run quarantine lives on the router (``quarantine_after``):
  a run whose replica dies K times across incarnations is settled
  FAILED with a named error instead of cascading through the fleet.
  The settlement rides the normal pump path, so serve/api.py journals
  it like any failure and recovery replay agrees byte-for-byte.

MTTD (last beat -> DEAD verdict) and MTTR (DEAD verdict -> rejoined)
are measured on the watchdog's injectable clock and surfaced as
``cluster.mttd`` / ``cluster.mttr`` spans plus lists on the objects for
bench.py's measured-or-null fields.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

# watchdog verdicts, in escalation order
ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


@dataclass(frozen=True)
class HealthPolicy:
    """Knobs of the ALIVE -> SUSPECT -> DEAD classifier.

    ``probe_interval_s``: minimum clock time between probe evaluations
    (0.0 = evaluate on every ``ClusterRouter.pump``, the deterministic
    default chaos soaks rely on — under a frozen VirtualClock a positive
    interval would evaluate exactly once).
    ``miss_budget``: consecutive signal-free probes before SUSPECT.
    ``hung_tick_threshold``: consecutive signal-free probes before DEAD;
    must exceed ``miss_budget`` so every replica passes through SUSPECT
    (and the router routes around it) before the failover fires.
    """

    probe_interval_s: float = 0.0
    miss_budget: int = 2
    hung_tick_threshold: int = 4

    def __post_init__(self):
        if self.probe_interval_s < 0.0:
            raise ValueError(
                f"probe_interval_s must be >= 0, got "
                f"{self.probe_interval_s}")
        if self.miss_budget < 1:
            raise ValueError(
                f"miss_budget must be >= 1 (a replica needs at least one "
                f"missed probe before suspicion), got {self.miss_budget}")
        if self.hung_tick_threshold <= self.miss_budget:
            raise ValueError(
                f"hung_tick_threshold ({self.hung_tick_threshold}) must "
                f"exceed miss_budget ({self.miss_budget}): a replica must "
                f"pass through SUSPECT before it is declared DEAD")


class HealthWatchdog:
    """Deterministic liveness classifier over a router's replicas.

    The router drives it: ``probe`` at the top of every ``pump`` (the
    returned list is the newly-DEAD replicas the router must heal) and
    ``beat`` after each replica's successful backend pump.  The per-
    replica signal is ``(pump beats, engine tick heartbeat)`` — beats
    keep an *idle* healthy replica ALIVE (its engine ticks nothing, but
    its pump completes), while the tick serial catches an engine that
    still answers pumps but never advances a tick.  A wedged replica
    (dead process) produces neither, misses accumulate, and the verdict
    escalates per ``HealthPolicy``.

    ``clock``: injectable time source (VirtualClock in soaks, wall time
    in bench) — the same discipline as ``EngineBase._now``.  The clock
    only timestamps MTTD/MTTR; classification depends on probe counts
    alone.
    """

    def __init__(self, policy: Optional[HealthPolicy] = None,
                 clock: Any = None):
        self.policy = policy or HealthPolicy()
        self.clock = clock
        self._states: Dict[int, str] = {}
        self._sig: Dict[int, tuple] = {}        # latest beat signal
        self._seen: Dict[int, tuple] = {}       # signal at last probe
        self._miss: Dict[int, int] = {}
        self._beats: Dict[int, int] = {}
        self._beat_t: Dict[int, float] = {}
        self._detected_t: Dict[int, float] = {}
        self._last_eval: Optional[float] = None
        self.detections: List[int] = []         # rid per DEAD verdict
        self.hard_detections: List[int] = []    # subset with OS evidence
        # evidence kind per hard detection, parallel to hard_detections:
        # "proc" (process death) vs "link" (relink budget exhausted) —
        # a separate list so hard_detections stays a plain rid list
        self.hard_kinds: List[str] = []
        self.mttd_s: List[float] = []           # last beat -> verdict

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.time()
        if inject._ARMED is not None:
            return inject._ARMED.clock.time()
        return time.time()

    # ------------------------------------------------------------ lifecycle

    def register(self, rid: int) -> None:
        """Start watching ``rid`` (router attach / fresh incarnation)."""
        self._states[rid] = ALIVE
        self._miss[rid] = 0
        self._seen.pop(rid, None)     # next probe re-baselines, no miss
        self._sig.pop(rid, None)
        self._beat_t[rid] = self._now()

    reset = register   # a restarted incarnation re-arms the same way

    def unregister(self, rid: int) -> None:
        """Stop watching ``rid`` (scale-down retirement,
        cluster/autoscale.py ``Autoscaler``): drop every per-replica
        signal so a stale verdict cannot leak into exports, and a later
        ``register`` of the same id starts from a clean baseline."""
        for d in (self._states, self._miss, self._seen, self._sig,
                  self._beats, self._beat_t, self._detected_t):
            d.pop(rid, None)

    # -------------------------------------------------------------- signals

    def beat(self, rid: int, ticks: Optional[int] = None) -> None:
        """One completed pump for ``rid`` (``ticks``: the engine's
        monotonic tick heartbeat, None for scripted replicas)."""
        self._beats[rid] = self._beats.get(rid, 0) + 1
        self._sig[rid] = (self._beats[rid], ticks)
        self._beat_t[rid] = self._now()

    # ------------------------------------------------------------- verdicts

    def state(self, rid: int) -> str:
        return self._states.get(rid, ALIVE)

    def states(self) -> Dict[int, str]:
        return dict(self._states)

    def is_suspect(self, rid: int) -> bool:
        return self._states.get(rid) == SUSPECT

    def detected_at(self, rid: int) -> Optional[float]:
        """Clock time of ``rid``'s latest DEAD verdict (MTTR's t0)."""
        return self._detected_t.get(rid)

    def _declare_dead(self, rid: int, now: float, misses: int,
                      evidence: Optional[str] = None,
                      kind: str = "proc") -> None:
        self._states[rid] = DEAD
        self._detected_t[rid] = now
        self.detections.append(rid)
        if evidence is not None:
            self.hard_detections.append(rid)
            self.hard_kinds.append(kind)
        t0 = self._beat_t.get(rid, now)
        self.mttd_s.append(max(0.0, now - t0))
        METRICS.inc("cluster.deaths_detected")
        obs_trace.event("cluster.health", replica=rid, state=DEAD,
                        misses=misses, evidence=evidence, kind=kind)
        tr = obs_trace._ACTIVE
        if tr is not None:
            tr.add_span("cluster.mttd", t0, now, cat="cluster",
                        args={"replica": rid})
        log.warning("watchdog: replica %d DEAD after %d missed probes%s",
                    rid, misses,
                    f" (hard evidence: {evidence})" if evidence else "")

    def probe(self, router) -> List[int]:
        """One probe evaluation; returns the newly-DEAD replica ids.

        Deterministic: a replica whose signal did not change since the
        last evaluation accrues one miss; a fresh signal clears the miss
        count (and demotes SUSPECT back to ALIVE).  The first evaluation
        after ``register`` only baselines the signal — startup is never
        a miss.

        Hard evidence (cluster/proc.py ``proc_liveness``: pipe EOF,
        ``poll()`` exit code, torn frame, missed protocol heartbeat)
        SHORT-CIRCUITS the miss budget: the OS already rendered the
        verdict, so the replica escalates one state per probe —
        ALIVE -> SUSPECT, SUSPECT -> DEAD — regardless of how fresh its
        last beat looked.  It still passes through SUSPECT (the
        invariant the router's routing-around contract relies on), but
        detection latency is 2 probes, not ``hung_tick_threshold``.
        """
        now = self._now()
        p = self.policy
        if (p.probe_interval_s > 0.0 and self._last_eval is not None
                and now - self._last_eval < p.probe_interval_s):
            return []
        self._last_eval = now
        newly_dead: List[int] = []
        for rid, replica in router.replicas.items():
            if not replica.alive or self._states.get(rid) == DEAD:
                continue   # already failed over / awaiting restart
            liveness = getattr(replica, "proc_liveness", None)
            evidence = liveness() if liveness is not None else None
            if evidence is not None:
                # "link" when the verdict came from relink-budget
                # exhaustion (cluster/proc.py death_kind), "proc" else
                ekind = getattr(replica, "evidence_kind", None)
                kind = ekind() if ekind is not None else "proc"
                self._miss[rid] = self._miss.get(rid, 0) + 1
                if self._states.get(rid) == SUSPECT:
                    self._declare_dead(rid, now, self._miss[rid],
                                       evidence=evidence, kind=kind)
                    newly_dead.append(rid)
                else:
                    self._states[rid] = SUSPECT
                    obs_trace.event("cluster.health", replica=rid,
                                    state=SUSPECT, misses=self._miss[rid],
                                    evidence=evidence)
                    log.warning("watchdog: replica %d SUSPECT on hard "
                                "evidence (%s)", rid, evidence)
                continue
            sig = self._sig.get(rid)
            if rid not in self._seen:
                self._seen[rid] = sig
                continue
            if sig != self._seen[rid]:
                self._seen[rid] = sig
                self._miss[rid] = 0
                if self._states.get(rid) == SUSPECT:
                    self._states[rid] = ALIVE
                    obs_trace.event("cluster.health", replica=rid,
                                    state=ALIVE, misses=0)
                continue
            self._miss[rid] = self._miss.get(rid, 0) + 1
            misses = self._miss[rid]
            if misses >= p.hung_tick_threshold:
                self._declare_dead(rid, now, misses)
                newly_dead.append(rid)
            elif misses >= p.miss_budget and self._states[rid] == ALIVE:
                self._states[rid] = SUSPECT
                obs_trace.event("cluster.health", replica=rid,
                                state=SUSPECT, misses=misses)
                log.warning("watchdog: replica %d SUSPECT after %d missed "
                            "probes (routing around it)", rid, misses)
        return newly_dead


class ReplicaSupervisor:
    """Restart-and-rejoin for DEAD replicas.

    On ``restart(rid)`` the supervisor runs the replica's recorded
    ``rebuild`` recipe (``build_replicas`` closes over the host params,
    partition specs and the replica's ORIGINAL submesh, so the fresh
    incarnation is byte-identical to the first — greedy decode on
    identical weights), re-tags observability, clears the wedge, and
    marks the replica alive so the router's next ``_pick`` sees the
    fleet back at N.

    ``restart=False`` keeps the supervisor as a recorder only: the
    router then treats it as absent — ``fail_replica``'s last-alive
    refusal stays in force (the pre-self-healing fallback).

    ``warmup_prompt``: optional prompt generated for 1 token on the
    fresh engine before rejoin, forcing compilation out of the serving
    path; never use it under an armed FaultPlan (the warmup ticks would
    shift ``SITE_ENGINE_TICK`` poll counters).  Rebuild + warmup wall
    cost lands in ``restart_s`` (bench's ``selfheal_restart_warmup_s``).
    """

    def __init__(self, restart: bool = True,
                 warmup_prompt: Optional[str] = None):
        self.restart_enabled = bool(restart)
        self.warmup_prompt = warmup_prompt
        self.router = None
        self.restarts: List[int] = []           # rid per restart, in order
        # rid per successful RELINK (same incarnation, new nonce) — the
        # router's _replay_relinked records these; a soak asserting
        # "every heal was a relink" checks relinks against the killer's
        # kills and restarts == []
        self.relinks: List[int] = []
        self.incarnations: Dict[int, int] = {}  # rid -> rebuild count
        self.restart_s: List[float] = []        # wall rebuild(+warmup) cost
        self.mttr_s: List[float] = []           # verdict -> rejoined

    def bind(self, router) -> None:
        """Attach to a router (``ClusterRouter.attach_health`` calls
        this).  Validates the engine replicas' submeshes are disjoint —
        restarting onto an overlapping submesh would race the survivors'
        collectives, so it is rejected loudly up front."""
        from k8s_llm_rca_tpu.engine.engine import validate_disjoint_submeshes

        meshes = [r.mesh for r in router.replicas.values()
                  if r.mesh is not None]
        if meshes:
            validate_disjoint_submeshes(meshes)
        self.router = router

    def restart(self, rid: int) -> None:
        """Rebuild ``rid`` on its original submesh and rejoin it."""
        if not self.restart_enabled:
            return
        router = self.router
        if router is None:
            raise ValueError("ReplicaSupervisor.restart before bind(): "
                             "attach via ClusterRouter.attach_health")
        replica = router.replicas[rid]
        if replica.rebuild is None:
            raise ValueError(
                f"replica {rid} has no rebuild recipe: build_replicas "
                f"records one per engine replica; scripted replicas need "
                f"Replica(..., rebuild=...) for restart-and-rejoin")
        t0 = time.perf_counter()
        backend = replica.rebuild()
        engine = getattr(backend, "engine", None)
        if engine is not None:
            engine.obs_replica = rid
            if router.health is not None:
                engine._hb_stamp = True
            if self.warmup_prompt is not None:
                sid = engine.submit(
                    engine.tokenizer.encode(self.warmup_prompt),
                    max_new_tokens=1)
                while engine.has_work:
                    engine.step()
                del sid
        replica.backend = backend
        replica.wedged = False
        replica.alive = True
        self.restart_s.append(time.perf_counter() - t0)
        inc = self.incarnations.get(rid, 0) + 1
        self.incarnations[rid] = inc
        self.restarts.append(rid)
        health = router.health
        if health is not None:
            detected = health.detected_at(rid)
            health.reset(rid)
            now = health._now()
            if detected is not None:
                self.mttr_s.append(max(0.0, now - detected))
                tr = obs_trace._ACTIVE
                if tr is not None:
                    tr.add_span("cluster.mttr", detected, now,
                                cat="cluster",
                                args={"replica": rid, "incarnation": inc})
        METRICS.inc("cluster.replica_restarts")
        obs_trace.event("cluster.restart", replica=rid, incarnation=inc)
        log.warning("supervisor: replica %d rebuilt and rejoined "
                    "(incarnation %d, fleet %d alive)", rid, inc,
                    len(router.alive_ids()))
