"""One serving replica: an LM backend bound to a replica id (and, for
engine replicas, its submesh).

``build_replicas`` is the engine path: the TINY weights are initialized
ONCE on host and sharded onto each replica's submesh
(runtime/sharding.py ``shard_pytree`` — GSPMD then keeps every replica's
compute on its own devices, the same committed-input propagation the TP
parity test relies on), so N replicas cost one param init and N device
transfers, not N inits.  Each replica gets its own engine, tokenizer
handle, and ``EngineBackend``; the engine is stamped with
``obs_replica`` so its ``engine.tick`` spans and TickSamples carry the
replica id (per-replica Chrome tracks, obs/export.py).

``Replica`` itself is backend-agnostic: the router only needs
``queue_depth()`` / ``occupancy()``, duck-typed here so scripted
backends (OracleBackend, EchoBackend — ``_inflight`` dicts) and the real
``EngineBackend`` (``_live`` + engine slots) all serve as replicas; the
cluster chaos soak runs 100 incidents on oracle replicas for exactly
this reason (tier-1 budget).

Overload composition (docs/serving.md "overload & priorities"): the
router admits by priority class against ``queue_depth()`` (CRITICAL
cap-exempt, BATCH one slot short), and migration preserves the class —
``fail_replica`` re-starts with the run's original GenOptions (priority
AND deadline_s ride along) while ``drain_replica`` adopts engine
snapshots whose sequence entries now carry priority and the absolute
engine deadline.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from k8s_llm_rca_tpu.engine.engine import validate_replica_mesh
from k8s_llm_rca_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Replica:
    """A replica slot in the cluster: id, backend, optional submesh.

    ``rebuild``: optional zero-arg recipe returning a FRESH backend for
    this slot — the restart-and-rejoin source (cluster/health.py
    ``ReplicaSupervisor``).  ``build_replicas`` records one per engine
    replica (re-shard the shared host params onto the SAME submesh);
    scripted replicas pass their own.

    ``wedged``: the in-tree stand-in for a dead worker process — the
    backend object still exists (its engine stands in for the corpse's
    device state) but the router stops pumping it, so it stops beating
    and the health watchdog must detect it.  ``fail_replica`` is the
    *consequence* of a wedge, never the injection itself.

    ``draining`` / ``retiring``: the scale-event window markers
    (cluster/autoscale.py).  ``draining`` is set while the replica's
    sequences are mid-migration (drain snapshot in flight), ``retiring``
    while its staged ``close()`` runs; both clear when the replica
    leaves the fleet (or rejoins a tier).  Fault killers REFUSE victims
    inside either window (faults/supervisor.py) — a kill there would
    orphan the drain snapshot.
    """

    def __init__(self, replica_id: int, backend: Any, mesh=None,
                 rebuild=None, layout=None, kv_layout=None):
        self.replica_id = replica_id
        self.backend = backend
        self.mesh = mesh
        self.rebuild = rebuild
        # weight-layout metadata for tiered serving (cluster/disagg.py):
        # ``layout`` is the runtime.rules.SpecLayout the params were
        # sharded under; ``kv_layout`` describes the KV-record geometry
        # a handoff peer must be able to adopt ({"page_size","kv_dtype",
        # "kv_dim","n_layers"}).  Scripted replicas (echo/oracle) leave
        # both None and skip the tier compatibility checks.
        self.layout = layout
        self.kv_layout = kv_layout
        self.alive = True
        self.wedged = False
        self.draining = False
        self.retiring = False

    def wedge(self) -> None:
        """Simulate this replica's process dying: it stays nominally
        alive (nobody told the router) but never beats again."""
        self.wedged = True

    def healthy(self) -> bool:
        """Serving right now, as far as the router knows.  Subclasses
        with a REAL process behind them (cluster/proc.py ProcReplica)
        also check hard liveness — drain loops that wait for the fleet
        to settle must use this, not ``alive``/``wedged`` directly, or a
        SIGKILLed worker would satisfy the predicate while dead."""
        return self.alive and not self.wedged

    def queue_depth(self) -> int:
        b = self.backend
        if hasattr(b, "queue_depth"):
            return int(b.queue_depth())
        if hasattr(b, "_live"):
            return len(b._live)
        if hasattr(b, "_inflight"):
            return len(b._inflight)
        raise TypeError(
            f"replica {self.replica_id}: backend "
            f"{type(b).__name__} exposes no queue-depth signal "
            f"(queue_depth() / _live / _inflight)")

    def occupancy(self) -> float:
        b = self.backend
        if hasattr(b, "occupancy"):
            return float(b.occupancy())
        return 0.0

    def __repr__(self) -> str:
        return (f"Replica({self.replica_id}, "
                f"{type(self.backend).__name__}, "
                f"alive={self.alive}, depth={self.queue_depth()})")


# kept as an alias for call sites that want to say what the replica IS
EngineReplica = Replica


def build_replicas(model_cfg, engine_cfg, n_replicas: int,
                   devices: Optional[Sequence[Any]] = None,
                   data: int = 1, fsdp: int = 1, seed: int = 0,
                   meshes=None, prefix_store=None, layout=None,
                   **engine_kw) -> List[Replica]:
    """N engine replicas on disjoint submeshes, one shared param init.

    ``meshes``: pre-carved submeshes (else ``carve_replica_meshes`` runs
    with ``devices``/``data``/``fsdp``).  Every mesh passes
    ``validate_replica_mesh`` — CP/PP/EP × replica compositions and
    submeshes the TINY head layout cannot shard are rejected loudly
    before any device work.  ``engine_kw`` forwards to ``make_engine``
    (e.g. ``use_kernel=False`` on the CPU test mesh).

    ``layout``: a ``runtime.rules.SpecLayout`` naming which mesh axes
    the logical data/fsdp/tp axes land on — the per-tier weight-layout
    hook (docs/cluster.md): a prefill tier can build TP-heavy replicas
    and a decode tier KV-wide ones from the SAME host params.  Defaults
    to ``FSDP_LAYOUT`` when the submeshes carry an fsdp axis > 1, else
    ``TP_LAYOUT``.  Every (layout, mesh) pair passes
    ``runtime.rules.validate_layout`` pre-flight — undefined axes and
    non-default mappings onto size-1 axes are named ValueErrors before
    any weight moves, and the supervisor ``rebuild`` recipe re-runs the
    same check so a restarted incarnation cannot silently change layout.

    ``prefix_store``: one SHARED ``engine.prefix.PrefixStore`` handed to
    every replica's engine (docs/cluster.md "warm-start"): pages any
    replica demotes (or ``flush_prefix_store``-publishes) become L1/L2
    hits on every other, so a new replica — and a supervisor-restarted
    incarnation, which rides the same ``engine_kw`` through the
    ``rebuild`` recipe below — warm-starts by h2d page promotion instead
    of re-prefilling the fleet's shared prompt preambles.
    """
    import jax

    from k8s_llm_rca_tpu.cluster.submesh import carve_replica_meshes
    from k8s_llm_rca_tpu.engine import make_engine
    from k8s_llm_rca_tpu.models import llama
    from k8s_llm_rca_tpu.runtime.sharding import (
        FSDP_LAYOUT, TP_LAYOUT, llama_param_specs, shard_pytree,
        validate_layout,
    )
    from k8s_llm_rca_tpu.serve.backend import EngineBackend

    if meshes is None:
        meshes = carve_replica_meshes(n_replicas, devices=devices,
                                      data=data, fsdp=fsdp)
    if len(meshes) != n_replicas:
        raise ValueError(f"{len(meshes)} meshes for {n_replicas} replicas")
    if layout is None:
        has_fsdp = fsdp > 1 or any(
            m is not None and m.shape.get("fsdp", 1) > 1 for m in meshes)
        layout = FSDP_LAYOUT if has_fsdp else TP_LAYOUT
    for mesh in meshes:
        validate_replica_mesh(mesh, model_cfg, engine_cfg)
        validate_layout(layout, mesh)

    if prefix_store is not None:
        engine_kw = dict(engine_kw, prefix_store=prefix_store)
    tok = engine_kw.pop("tokenizer", None)
    if tok is None:
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        tok = get_tokenizer(vocab_size=model_cfg.vocab_size)
    params = llama.init_params(model_cfg, jax.random.PRNGKey(seed))
    specs = llama_param_specs(model_cfg, layout=layout)
    kv_layout = {
        "page_size": engine_cfg.page_size if engine_cfg.paged else None,
        "kv_dtype": engine_cfg.kv_cache_dtype,
        "kv_dim": model_cfg.kv_dim,
        "n_layers": model_cfg.n_layers,
    }

    replicas: List[Replica] = []
    for rid, mesh in enumerate(meshes):
        sharded = shard_pytree(params, specs, mesh)
        engine = make_engine(model_cfg, engine_cfg, sharded, tok,
                             **engine_kw)
        engine.obs_replica = rid      # per-replica span/TickSample tag

        def _rebuild(mesh=mesh, rid=rid, kw=dict(engine_kw)):
            # restart-and-rejoin recipe (cluster/health.py): re-shard the
            # SAME host params onto the replica's ORIGINAL submesh — the
            # identical-replica invariant, so a restarted incarnation
            # generates byte-identically to the first.  The layout
            # pre-flight re-runs too: a rebuild can never adopt a layout
            # the original mesh would have refused.
            validate_layout(layout, mesh)
            eng = make_engine(model_cfg, engine_cfg,
                              shard_pytree(params, specs, mesh), tok, **kw)
            eng.obs_replica = rid
            return EngineBackend(eng)

        replicas.append(Replica(rid, EngineBackend(engine), mesh=mesh,
                                rebuild=_rebuild, layout=layout,
                                kv_layout=kv_layout))
    log.info("built %d engine replicas: %s devices each",
             len(replicas), meshes[0].devices.size if replicas else 0)
    return replicas
