"""Cluster router: session affinity, queue-depth balancing, admission
backpressure, and journal-consistent failover across replicas.

``ClusterRouter`` IS an ``LMBackend`` (serve/backend.py protocol): the
assistants service plugs it in where a single EngineBackend would go and
never learns it is talking to N replicas.  Global handles belong to the
router; each maps to ``(replica_id, local_handle)`` and the mapping is
rewritten — never surfaced — when a run migrates.

Routing (``start``):

- **affinity**: ``GenOptions.session`` (the thread id,
  serve/api.py:create_run) pins a session to one replica while that
  replica is alive.  A thread's prompt grows monotonically, so keeping
  its runs on one replica keeps its history in that replica's prefix
  cache (engine/prefix.py) — affinity is a cache-locality policy, not
  just stickiness.  A pinned replica at capacity overflows THIS run to
  the least-loaded replica without re-pinning: the next run returns to
  the warm replica.
- **balance**: un-pinned (or overflowed) runs go to the alive replica
  with the smallest ``queue_depth()``, ties to the lowest replica id —
  fully deterministic, no randomization (reports must be byte-stable).
- **backpressure**: when every alive replica is at
  ``max_inflight_per_replica``, ``start`` raises
  ``RouterAdmissionError`` instead of queueing unboundedly — the
  serve-layer caller owns retry/shedding policy, the router only refuses
  loudly (same philosophy as the engine's loud ValueError exclusions).

Failover (``fail_replica``): process-kill semantics — the replica's
device state is gone.  Its journaled-in-memory ``(prompt, opts)`` pairs
(the router records every admitted run; the durable twin lives in the
run journal, serve/journal.py) are re-started on survivors under the
SAME global handles, so the serve layer's ``_inflight`` map stays valid
across the kill and ``recover_service`` replay agrees with the router's
view.  Greedy decode makes the re-run byte-identical; generated-but-
unsettled tokens are dropped exactly like a supervised process crash
(serve/recover.py replay contract).

Migration (``drain_replica``): graceful decommission — the source is
still alive, so its sequences move WITH their decode position:
``engine.snapshot_sequences`` on the source, seq-id-remapping
``EngineBackend.adopt_sequences`` on the target, handle map rewritten in
place.  The re-prefill on the target is a prefix-cache mostly-HIT when
the target has seen the session before (tests/test_cluster.py proves
both the byte-identity and the hit-rate).

Self-healing (``attach_health``; cluster/health.py, docs/cluster.md
"Self-healing"): with a ``HealthWatchdog`` attached, every ``pump``
probes replica liveness first — a newly-DEAD replica is quarantine-
checked, failed over through the SAME ``fail_replica`` path (unchanged
semantics, now triggered in-tree), and, when a restart-enabled
``ReplicaSupervisor`` rides along, rebuilt on its original submesh so
the fleet returns to N.  ``_pick`` routes new work around SUSPECT
replicas while any fully-ALIVE replica has capacity.  Poison-run
quarantine: a run whose replica dies ``quarantine_after`` times across
incarnations settles FAILED with a named error through the normal pump
result path (serve/api.py journals it; recovery replay agrees).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

from k8s_llm_rca_tpu.cluster.replica import Replica
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.serve.backend import BackendResult, GenOptions
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


class RouterAdmissionError(RuntimeError):
    """Every alive replica is at its inflight cap — the cluster sheds the
    request instead of queueing it invisibly.  Retry/backoff belongs to
    the caller (resilience policy), not the router."""


class ClusterRouter:
    """LMBackend facade over N replicas.  See module docstring."""

    def __init__(self, replicas: List[Replica],
                 max_inflight_per_replica: Optional[int] = None,
                 quarantine_after: int = 2):
        if not replicas:
            raise ValueError("ClusterRouter needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {sorted(ids)}")
        if quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1 (a poison run needs at "
                f"least one fatal incarnation), got {quarantine_after}")
        self.replicas: Dict[int, Replica] = {
            r.replica_id: r for r in sorted(replicas,
                                            key=lambda r: r.replica_id)}
        self.max_inflight = max_inflight_per_replica
        self.quarantine_after = quarantine_after
        # self-healing attachments (attach_health; cluster/health.py)
        self.health = None                  # HealthWatchdog
        self.supervisor = None              # ReplicaSupervisor
        # poison-run tracking: global handle -> fatal incarnations
        self._deaths: Dict[int, int] = {}
        # quarantine settlements awaiting the next pump's result dict
        self._quarantined_results: Dict[int, "BackendResult"] = {}
        self.quarantined = 0
        self._handles = itertools.count()
        # global handle -> (replica_id, local handle); rewritten on
        # migration, never surfaced to callers
        self._handle_map: Dict[int, Tuple[int, int]] = {}
        self._local: Dict[Tuple[int, int], int] = {}   # reverse map
        # every admitted run's (prompt, opts): the failover re-start
        # source (in-memory twin of the journaled run_submit record)
        self._runs: Dict[int, Tuple[str, GenOptions]] = {}
        self._affinity: Dict[str, int] = {}            # session -> replica
        self.failovers = 0
        self.migrated_runs = 0

    # ------------------------------------------------------------ accessors

    def alive_ids(self) -> List[int]:
        return [rid for rid, r in self.replicas.items() if r.alive]

    def queue_depths(self) -> Dict[int, int]:
        return {rid: r.queue_depth()
                for rid, r in self.replicas.items() if r.alive}

    def occupancies(self) -> Dict[int, float]:
        return {rid: r.occupancy()
                for rid, r in self.replicas.items() if r.alive}

    # --------------------------------------------------------- self-healing

    def attach_health(self, watchdog, supervisor=None) -> None:
        """Arm the self-healing loop: ``watchdog`` (HealthWatchdog)
        classifies replicas on every ``pump``; ``supervisor``
        (ReplicaSupervisor, optional) restarts the DEAD ones so the
        fleet returns to N.  A single-replica router without a restart-
        enabled supervisor is rejected loudly — its only possible DEAD
        verdict would declare an unrecoverable outage, which is a
        monitoring wish, not a healing loop."""
        restart_on = (supervisor is not None
                      and supervisor.restart_enabled)
        if len(self.replicas) == 1 and not restart_on:
            raise ValueError(
                "watchdog on a single-replica router without restart: a "
                "DEAD verdict could neither fail over nor rejoin (attach "
                "a restart-enabled ReplicaSupervisor or add replicas)")
        if supervisor is not None:
            supervisor.bind(self)   # validates disjoint submeshes
        self.health = watchdog
        self.supervisor = supervisor
        for rid, replica in self.replicas.items():
            watchdog.register(rid)
            engine = getattr(replica.backend, "engine", None)
            if engine is not None:
                engine._hb_stamp = True   # clock-stamp tick heartbeats

    # ----------------------------------------------------- fleet membership

    def add_replica(self, replica: Replica, tier: Optional[str] = None) -> None:
        """Admit ``replica`` into the fleet (the elastic scale-up seam,
        cluster/autoscale.py).  Validates the id is fresh and — when the
        newcomer carries a submesh — that it is disjoint from every
        incumbent's (an overlapping submesh would race the survivors'
        collectives, same refusal as ``ReplicaSupervisor.bind``).  The
        replica dict is rebuilt SORTED by id: pump iteration order is a
        determinism surface and must not depend on admission history.
        With a watchdog attached the newcomer is registered immediately
        so its first probe baselines instead of missing."""
        if tier is not None:
            raise ValueError(
                f"add_replica(tier={tier!r}): a plain ClusterRouter has "
                f"no tiers — use a TierRouter (cluster/disagg.py) for "
                f"tiered admission")
        self._admit_replica(replica)

    def _admit_replica(self, replica: Replica) -> None:
        rid = replica.replica_id
        if rid in self.replicas:
            raise ValueError(
                f"replica id {rid} is already in the fleet "
                f"(ids: {sorted(self.replicas)})")
        if replica.mesh is not None:
            from k8s_llm_rca_tpu.engine.engine import (
                validate_disjoint_submeshes,
            )

            meshes = [r.mesh for r in self.replicas.values()
                      if r.mesh is not None]
            if meshes:
                validate_disjoint_submeshes(meshes + [replica.mesh])
        self.replicas[rid] = replica
        self.replicas = {r: self.replicas[r]
                         for r in sorted(self.replicas)}
        if self.health is not None:
            self.health.register(rid)
            engine = getattr(replica.backend, "engine", None)
            if engine is not None:
                engine._hb_stamp = True
        log.info("replica %d admitted to the fleet (%d replicas)",
                 rid, len(self.replicas))

    def remove_replica(self, rid: int) -> Replica:
        """Retire ``rid`` from the fleet entirely (the elastic
        scale-down seam) and return the Replica object so the caller can
        park it as a free submesh.  Refuses while the replica still owns
        in-flight runs (drain or fail it over first — silently dropping
        admitted work is the one thing the router never does) and when
        it is the last alive replica (an outage, not a scale-down)."""
        replica = self.replicas.get(rid)
        if replica is None:
            raise ValueError(
                f"replica {rid} is not in the fleet "
                f"(ids: {sorted(self.replicas)})")
        orphans = self._orphans(rid)
        if orphans:
            raise ValueError(
                f"refusing to remove replica {rid}: it still owns "
                f"{len(orphans)} in-flight run(s) — drain_replica or "
                f"fail_replica must migrate them first")
        if replica.alive and len(self.alive_ids()) <= 1:
            raise ValueError(
                f"refusing to remove replica {rid}: it is the last "
                f"alive replica (an outage, not a scale-down)")
        del self.replicas[rid]
        for session in [s for s, r in self._affinity.items() if r == rid]:
            del self._affinity[session]
        if self.health is not None:
            self.health.unregister(rid)
        log.info("replica %d removed from the fleet (%d replicas left)",
                 rid, len(self.replicas))
        return replica

    def _heal(self) -> None:
        """Top of every ``pump``: probe, then heal each newly-DEAD
        replica — quarantine its poison runs, fail it over (the existing
        ``fail_replica`` semantics, now triggered in-tree), and restart
        it when a restart-enabled supervisor is attached."""
        sup = self.supervisor
        restart_on = sup is not None and sup.restart_enabled
        for rid in self.health.probe(self):
            # poison-run quarantine BEFORE failover: a run that keeps
            # sinking its replica must not be re-started a K+1th time
            for ghandle in self._orphans(rid):
                deaths = self._deaths.get(ghandle, 0) + 1
                self._deaths[ghandle] = deaths
                if deaths >= self.quarantine_after:
                    self._quarantine(ghandle, rid, deaths)
            if restart_on and len(self.alive_ids()) <= 1:
                # last alive: fail_replica would refuse (an outage) but
                # with restart the outage is recoverable — rebuild the
                # corpse in place, then re-start its orphans on the
                # fresh incarnation
                self._restart_in_place(rid)
            else:
                self.fail_replica(rid)
                if restart_on:
                    sup.restart(rid)

    def _quarantine(self, ghandle: int, rid: int, deaths: int) -> None:
        """Settle a poison run FAILED with a named error.  The result
        rides the next ``pump``'s dict, so serve/api.py maps it to
        FAILED and journals ``run_settle`` exactly like any backend
        failure — recovery replay agrees with the live outcome."""
        loc = self._handle_map.pop(ghandle, None)
        self._runs.pop(ghandle, None)
        self._deaths.pop(ghandle, None)
        if loc is not None:
            self._local.pop(loc, None)
            self.replicas[loc[0]].backend.cancel(loc[1])
        self._quarantined_results[ghandle] = BackendResult(
            text="", completion_tokens=0,
            error=(f"quarantined: replica died {deaths} times with this "
                   f"run in flight (poison run, quarantine_after="
                   f"{self.quarantine_after})"))
        self.quarantined += 1
        METRICS.inc("cluster.quarantined_runs")
        obs_trace.event("cluster.quarantine", run=ghandle, replica=rid,
                        deaths=deaths)
        log.warning("run %d quarantined after %d fatal incarnations "
                    "(replica %d)", ghandle, deaths, rid)

    def _restart_in_place(self, rid: int) -> None:
        """Last-alive heal path: take the corpse out without the
        last-alive refusal, restart it on its submesh, then re-start its
        orphans on the fresh incarnation (same global handles — the same
        contract as ``fail_replica``, minus survivors)."""
        replica = self.replicas[rid]
        replica.alive = False
        orphans = self._orphans(rid)
        for ghandle in orphans:
            _, lhandle = self._handle_map[ghandle]
            self._local.pop((rid, lhandle), None)
            replica.backend.cancel(lhandle)
        for session in [s for s, r in self._affinity.items() if r == rid]:
            del self._affinity[session]
        self.supervisor.restart(rid)
        for ghandle in orphans:
            prompt, opts = self._runs[ghandle]
            new_rid = self._pick(opts.session, admit=False)
            with inject.readmission():
                new_lhandle = self.replicas[new_rid].backend.start(prompt,
                                                                   opts)
            self._handle_map[ghandle] = (new_rid, new_lhandle)
            self._local[(new_rid, new_lhandle)] = ghandle
        self.failovers += 1
        METRICS.inc("cluster.failovers")
        obs_trace.event("cluster.failover", replica=rid,
                        kind="restart-in-place", migrated=len(orphans),
                        alive=len(self.alive_ids()))
        log.warning("replica %d restarted in place: %d runs re-started "
                    "on the fresh incarnation", rid, len(orphans))

    # -------------------------------------------------------------- routing

    def _has_capacity(self, replica: Replica, priority: int = 1) -> bool:
        """Priority-tiered admission (docs/serving.md "overload &
        priorities"): CRITICAL (priority <= 0) is cap-EXEMPT — never shed
        while any replica is alive; NORMAL fills up to the inflight cap;
        BATCH (priority >= 2) stops one slot short, reserving headroom so
        backpressure sheds BATCH strictly before NORMAL."""
        if priority <= 0 or self.max_inflight is None:
            return True
        cap = self.max_inflight if priority == 1 else self.max_inflight - 1
        return replica.queue_depth() < cap

    def _pick(self, session: str, admit: bool = True,
              priority: int = 1,
              among: Optional[List[int]] = None) -> int:
        """Deterministic replica choice; raises RouterAdmissionError when
        the cluster is saturated for the request's priority class.
        ``admit=False`` is the failover path: the run was ALREADY
        admitted, so the inflight cap does not apply — a kill must never
        shed work the cluster accepted.  ``among`` narrows the candidate
        set (the TierRouter's tier filter, cluster/disagg.py); when no
        candidate is alive the filter is DROPPED rather than refusing —
        a whole dead tier degrades to keep-serving, not an outage."""
        full_alive = self.alive_ids()
        if not full_alive:
            raise RouterAdmissionError("no alive replica")
        alive = full_alive
        if among is not None:
            tiered = [rid for rid in full_alive if rid in among]
            if tiered:
                alive = tiered
        # route around SUSPECT replicas (cluster/health.py) while any
        # fully-ALIVE replica exists — new work must not pile onto a
        # replica the watchdog already distrusts; if EVERY replica is
        # suspect, keep serving (a stall beats an outage)
        suspect = (set() if self.health is None
                   else {rid for rid in alive
                         if self.health.is_suspect(rid)})
        if session:
            pinned = self._affinity.get(session)
            if pinned is not None and not self.replicas[pinned].alive:
                pinned = None               # re-pin below
            if pinned is not None and pinned not in alive:
                # alive but outside this pick's candidate tier: ignore
                # the pin for THIS pick without deleting it — it stays
                # valid for future picks over its own tier
                pinned = None
            if (pinned is not None and pinned in suspect
                    and len(suspect) < len(alive)):
                del self._affinity[session]   # pin follows to a healthy
                pinned = None                 # replica picked below
            if pinned is not None and (not admit or self._has_capacity(
                    self.replicas[pinned], priority)):
                return pinned
        open_ = [rid for rid in alive
                 if not admit or self._has_capacity(self.replicas[rid],
                                                    priority)]
        if suspect and open_:
            healthy = [rid for rid in open_ if rid not in suspect]
            if healthy:
                open_ = healthy
        if not open_:
            raise RouterAdmissionError(
                f"all {len(alive)} alive replicas at inflight cap "
                f"{self.max_inflight} for priority {priority}; "
                "shedding request")
        rid = min(open_, key=lambda r: (self.replicas[r].queue_depth(), r))
        if session and self._affinity.get(session) not in full_alive:
            self._affinity[session] = rid   # (re-)pin; overflow keeps pin
        return rid

    # ------------------------------------------------------------- protocol

    def start(self, prompt: str, opts: GenOptions) -> int:
        rid = self._pick(opts.session, priority=opts.priority)
        replica = self.replicas[rid]
        lhandle = replica.backend.start(prompt, opts)
        ghandle = next(self._handles)
        self._handle_map[ghandle] = (rid, lhandle)
        self._local[(rid, lhandle)] = ghandle
        self._runs[ghandle] = (prompt, opts)
        obs_trace.event("cluster.route", replica=rid,
                        session=opts.session,
                        depth=replica.queue_depth())
        METRICS.inc("cluster.dispatches")
        return ghandle

    def pump(self) -> Dict[int, BackendResult]:
        results: Dict[int, BackendResult] = {}
        if self.health is not None:
            self._heal()
            if self._quarantined_results:
                results.update(self._quarantined_results)
                self._quarantined_results.clear()
        for rid, replica in self.replicas.items():
            if not replica.alive or replica.wedged:
                # wedged: the worker process is gone — nothing to pump,
                # no beat; the watchdog detects it by the silence
                continue
            liveness = getattr(replica, "proc_liveness", None)
            if liveness is not None and liveness() is not None:
                # a real dead OS process (cluster/proc.py): pumping its
                # proxy would "succeed" (empty dict) and beat the
                # watchdog forever — skip pump AND beat, so the silence
                # plus the hard exit evidence escalates SUSPECT -> DEAD
                continue
            link = getattr(replica, "link_liveness", None)
            if link is not None and link() is not None:
                # live process, dead LINK (cluster/net.py): not death
                # evidence — relink the SAME incarnation and replay its
                # in-flight runs in place.  While the relink budget
                # holds, BEAT the watchdog even on a failed attempt so
                # the soft-miss path cannot race the budget to a DEAD
                # verdict; budget exhaustion converts the outage into
                # hard "link" evidence, which escalates like any death.
                if replica.relink():
                    # watchdog-heal flush (cluster/proc.py telemetry
                    # shipping): telemetry buffered while the link was
                    # down ships before the replay re-drives the runs
                    drain_tel = getattr(replica.backend,
                                        "drain_telemetry", None)
                    if drain_tel is not None:
                        drain_tel()
                    self._replay_relinked(rid)
                else:
                    if (self.health is not None
                            and replica.proc_liveness() is None):
                        self.health.beat(
                            rid, ticks=getattr(replica.backend,
                                               "last_heartbeat", None))
                    continue
            # mirror the router's view into the replica engine before its
            # tick, so this tick's TickSample carries this tick's load
            engine = getattr(replica.backend, "engine", None)
            if engine is not None:
                engine._cluster_gauges = {
                    "queue_depth": float(replica.queue_depth()),
                    "occupancy": float(replica.occupancy()),
                }
            for lhandle, res in replica.backend.pump().items():
                ghandle = self._local.pop((rid, lhandle), None)
                if ghandle is None:        # settled after cancel: drop
                    continue
                self._handle_map.pop(ghandle, None)
                self._runs.pop(ghandle, None)
                self._deaths.pop(ghandle, None)
                results[ghandle] = res
            if self.health is not None:
                # engine-less proc proxies still carry a tick signal:
                # the worker's protocol heartbeat from its last response
                ticks = (engine.heartbeat if engine is not None
                         else getattr(replica.backend, "last_heartbeat",
                                      None))
                self.health.beat(rid, ticks=ticks)
        return results

    def busy(self, handle: int) -> bool:
        return handle in self._handle_map

    def cancel(self, handle: int) -> None:
        loc = self._handle_map.pop(handle, None)
        self._runs.pop(handle, None)
        self._deaths.pop(handle, None)
        self._quarantined_results.pop(handle, None)
        if loc is None:
            return
        self._local.pop(loc, None)
        rid, lhandle = loc
        self.replicas[rid].backend.cancel(lhandle)

    def count_tokens(self, text: str) -> int:
        first = next(iter(self.replicas.values()))
        return first.backend.count_tokens(text)

    def host_counters(self) -> Dict[str, float]:
        """Sum of the alive replicas' engine host counters (the cluster's
        aggregate host<->device traffic, serve/backend.py contract)."""
        total: Dict[str, float] = {}
        for r in self.replicas.values():
            if not r.alive or not hasattr(r.backend, "host_counters"):
                continue
            for k, v in r.backend.host_counters().items():
                total[k] = total.get(k, 0.0) + v
        return total

    # ------------------------------------------------------------- failover

    def _replay_relinked(self, rid: int) -> None:
        """After a successful relink: replay ``rid``'s in-flight runs on
        the SAME warm incarnation under their existing global handles —
        the journal-boundary twin of ``fail_replica``, minus the
        failover.  A partition can black-hole a start OR swallow a pump
        reply the worker already settled, so every non-injected orphan
        is cancelled (pop-tolerant both sides) and re-started through
        ``inject.readmission``; greedy determinism regenerates settled
        results byte-identically.  Injected-failed/stalled handles are
        excluded — they settle locally, and replaying them would erase
        their injected outcomes."""
        replica = self.replicas[rid]
        backend = replica.backend
        replay_ok = getattr(backend, "replayable", None)
        replayed = 0
        for ghandle in self._orphans(rid):
            _, lhandle = self._handle_map[ghandle]
            if replay_ok is not None and not replay_ok(lhandle):
                continue
            self._local.pop((rid, lhandle), None)
            backend.cancel(lhandle)
            prompt, opts = self._runs[ghandle]
            with inject.readmission():
                new_lhandle = backend.start(prompt, opts)
            self._handle_map[ghandle] = (rid, new_lhandle)
            self._local[(rid, new_lhandle)] = ghandle
            replayed += 1
        if self.supervisor is not None:
            self.supervisor.relinks.append(rid)
        log.warning("replica %d relinked: %d run(s) replayed on the "
                    "same incarnation", rid, replayed)

    def _orphans(self, rid: int) -> List[int]:
        """Global handles currently assigned to ``rid``, in admission
        order (global handles are monotonic)."""
        return sorted(g for g, (r, _) in self._handle_map.items()
                      if r == rid)

    def fail_replica(self, rid: int) -> List[int]:
        """Hard-kill ``rid`` and re-start its in-flight runs on
        survivors under their existing global handles.  Returns the
        migrated global handles.  Refuses to kill the last alive
        replica."""
        replica = self.replicas.get(rid)
        if replica is None or not replica.alive:
            raise ValueError(f"replica {rid} is not alive")
        if len(self.alive_ids()) <= 1:
            raise ValueError(
                f"refusing to fail replica {rid}: it is the last alive "
                f"replica (an outage, not a failover)")
        replica.alive = False
        orphans = self._orphans(rid)
        # reap the dead replica's engine state (the engine OBJECT stands
        # in for the dead worker; cancelling releases its slots/pages)
        for ghandle in orphans:
            _, lhandle = self._handle_map[ghandle]
            self._local.pop((rid, lhandle), None)
            replica.backend.cancel(lhandle)
        # drop dead pins; _pick re-pins each session on its next touch
        for session in [s for s, r in self._affinity.items() if r == rid]:
            del self._affinity[session]
        for ghandle in orphans:
            prompt, opts = self._runs[ghandle]
            new_rid = self._pick(opts.session, admit=False)
            # a re-admission, not a new run: the logical run drew its
            # SITE_BACKEND fault at its FIRST start (see inject.readmission)
            with inject.readmission():
                new_lhandle = self.replicas[new_rid].backend.start(prompt,
                                                                   opts)
            self._handle_map[ghandle] = (new_rid, new_lhandle)
            self._local[(new_rid, new_lhandle)] = ghandle
        self.failovers += 1
        METRICS.inc("cluster.failovers")
        obs_trace.event("cluster.failover", replica=rid, kind="kill",
                        migrated=len(orphans),
                        alive=len(self.alive_ids()))
        log.warning("replica %d failed: %d runs re-started on survivors "
                    "(%d alive)", rid, len(orphans),
                    len(self.alive_ids()))
        return orphans

    def drain_replica(self, rid: int,
                      target: Optional[int] = None) -> List[int]:
        """Gracefully decommission ``rid``: migrate its sequences — WITH
        their decode position — onto ``target`` (default: least-loaded
        survivor) via snapshot/adopt, then take it out of rotation.
        Returns the migrated global handles."""
        replica = self.replicas.get(rid)
        if replica is None or not replica.alive:
            raise ValueError(f"replica {rid} is not alive")
        alive = [r for r in self.alive_ids() if r != rid]
        if not alive:
            raise ValueError(
                f"refusing to drain replica {rid}: no surviving replica "
                f"to migrate onto")
        if target is None:
            target = min(alive,
                         key=lambda r: (self.replicas[r].queue_depth(), r))
        if target == rid or target not in alive:
            raise ValueError(f"drain target {target} must be a DIFFERENT "
                             f"alive replica (alive: {alive})")
        src, dst = replica.backend, self.replicas[target].backend
        if (not hasattr(src, "snapshot_sequences")
                or not hasattr(dst, "adopt_sequences")):
            raise ValueError(
                "drain_replica needs engine replicas on both sides "
                "(snapshot_sequences/adopt_sequences); for scripted "
                "replicas use fail_replica (re-start semantics)")
        # the BACKEND-level migration seam (serve/backend.py
        # EngineBackend.snapshot_sequences): flush-prefix-store (the
        # warm-start contract), snapshot, and the seq->handle mapping
        # all happen behind it, so an out-of-process replica
        # (cluster/proc.py) answers the same call over the wire and the
        # router never reaches for engine internals it cannot see
        snap, src_lhandles = src.snapshot_sequences()
        ghandles = [self._local[(rid, lh)] for lh in src_lhandles]
        opts_list = [self._runs[g][1] for g in ghandles]
        new_lhandles = dst.adopt_sequences(snap, opts_list)
        replica.alive = False
        # runs with no engine sequence (injected-failed/stalled) cannot
        # be snapshotted; they fail over by re-start, like a kill
        leftovers = [g for g in self._orphans(rid) if g not in ghandles]
        # the source's sequences moved; retire them there so the drained
        # engine ends clean (pages freed through the normal cancel path)
        for ghandle, lhandle in zip(ghandles, src_lhandles):
            self._local.pop((rid, lhandle), None)
            src.cancel(lhandle)
        for ghandle, new_lhandle in zip(ghandles, new_lhandles):
            self._handle_map[ghandle] = (target, new_lhandle)
            self._local[(target, new_lhandle)] = ghandle
        for ghandle in leftovers:
            _, lhandle = self._handle_map[ghandle]
            self._local.pop((rid, lhandle), None)
            src.cancel(lhandle)
            prompt, opts = self._runs[ghandle]
            new_rid = min(alive,
                          key=lambda r: (self.replicas[r].queue_depth(),
                                         r))
            with inject.readmission():
                nl = self.replicas[new_rid].backend.start(prompt, opts)
            self._handle_map[ghandle] = (new_rid, nl)
            self._local[(new_rid, nl)] = ghandle
        for session in [s for s, r in self._affinity.items() if r == rid]:
            self._affinity[session] = target   # follow the sequences
        self.migrated_runs += len(ghandles)
        METRICS.inc("cluster.migrated_runs", len(ghandles))
        obs_trace.event("cluster.failover", replica=rid, kind="drain",
                        migrated=len(ghandles), target=target)
        log.info("replica %d drained: %d sequences adopted by replica %d",
                 rid, len(ghandles), target)
        return ghandles
