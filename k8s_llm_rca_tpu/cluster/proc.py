"""Out-of-process replicas: each replica's backend runs in its OWN
interpreter, so a real OS-process death (SIGKILL mid-decode) is finally a
fault the fleet can experience — and survive — in-tree.

Until now every "replica" was an in-process object and the worst a chaos
plan could do was *pretend* a process died (``Replica.wedge``).  This
module makes the fault domain real:

- **worker**: ``python -m k8s_llm_rca_tpu.cluster.proc '<spec-json>'``
  builds one backend (scripted oracle / echo, or a real TINY engine) and
  serves the framed request/response protocol of cluster/wire.py over
  its stdin/stdout pipes.  The parent spawns it with the
  ``__graft_entry__._respawn_clean`` / bench.py per-leg env recipe —
  ``PYTHONPATH`` REPLACED by the repo root (dropping the axon
  sitecustomize that would force the tunnel platform at CONFIG level)
  and ``JAX_PLATFORMS=cpu`` set before any computation — so a worker can
  never grab the tunnel's chip grant (host rule: one TPU process at a
  time).
- **ProcBackend**: the parent-side proxy presenting the exact
  ``LMBackend`` surface (start/pump/busy/cancel/count_tokens plus the
  queue_depth/occupancy gauges), so ``ClusterRouter`` plugs it in
  unchanged.  Every response frame carries the worker's incarnation and
  a protocol heartbeat; a transport failure (pipe EOF, torn/corrupt
  frame, RPC timeout, nonzero ``poll()``) is recorded as hard death
  EVIDENCE — the proxy goes silent instead of raising into the router,
  and the health watchdog turns silence + evidence into SUSPECT -> DEAD
  (cluster/health.py), never a hang.
- **ProcReplica**: a ``Replica`` whose rebuild recipe spawns a fresh OS
  process (incarnation + 1), so ``ReplicaSupervisor.restart`` restarts
  the *actual process* and rejoins it.  Recovery is journal-fenced at
  two levels: orphaned runs re-start on survivors under their original
  global handles via the router's recorded ``(prompt, opts)`` twin of
  the run journal (``fail_replica`` + ``inject.readmission``), and every
  response frame's incarnation is checked so a stale worker's bytes can
  never be attributed to the new incarnation.

Protocol (one JSON frame per message, cluster/wire.py framing):

  parent -> worker: ``{"op", "id", ...}`` (plus an optional ``trace``
  propagation context when the spec opts into telemetry); worker ->
  parent: ``{"id", "inc", "hb", ...}`` (or ``{"err": {"type",
  "msg"}}``), optionally carrying a piggybacked ``tel`` telemetry
  payload.  Ops: ready (handshake, worker-initiated), ping, start,
  pump, cancel, snapshot, adopt, export_run, adopt_run,
  drain_telemetry, drain.  GenOptions cross the wire as serve/journal.py's
  ``encode_gen`` dicts (grammar as SPEC — compiled FSMs never cross a
  process boundary); engine state crosses as the JSON-safe
  ``snapshot_sequences`` export.

Fault-injection parity (the soak byte-identity contract): the armed
FaultPlan lives in the PARENT, so ProcBackend polls ``SITE_BACKEND`` for
engine-kind workers exactly where ``EngineBackend.start`` would
(budget/error/stall, plus the stalled-run virtual-clock sleep in pump) —
injected runs never reach the worker, mirroring the in-process backend
where they never reach the engine.  Scripted kinds poll NOTHING, exactly
like OracleBackend/EchoBackend, which is why the proc-cluster oracle
soak's report is byte-identical to the in-process cluster-oracle run.
"""

from __future__ import annotations

import itertools
import json
import os
import select
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from k8s_llm_rca_tpu.cluster.net import (
    DEFAULT_HANDSHAKE_TIMEOUT_S, PipeTransport, SocketTransport,
    connect_transport,
)
from k8s_llm_rca_tpu.cluster.replica import Replica
from k8s_llm_rca_tpu.cluster.wire import (
    FrameReader, WireEOF, WireError, WireTimeout, write_frame,
)
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

# set in every worker's environment; a worker trying to spawn its own
# proc replicas is refused loudly (nested proc-in-proc)
WORKER_ENV = "K8S_RCA_PROC_WORKER"

WORKER_KINDS = ("oracle", "echo", "engine")

# how the parent reaches the worker: the PR 12 stdio pipes, or a TCP
# socket (cluster/net.py) — the cross-host shape, relinkable on link
# failure because a dead SOCKET is not a dead PROCESS
TRANSPORTS = ("pipe", "socket")

# relink attempts (one per router pump) before a down link becomes hard
# death evidence of kind "link" and the respawn path takes over
DEFAULT_RELINK_BUDGET = 3

# engine workers compile their TINY engine before answering the ready
# handshake; scripted workers only pay the import of the serving stack
DEFAULT_SPAWN_TIMEOUT_S = 300.0
DEFAULT_RPC_TIMEOUT_S = 60.0


class WorkerError(RuntimeError):
    """A worker op raised; the error crossed the wire by name/message."""


def _repo_root() -> str:
    """The directory that contains the ``k8s_llm_rca_tpu`` package — the
    ONLY entry the worker's PYTHONPATH gets (replacing, not extending,
    the parent's: the axon sitecustomize on the parent's path would
    force the tunnel platform inside the worker)."""
    import k8s_llm_rca_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(k8s_llm_rca_tpu.__file__)))


def _with_host_device_count(flags: str, n: int) -> str:
    """XLA_FLAGS with --xla_force_host_platform_device_count pinned to
    n, replacing any existing (possibly mismatched) value — the
    __graft_entry__._respawn_clean recipe, reimplemented here because
    package code must not import the top-level driver."""
    parts = [p for p in flags.split()
             if not p.startswith("--xla_force_host_platform_device_count")]
    parts.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(parts)


def worker_env(devices: int = 1) -> Dict[str, str]:
    """The spawn environment: the parent's env with the CPU-platform
    pins applied BEFORE any computation (CLAUDE.md host rule — see
    ``_respawn_clean`` and bench.py's per-leg recipe)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo_root()
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _with_host_device_count(env.get("XLA_FLAGS", ""),
                                               devices)
    env[WORKER_ENV] = "1"
    return env


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


def _build_worker_backend(spec: Dict[str, Any]):
    """Build the worker's backend from its spec.  Returns ``(backend,
    heartbeat_fn)`` — the heartbeat is the engine's monotonic tick serial
    for engine workers (so a worker that answers pumps but whose engine
    never advances is still caught) and a per-pump counter otherwise."""
    kind = spec.get("kind", "oracle")
    if kind == "oracle":
        from k8s_llm_rca_tpu.rca.oracle import OracleBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        backend = OracleBackend(get_tokenizer(),
                                chaos=spec.get("oracle_chaos"))
        return backend, None
    if kind == "echo":
        from k8s_llm_rca_tpu.serve.backend import EchoBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        backend = EchoBackend(get_tokenizer(),
                              reply=spec.get("echo_reply"),
                              delay_pumps=int(spec.get("echo_delay_pumps",
                                                       0)))
        return backend, None
    if kind == "engine":
        import jax

        # belt and braces: the env pin is authoritative, but re-assert at
        # CONFIG level before any computation (tests/_distributed_worker.py
        # discipline) so a future jax cannot lazily re-probe platforms
        jax.config.update("jax_platforms", "cpu")

        from k8s_llm_rca_tpu.config import TINY, EngineConfig
        from k8s_llm_rca_tpu.engine import make_engine
        from k8s_llm_rca_tpu.models import llama
        from k8s_llm_rca_tpu.serve.backend import EngineBackend
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        # the soak/cluster TINY shape (faults/soak.py
        # _build_engine_service), one compile bucket, greedy — the
        # identical-replica invariant: every incarnation of every proc
        # replica initializes the same params from the same seed, so a
        # restarted process generates byte-identically to the first
        cfg = TINY.replace(max_seq_len=2560)
        ecfg = EngineConfig(max_batch=4, max_seq_len=2560,
                            prefill_buckets=(2560,),
                            max_new_tokens=96, temperature=0.0,
                            paged=True, page_size=64, num_pages=168,
                            prefix_cache=False, decode_chunk=16)
        overrides = spec.get("engine_overrides") or {}
        if overrides:
            import dataclasses as _dc

            ecfg = _dc.replace(ecfg, **overrides)
        params = llama.init_params(cfg,
                                   jax.random.PRNGKey(spec.get("seed", 0)))
        tok = get_tokenizer(vocab_size=cfg.vocab_size)
        # per-tier weight layout (spec "layout"/"mesh_shape", validated
        # parent-side by build_proc_replicas): the worker builds a
        # data×fsdp×tp mesh over its OWN virtual CPU devices (the spec's
        # "devices" count, pinned by worker_env), rule-shards the params
        # under the shipped SpecLayout, and hands the mesh to the engine
        # for cache/pool placement — same params, same seed, different
        # layout per tier; greedy outputs stay byte-identical (GSPMD
        # committed-input propagation, tests/test_sharding_rules.py).
        mesh_kw: Dict[str, Any] = {}
        layout_d = spec.get("layout")
        mesh_shape = spec.get("mesh_shape") or {}
        if layout_d is not None or mesh_shape:
            from k8s_llm_rca_tpu.config import MeshConfig
            from k8s_llm_rca_tpu.runtime.mesh import build_mesh
            from k8s_llm_rca_tpu.runtime.rules import (
                FSDP_LAYOUT, SpecLayout, TP_LAYOUT, validate_layout,
            )
            from k8s_llm_rca_tpu.runtime.sharding import (
                llama_param_specs, shard_pytree,
            )

            mcfg = MeshConfig(**{k: int(v) for k, v in mesh_shape.items()})
            mesh = build_mesh(mcfg, devices=jax.devices()[:mcfg.n_devices])
            layout = (SpecLayout.from_dict(layout_d)
                      if layout_d is not None
                      else (FSDP_LAYOUT if mcfg.fsdp > 1 else TP_LAYOUT))
            validate_layout(layout, mesh)
            params = shard_pytree(
                params, llama_param_specs(cfg, layout=layout), mesh)
            mesh_kw["tp_mesh"] = mesh
            if mcfg.fsdp > 1:
                mesh_kw["fsdp_mesh"] = mesh
        # cache-fabric attachment (docs/cluster.md "Cache fabric"): a
        # ``store_addr`` [host, port] in the spec dials the shared
        # cross-host StoreServer and plugs it in as the engine's prefix
        # store — the same PrefixStore surface the in-process tiers use,
        # so warm starts / store-backed restores work identically from a
        # worker process.  A dead store degrades every op to a counted
        # cold miss (cluster/store.py failure contract), so worker
        # byte-parity never depends on the fabric's health.
        store = None
        if spec.get("store_addr") is not None:
            from k8s_llm_rca_tpu.cluster.store import RemoteStore

            host, port = spec["store_addr"]
            store = RemoteStore(addr=(str(host), int(port)))
        backend = EngineBackend(make_engine(cfg, ecfg, params, tok,
                                            use_kernel=False,
                                            prefix_store=store,
                                            **mesh_kw))
        return backend, (lambda: int(backend.engine.heartbeat))
    raise ValueError(f"unknown proc worker kind {kind!r}: expected one "
                     f"of {WORKER_KINDS}")


def _result_to_json(res) -> Dict[str, Any]:
    return {"text": res.text, "completion_tokens": res.completion_tokens,
            "prompt_tokens": res.prompt_tokens, "error": res.error,
            "expired": bool(res.expired)}


# telemetry shipping (spec {"trace": true}): the worker buffers completed
# spans / events / TickSamples in a bounded ring and piggybacks up to
# REPLY_BUDGET items on every reply frame; drain ops flush DRAIN_BUDGET
# per turn.  Both budgets keep a reply frame far under
# wire.MAX_FRAME_SIZE; a SIGKILL loses at most the ring (bounded loss).
DEFAULT_TELEMETRY_RING = 4096
TELEMETRY_REPLY_BUDGET = 64
TELEMETRY_DRAIN_BUDGET = 1024


class _WorkerTelemetry:
    """Worker half of telemetry shipping: watches the worker's own
    Tracer for newly-COMPLETED spans (the worker is single-threaded, so
    the span store is a completed prefix between ops), new events, and
    new TickSamples, converts them to wire form, and buffers them in a
    TelemetryRing until a reply frame carries them out."""

    def __init__(self, tracer, ring_capacity: int = DEFAULT_TELEMETRY_RING):
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        self.tracer = tracer
        self.ring = obs_trace.TelemetryRing(ring_capacity)
        self._wire = (obs_trace.span_to_wire, obs_trace.event_to_wire,
                      obs_trace.tick_to_wire)
        self._spans_seen = 0
        self._events_seen = 0
        self._ticks_seen = 0

    def collect(self) -> None:
        span_fn, event_fn, tick_fn = self._wire
        spans = self.tracer.spans
        i = self._spans_seen
        while i < len(spans) and spans[i].t1 is not None:
            self.ring.push(span_fn(spans[i]))
            i += 1
        self._spans_seen = i
        for ev in self.tracer.events[self._events_seen:]:
            self.ring.push(event_fn(ev))
        self._events_seen = len(self.tracer.events)
        delta = self.tracer.timeline.total - self._ticks_seen
        if delta > 0:
            samples = self.tracer.timeline.samples()
            fresh = samples[max(0, len(samples) - delta):]
            # ticks the timeline ring overwrote before we got here are
            # loss too — count them with the ring's own shed
            self.ring.shed += delta - len(fresh)
            for s in fresh:
                self.ring.push(tick_fn(s))
            self._ticks_seen = self.tracer.timeline.total

    def payload(self, budget: int,
                counters: bool = False) -> Optional[Dict[str, Any]]:
        items = self.ring.pop(budget)
        if not items and not counters:
            return None
        p: Dict[str, Any] = {
            "pid": os.getpid(), "items": items,
            "shed": self.ring.shed + self.tracer.dropped,
            "more": len(self.ring) > 0}
        if counters:
            p["counters"] = METRICS.snapshot()
        return p


def _build_worker_telemetry(spec: Dict[str, Any]):
    """Worker tracer + shipping ring when the spec opts in
    (``{"trace": true}``) — the worker tracer runs on a PropagatedClock
    so its spans are stamped in the parent's (possibly virtual)
    timebase, and it is module-activated so the engine's existing
    instrumentation records into it untouched."""
    if not spec.get("trace"):
        return None
    from k8s_llm_rca_tpu.obs import trace as obs_trace

    tracer = obs_trace.Tracer(clock=obs_trace.PropagatedClock())
    obs_trace.activate(tracer)
    return _WorkerTelemetry(
        tracer,
        ring_capacity=int(spec.get("telemetry_ring",
                                   DEFAULT_TELEMETRY_RING)))


def _handle_op(msg: Dict[str, Any], backend, state: Dict[str, Any],
               inc: int, hb) -> Tuple[Dict[str, Any], bool]:
    """One decoded request -> ``(reply, drain)`` — shared by the pipe
    loop and both socket serve loops so every transport speaks the exact
    same op surface.  The reply is hb-stamped; the serve loop that owns
    the link stamps the session nonce (socket modes only).

    When the worker runs a tracer (``state["tel"]``, spec
    ``{"trace": true}``), each handled op is bracketed by a
    ``cluster.proc.serve`` span parented onto the request's propagated
    trace context, and the reply frame piggybacks a bounded telemetry
    payload — shipping rides frames that exist anyway, so it can never
    change a fault draw."""
    from k8s_llm_rca_tpu.serve.journal import decode_gen

    op = msg.get("op")
    reply: Dict[str, Any] = {"id": msg.get("id"), "inc": inc}
    drain = False
    tel = state.get("tel")
    serve_span = None
    if tel is not None:
        ctx = msg.get("trace") or {}
        if "ts" in ctx:
            tel.tracer.clock.advance_to(ctx["ts"])
        serve_span = tel.tracer.begin(
            "cluster.proc.serve", cat="cluster",
            args={"op": op, "trace": ctx.get("id"),
                  "link": ctx.get("parent")})
        if serve_span is not None and ctx.get("parent") is not None:
            # parent onto the PROPAGATED context: the serve span is a
            # worker-side root, so its parent is the parent process's
            # cluster.proc.rpc span (args.link keeps the id visible in
            # the merged trace UI, where X events hide parentage)
            serve_span.parent_id = int(ctx["parent"])
    try:
        if op == "ping":
            reply["ok"] = True
        elif op == "start":
            reply["handle"] = backend.start(msg["prompt"],
                                            decode_gen(msg["gen"]))
        elif op == "pump":
            state["pumps"] += 1
            results = backend.pump()
            reply["results"] = {str(h): _result_to_json(r)
                                for h, r in results.items()}
            # Replica.queue_depth's duck typing, worker-side
            if hasattr(backend, "queue_depth"):
                reply["depth"] = int(backend.queue_depth())
            else:
                reply["depth"] = len(getattr(backend, "_live", None)
                                     or getattr(backend, "_inflight",
                                                ()))
            occ = getattr(backend, "occupancy", None)
            reply["occupancy"] = float(occ()) if occ else 0.0
        elif op == "cancel":
            backend.cancel(int(msg["handle"]))
            reply["ok"] = True
        elif op == "snapshot":
            snap, handles = backend.snapshot_sequences()
            reply["snap"] = snap
            reply["handles"] = handles
        elif op == "adopt":
            opts = [decode_gen(g) for g in msg["gens"]]
            reply["handles"] = backend.adopt_sequences(msg["snap"],
                                                       opts)
        elif op == "export_run":
            # per-run handoff EXPORT (cluster/disagg.py); None frame =
            # not exportable this pump (settled / mid-prefill) — the
            # caller treats that as try-again, not failure
            reply["frame"] = backend.export_run(int(msg["handle"]))
        elif op == "adopt_run":
            # per-run handoff ADOPT: a torn frame raises inside
            # adopt_run and crosses the wire as err (WorkerError
            # parent-side) BEFORE any engine state moved
            reply["handle"] = backend.adopt_run(msg["frame"],
                                                decode_gen(msg["gen"]))
        elif op == "drain_telemetry":
            # explicit flush (parent close() / watchdog relink heal):
            # touches ONLY the telemetry ring — no backend call, no
            # fault-site poll, so shipping can never change a fault draw
            reply["ok"] = True
        elif op == "drain":
            # graceful shutdown: finish nothing, ack, exit 0 — the
            # parent has already migrated/cancelled what it wanted
            reply["ok"] = True
            drain = True
        else:
            raise ValueError(f"unknown wire op {op!r}")
    except Exception as e:                    # noqa: BLE001 — crosses wire
        reply = {"id": msg.get("id"), "inc": inc,
                 "err": {"type": type(e).__name__, "msg": str(e)}}
    if tel is not None:
        # close the serve span BEFORE collecting, so op N's own span is
        # part of the completed prefix and ships in reply N
        tel.tracer.end(serve_span)
        tel.collect()
        big = op in ("drain", "drain_telemetry")
        payload = tel.payload(
            TELEMETRY_DRAIN_BUDGET if big else TELEMETRY_REPLY_BUDGET,
            counters=big)
        if payload is not None:
            reply["tel"] = payload
    reply["hb"] = hb()
    return reply, drain


def _adopt_connection(sock: socket.socket, inc: int, cur_nonce: int, hb,
                      kind: str):
    """Worker half of the link-fencing handshake on one fresh
    connection.  Returns ``(transport, nonce)`` when adopted,
    ``(None, cur_nonce)`` when refused — refusal answers on the NEW
    connection and closes it, leaving any serving link untouched.

    The fencing rule: adopt only a session nonce STRICTLY greater than
    the one currently served.  A stale nonce is a connection the parent
    already superseded (or a partitioned twin of the parent) — refusing
    it here is the no-split-brain half the WORKER owns; the parent owns
    the other half by discarding stale-nonce reply frames."""
    transport = SocketTransport(sock)
    try:
        hello = transport.recv(timeout_s=DEFAULT_HANDSHAKE_TIMEOUT_S)
    except (WireError, OSError):
        transport.close()
        return None, cur_nonce
    nonce = hello.get("nonce")
    if (hello.get("op") != "hello" or hello.get("inc") != inc
            or not isinstance(nonce, int)):
        _refuse(transport, inc, "BadHello",
                f"expected hello(inc={inc}, nonce=int), got {hello!r}")
        return None, cur_nonce
    if nonce <= cur_nonce:
        _refuse(transport, inc, "StaleNonce",
                f"nonce {nonce} <= serving nonce {cur_nonce}: link "
                f"already superseded")
        return None, cur_nonce
    transport.nonce = nonce
    try:
        transport.send({"op": "ready", "id": -1, "inc": inc,
                        "pid": os.getpid(), "kind": kind, "nonce": nonce,
                        "hb": hb()})
    except (WireError, OSError):
        transport.close()
        return None, cur_nonce
    return transport, nonce


def _refuse(transport, inc: int, err_type: str, msg: str) -> None:
    try:
        transport.send({"id": -1, "inc": inc,
                        "err": {"type": err_type, "msg": msg}})
    except (WireError, OSError):
        pass
    transport.close()


def _serve_frames(conn, backend, state: Dict[str, Any], inc: int, hb,
                  corrupt_after, hang_after) -> str:
    """Answer every frame currently available on a readable link (one
    select wakeup can deliver many frames — drain via ``pending()``).
    Returns ``"ok"``, ``"linkdown"`` (the LINK died; the worker keeps
    its state warm for a relink) or ``"drain"`` (exit requested)."""
    try:
        msg = conn.recv(timeout_s=DEFAULT_RPC_TIMEOUT_S)
    except (WireError, OSError):
        return "linkdown"
    while msg is not None:
        state["handled"] += 1
        if corrupt_after is not None and state["handled"] > int(corrupt_after):
            try:
                conn.send_raw(b"\x00garbage-not-a-frame\xff\xfe")
            except (WireError, OSError):
                pass
            os._exit(3)
        if hang_after is not None and state["handled"] > int(hang_after):
            while True:
                time.sleep(3600)
        reply, drain = _handle_op(msg, backend, state, inc, hb)
        reply["nonce"] = conn.nonce
        try:
            conn.send(reply)
        except (WireError, OSError):
            return "linkdown"
        if drain:
            return "drain"
        msg = conn.pending()
    return "ok"


_LEASH_CHUNK = 4096


def _serve_listen(spec: Dict[str, Any], out, backend,
                  state: Dict[str, Any], inc: int, hb) -> int:
    """``--listen`` socket mode: bind loopback (or ``listen_host``),
    announce the port in a ``listening`` bootstrap frame on stdout (the
    ONLY frame stdout ever carries in socket mode), then serve the op
    protocol over whichever connection holds the highest session nonce.

    Link death is NOT worker death: on conn EOF/corruption the worker
    drops that link and keeps accepting, state warm, so the parent can
    relink to the SAME incarnation.  stdin is the lifetime leash — EOF
    there means the parent is gone and the worker exits 0 (a worker
    never outlives its parent, even with no link up)."""
    corrupt_after = spec.get("chaos_corrupt_after")
    hang_after = spec.get("chaos_hang_after")
    # chaos knob for the relink-budget-exhaustion tests: stop accepting
    # (close the listener) after N adopted links, so every further
    # relink dial dies at connect()
    max_accepts = spec.get("chaos_max_accepts")
    kind = spec.get("kind", "oracle")
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind((spec.get("listen_host", "127.0.0.1"),
                   int(spec.get("listen_port", 0))))
    listener.listen(8)
    port = listener.getsockname()[1]
    write_frame(out, {"op": "listening", "id": -1, "inc": inc,
                      "pid": os.getpid(), "port": port, "kind": kind,
                      "hb": hb()})
    leash = sys.stdin.buffer
    conn = None                   # link serving the highest nonce
    nonce = 0
    adopted = 0
    try:
        while True:
            rlist = [leash]
            if listener is not None:
                rlist.append(listener)
            if conn is not None:
                rlist.append(conn)
            readable, _, _ = select.select(rlist, [], [])
            if leash in readable:
                if not os.read(leash.fileno(), _LEASH_CHUNK):
                    return 0      # parent went away
            if listener is not None and listener in readable:
                fresh, _ = listener.accept()
                transport, nonce = _adopt_connection(fresh, inc, nonce,
                                                     hb, kind)
                if transport is not None:
                    if conn is not None:
                        # no split-brain: at most one live link per
                        # worker — the newer nonce drops the old
                        # connection the instant it is adopted
                        conn.close()
                    conn = transport
                    adopted += 1
                    if (max_accepts is not None
                            and adopted >= int(max_accepts)):
                        listener.close()
                        listener = None
                    continue      # re-select: old conn is gone
            if conn is not None and conn in readable:
                verdict = _serve_frames(conn, backend, state, inc, hb,
                                        corrupt_after, hang_after)
                if verdict == "drain":
                    return 0
                if verdict == "linkdown":
                    conn.close()
                    conn = None
    finally:
        if conn is not None:
            conn.close()
        if listener is not None:
            listener.close()


def _serve_connect(spec: Dict[str, Any], peer: Tuple[str, int], backend,
                   state: Dict[str, Any], inc: int, hb) -> int:
    """``--connect`` socket mode: the cross-host inversion where the
    WORKER dials a listening parent (NAT/firewall-friendly) and serves
    the identical fenced protocol — the parent still initiates the
    ``hello``/nonce, so the fencing rule is direction-agnostic.  On link
    death the worker re-dials (the relink initiative flips sides with
    the dial direction), giving up after ``connect_retries`` consecutive
    failures; stdin EOF still exits."""
    corrupt_after = spec.get("chaos_corrupt_after")
    hang_after = spec.get("chaos_hang_after")
    kind = spec.get("kind", "oracle")
    retries = int(spec.get("connect_retries", 3))
    leash = sys.stdin.buffer
    nonce = 0
    failures = 0
    while True:
        try:
            sock = socket.create_connection(
                peer, timeout=DEFAULT_HANDSHAKE_TIMEOUT_S)
            sock.settimeout(None)
        except OSError:
            failures += 1
            if failures > retries:
                return 1
            time.sleep(0.05 * failures)
            continue
        conn, nonce = _adopt_connection(sock, inc, nonce, hb, kind)
        if conn is None:
            failures += 1
            if failures > retries:
                return 1
            continue
        failures = 0
        try:
            while conn is not None:
                readable, _, _ = select.select([leash, conn], [], [])
                if leash in readable:
                    if not os.read(leash.fileno(), _LEASH_CHUNK):
                        return 0
                if conn is not None and conn in readable:
                    verdict = _serve_frames(conn, backend, state, inc,
                                            hb, corrupt_after,
                                            hang_after)
                    if verdict == "drain":
                        return 0
                    if verdict == "linkdown":
                        conn.close()
                        conn = None
        finally:
            if conn is not None:
                conn.close()


def worker_main(argv: Sequence[str]) -> int:
    """Serve the wire protocol until a drain frame or stdin EOF.

    The real stdout fd is claimed for frames FIRST and ``sys.stdout`` is
    repointed at stderr, so a stray ``print`` anywhere in the serving
    stack garbles a log line instead of a frame.

    Modes: bare ``'<spec-json>'`` serves over the stdio pipes (PR 12,
    byte-identical); ``--listen '<spec-json>'`` binds a TCP listener and
    announces the port on stdout; ``--connect HOST:PORT '<spec-json>'``
    dials a listening parent.  Both socket modes serve the same framed
    protocol with session-nonce link fencing (cluster/net.py).
    """
    out = sys.stdout.buffer
    sys.stdout = sys.stderr
    args = list(argv)
    mode = "pipe"
    peer: Optional[Tuple[str, int]] = None
    if args and args[0] == "--listen":
        mode = "listen"
        args = args[1:]
    elif args and args[0] == "--connect":
        if len(args) < 2 or ":" not in args[1]:
            raise SystemExit(
                "usage: python -m k8s_llm_rca_tpu.cluster.proc "
                "--connect HOST:PORT '<spec-json>'")
        host, _, port = args[1].rpartition(":")
        peer = (host, int(port))
        mode = "connect"
        args = args[2:]
    if len(args) != 1:
        raise SystemExit("usage: python -m k8s_llm_rca_tpu.cluster.proc "
                         "[--listen | --connect HOST:PORT] '<spec-json>'")
    spec = json.loads(args[0])
    inc = int(spec.get("incarnation", 0))
    # chaos knobs for the wire-failure tests: after N handled requests,
    # corrupt the stream (garbage bytes, hard exit) or go silent forever
    # (the missed-protocol-heartbeat path) — deterministic, no signals
    corrupt_after = spec.get("chaos_corrupt_after")
    hang_after = spec.get("chaos_hang_after")

    backend, hb_fn = _build_worker_backend(spec)
    state: Dict[str, Any] = {"pumps": 0, "handled": 0,
                             "tel": _build_worker_telemetry(spec)}

    def hb() -> int:
        return hb_fn() if hb_fn is not None else state["pumps"]

    if mode == "listen":
        return _serve_listen(spec, out, backend, state, inc, hb)
    if mode == "connect":
        return _serve_connect(spec, peer, backend, state, inc, hb)

    write_frame(out, {"op": "ready", "id": -1, "inc": inc, "pid": os.getpid(),
                      "kind": spec.get("kind", "oracle"), "hb": hb()})
    reader = FrameReader(sys.stdin.buffer)
    while True:
        try:
            msg = reader.read_frame()
        except WireEOF:
            return 0      # parent went away: a worker never outlives it
        state["handled"] += 1
        if corrupt_after is not None and state["handled"] > int(corrupt_after):
            out.write(b"\x00garbage-not-a-frame\xff\xfe")
            out.flush()
            os._exit(3)
        if hang_after is not None and state["handled"] > int(hang_after):
            while True:
                time.sleep(3600)
        reply, drain = _handle_op(msg, backend, state, inc, hb)
        write_frame(out, reply)
        if drain:
            return 0
    return 0


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------


class ProcBackend:
    """Parent-side proxy for one worker process (LMBackend surface).

    Local (synthetic, NEGATIVE) handles exist for runs that never reach
    the worker: injected-failed/stalled engine-kind runs (the parent
    polls the armed plan, mirroring EngineBackend.start) and runs routed
    here after the process died but before the watchdog's verdict
    (black-holed — exactly like a request on the wire to a dead box; the
    failover re-start under the same global handle recovers it).
    """

    def __init__(self, spec: Dict[str, Any],
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 rpc_timeout_s: float = DEFAULT_RPC_TIMEOUT_S):
        from k8s_llm_rca_tpu.obs import trace as obs_trace
        from k8s_llm_rca_tpu.utils.tokenizer import get_tokenizer

        self.spec = dict(spec)
        self.kind = self.spec.get("kind", "oracle")
        if self.kind not in WORKER_KINDS:
            raise ValueError(f"unknown proc worker kind {self.kind!r}: "
                             f"expected one of {WORKER_KINDS}")
        self.incarnation = int(self.spec.get("incarnation", 0))
        self.replica_id = int(self.spec.get("replica_id", 0))
        self.rpc_timeout_s = rpc_timeout_s
        self.transport_kind = self.spec.get("transport", "pipe")
        if self.transport_kind not in TRANSPORTS:
            raise ValueError(
                f"unknown proc transport {self.transport_kind!r}: "
                f"expected one of {TRANSPORTS}")
        self.relink_budget = int(self.spec.get("relink_budget",
                                               DEFAULT_RELINK_BUDGET))
        if self.relink_budget < 1:
            raise ValueError(
                f"relink_budget must be >= 1, got {self.relink_budget}: "
                f"a zero budget makes every link blip process death, "
                f"which is the pipe transport's semantics — use "
                f"transport='pipe' instead")
        # session-nonce link fencing (socket transports): monotonic per
        # connection; the worker adopts only strictly-greater nonces
        self._nonce = 0
        self.relinks = 0
        self.relink_attempts = 0
        self._link_evidence: Optional[str] = None
        # evidence kind for health.hard_kinds: "proc" (death observed /
        # inferred at the process) vs "link" (relink budget exhausted)
        self.death_kind: Optional[str] = None
        self._transport = None
        self._port: Optional[int] = None
        self._ids = itertools.count()
        # parent-side run mirror: handle -> True (remote) / False (local)
        self._live: Dict[int, bool] = {}
        self._local_handles = itertools.count(-1, -1)
        self._failed: Dict[int, str] = {}     # injected run failures
        self._stalled: set = set()            # injected stalls
        self._dead_evidence: Optional[str] = None
        self._occupancy = 0.0
        self.last_heartbeat: Optional[int] = None
        self.rpcs = 0
        self.spawn_s: Optional[float] = None
        # fleet flight recorder (spec {"trace": true}): outbound frames
        # carry the active tracer's propagation context; reply frames
        # carry back worker telemetry, ingested into the tracer's
        # remote store keyed (replica_id, incarnation)
        self.telemetry = bool(self.spec.get("trace"))
        self.telemetry_frames = 0
        self.telemetry_items = 0
        self._tel_more = False
        if self.kind == "engine":
            # count_tokens stays parent-side (one RPC per usage line
            # would dominate the protocol); the tokenizer is the
            # deterministic byte-fallback one, so parent and worker
            # counts agree exactly
            from k8s_llm_rca_tpu.config import TINY

            self._tokenizer = get_tokenizer(vocab_size=TINY.vocab_size)
            # drain/adopt seam, bound per-kind so ``hasattr`` keeps the
            # router's scripted-replica drain refusal intact; the
            # per-run handoff seam (cluster/disagg.py) follows the same
            # pattern — TierRouter detects it with hasattr too
            self.snapshot_sequences = self._snapshot_sequences
            self.adopt_sequences = self._adopt_sequences
            self.export_run = self._export_run
            self.adopt_run = self._adopt_run
        else:
            self._tokenizer = get_tokenizer()
        t0 = time.perf_counter()
        with obs_trace.span("cluster.proc.spawn", cat="cluster",
                            replica=self.replica_id, kind=self.kind,
                            incarnation=self.incarnation):
            argv = [sys.executable, "-m", "k8s_llm_rca_tpu.cluster.proc"]
            if self.transport_kind == "socket":
                argv.append("--listen")
            argv.append(json.dumps(self.spec, sort_keys=True))
            self._proc = subprocess.Popen(
                argv,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                env=worker_env(int(self.spec.get("devices", 1))))
            if self.transport_kind == "pipe":
                self._transport = PipeTransport(self._proc.stdin,
                                                self._proc.stdout)
                try:
                    ready = self._transport.recv(timeout_s=spawn_timeout_s)
                except WireError as e:
                    rc = self._proc.poll()
                    self._reap()
                    raise WorkerError(
                        f"proc replica {self.replica_id} worker failed "
                        f"its ready handshake (rc={rc}): {e}") from e
                if (ready.get("op") != "ready"
                        or ready.get("inc") != self.incarnation):
                    self._reap()
                    raise WorkerError(
                        f"proc replica {self.replica_id}: bad ready "
                        f"frame {ready!r}")
            else:
                # socket bootstrap: the worker's only stdout frame
                # announces its port; stdin stays open afterwards as
                # the worker's lifetime leash (EOF there = parent gone)
                boot_reader = FrameReader(self._proc.stdout)
                try:
                    boot = boot_reader.read_frame(
                        timeout_s=spawn_timeout_s)
                except WireError as e:
                    rc = self._proc.poll()
                    self._reap()
                    raise WorkerError(
                        f"proc replica {self.replica_id} worker failed "
                        f"its listening bootstrap (rc={rc}): {e}") from e
                if (boot.get("op") != "listening"
                        or boot.get("inc") != self.incarnation):
                    self._reap()
                    raise WorkerError(
                        f"proc replica {self.replica_id}: bad listening "
                        f"frame {boot!r}")
                self._port = int(boot["port"])
                try:
                    ready = self._connect()
                except (WireError, OSError) as e:
                    self._reap()
                    raise WorkerError(
                        f"proc replica {self.replica_id} worker refused "
                        f"the fenced connect on port {self._port}: {e}"
                    ) from e
        self.pid = int(ready["pid"])
        self.last_heartbeat = ready.get("hb")
        self.spawn_s = time.perf_counter() - t0
        METRICS.inc("cluster.proc_spawns")
        log.info("proc replica %d: %s worker pid %d up (incarnation %d, "
                 "%.2fs)", self.replica_id, self.kind, self.pid,
                 self.incarnation, self.spawn_s)

    # ------------------------------------------------------------ transport

    def _mark_dead(self, evidence: str) -> None:
        if self._dead_evidence is None:
            rc = self._proc.poll()
            if rc is not None:
                evidence = f"{evidence}; exit:{rc}"
            self._dead_evidence = evidence
            if self.death_kind is None:
                self.death_kind = "proc"
            METRICS.inc("cluster.proc_deaths_observed")
            log.warning("proc replica %d: transport down (%s)",
                        self.replica_id, evidence)

    def proc_liveness(self) -> Optional[str]:
        """Hard death evidence, or None while the process looks alive.
        Checks the OS first (``poll()`` sees a SIGKILL before any RPC
        does) — this is the signal the watchdog's hard-evidence path
        escalates on (pipe EOF / exit code, not just wedged ticks)."""
        if self._dead_evidence is not None:
            return self._dead_evidence
        rc = self._proc.poll()
        if rc is not None:
            self._mark_dead("process exited")
            return self._dead_evidence
        return None

    def _connect(self) -> Dict[str, Any]:
        """Dial the worker's listener and fence a fresh link under the
        NEXT session nonce.  Replaces (and closes) any previous
        transport only AFTER the handshake succeeds, so a failed relink
        attempt leaves the evidence state untouched.  The nonce burns
        even on failure — monotonicity is all the fence needs."""
        self._nonce += 1
        transport, ready = connect_transport(
            "127.0.0.1", self._port, self.incarnation, self._nonce,
            timeout_s=min(self.rpc_timeout_s, DEFAULT_HANDSHAKE_TIMEOUT_S),
            write_timeout_s=self.rpc_timeout_s)
        old, self._transport = self._transport, transport
        if old is not None:
            old.close()
        if ready.get("hb") is not None:
            self.last_heartbeat = int(ready["hb"])
        return ready

    def _mark_link_down(self, evidence: str) -> None:
        """Record LINK evidence: ``poll()`` just said the process is
        alive, only the socket between us died.  The router's relink
        path consumes this; it never feeds the watchdog's hard-death
        escalation until the relink budget is exhausted."""
        if (self._dead_evidence is not None
                or self._link_evidence is not None):
            return
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        self._link_evidence = evidence
        if self._transport is not None:
            self._transport.close()
        METRICS.inc("cluster.net_link_downs")
        obs_trace.event("cluster.net.partition", replica=self.replica_id,
                        incarnation=self.incarnation, nonce=self._nonce,
                        evidence=evidence)
        log.warning("proc replica %d: LINK down, process alive (%s)",
                    self.replica_id, evidence)

    def link_liveness(self) -> Optional[str]:
        """Link-down evidence, or None while the link is up.  Proc
        evidence outranks link evidence — callers (router pump, health
        probe) check ``proc_liveness`` first."""
        return self._link_evidence

    def relink(self) -> bool:
        """Reconnect a down link to the SAME incarnation under a fresh
        session nonce.  Returns True when the link is (now) up.  Budget
        exhaustion converts the outage into hard death evidence of kind
        "link", handing the watchdog/supervisor respawn path the
        replica — 'not DEAD until the relink budget is exhausted'."""
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        if self._dead_evidence is not None:
            return False
        if self.transport_kind != "socket":
            return False
        if self._proc.poll() is not None:
            self._mark_dead("process exited")
            return False
        if self._link_evidence is None:
            return True
        self.relink_attempts += 1
        try:
            self._connect()
        except (WireError, OSError) as e:
            if self.relink_attempts >= self.relink_budget:
                self.death_kind = "link"
                self._mark_dead(
                    f"relink budget exhausted "
                    f"({self.relink_attempts}/{self.relink_budget} "
                    f"attempts): {type(e).__name__}: {e}")
            return False
        healed = self._link_evidence
        self._link_evidence = None
        self.relink_attempts = 0
        self.relinks += 1
        METRICS.inc("cluster.net_relinks")
        obs_trace.event("cluster.net.relink", replica=self.replica_id,
                        incarnation=self.incarnation, nonce=self._nonce,
                        healed=healed)
        log.warning("proc replica %d: relinked (incarnation %d, nonce "
                    "%d) after %s", self.replica_id, self.incarnation,
                    self._nonce, healed)
        return True

    def drop_link(self, halfopen: bool = False) -> None:
        """Sever the parent side of the link WITHOUT touching the
        process — the killer's partition/halfopen fault.  Full partition
        closes the socket (both directions die); halfopen shuts only our
        receive direction (sends still flow), so the failure surfaces as
        the reply that never arrives (``WireTimeout``/EOF), not a send
        error."""
        if self.transport_kind != "socket":
            raise ValueError(
                f"proc replica {self.replica_id}: cannot partition a "
                f"{self.transport_kind!r} transport — a pipe to a child "
                f"cannot die without the child dying (spawn with "
                f"transport='socket')")
        if self._transport is None:
            return
        if halfopen:
            self._transport.shutdown_read()
        else:
            self._transport.close()
        METRICS.inc("cluster.net_partitions")
        log.warning("proc replica %d: link %s injected (nonce %d)",
                    self.replica_id,
                    "half-open" if halfopen else "partition",
                    self._nonce)

    def replayable(self, handle: int) -> bool:
        """Whether a relink replay may re-start this handle: injected
        failed/stalled runs settle locally — replaying them would erase
        their injected outcomes and break soak byte-identity."""
        return handle not in self._failed and handle not in self._stalled

    def link_stats(self) -> Optional[Dict[str, Any]]:
        """Per-link gauges for obs/export.py (socket transports only)."""
        if self.transport_kind != "socket":
            return None
        alive = (self._link_evidence is None
                 and self._dead_evidence is None)
        return {"nonce": self._nonce, "alive": 1 if alive else 0,
                "relinks": self.relinks}

    def _recv_reply(self, req: Dict[str, Any], timeout_s: float
                    ) -> Dict[str, Any]:
        """Receive the reply to ``req`` under ONE overall deadline.

        Pipe mode returns the next frame — the transport is lockstep by
        construction, so any mismatch downstream is a protocol desync.
        Socket mode tolerates what a network can legally do to a fenced
        link: frames tagged with a stale session nonce (a link this
        parent already abandoned) and duplicate deliveries of already-
        consumed ids (netem ``duplicate``) are DISCARDED, never desync
        evidence; a FUTURE id is still a breach."""
        if self.transport_kind != "socket":
            return self._transport.recv(timeout_s=timeout_s)
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise WireTimeout(
                    f"no current-nonce reply to {req['op']} id "
                    f"{req['id']} within {timeout_s}s")
            resp = self._transport.recv(timeout_s=remaining)
            rnonce = resp.get("nonce")
            if rnonce != self._nonce:
                METRICS.inc("cluster.net_stale_replies_discarded")
                log.info("proc replica %d: discarded stale-nonce reply "
                         "(%r != %d)", self.replica_id, rnonce,
                         self._nonce)
                continue
            rid = resp.get("id")
            if isinstance(rid, int) and rid < req["id"]:
                METRICS.inc("cluster.net_dup_replies_discarded")
                continue
            return resp

    def _rpc(self, op: str, timeout_s: Optional[float] = None,
             **fields) -> Dict[str, Any]:
        """One request/response turn.  Raises WorkerError for an error
        the WORKER reported; raises WireError/OSError for transport
        death AFTER recording the evidence (callers on the router path
        catch and go silent; the watchdog owns the verdict).  On a
        socket transport, a wire failure with a LIVE ``poll()`` records
        link evidence instead — relink territory, not respawn."""
        from k8s_llm_rca_tpu.obs import trace as obs_trace
        from k8s_llm_rca_tpu.serve.backend import BudgetError

        if self._dead_evidence is not None:
            raise WireEOF(f"proc replica {self.replica_id} transport "
                          f"already down: {self._dead_evidence}")
        if self._link_evidence is not None:
            raise WireTimeout(
                f"proc replica {self.replica_id} link down (awaiting "
                f"relink): {self._link_evidence}")
        req = dict(fields)
        req["op"] = op
        req["id"] = next(self._ids)
        effective = (timeout_s if timeout_s is not None
                     else self.rpc_timeout_s)
        with obs_trace.span("cluster.proc.rpc", cat="cluster", op=op,
                            replica=self.replica_id) as rpc_span:
            tr = obs_trace.active()
            if self.telemetry and tr is not None:
                # span-context propagation: the worker's serve span
                # parents onto THIS rpc span, so one run's tree spans
                # router -> wire -> worker engine ticks
                req["trace"] = tr.context(parent=rpc_span)
            try:
                self._transport.send(req, timeout_s=effective)
                resp = self._recv_reply(req, effective)
            except (WireError, OSError, ValueError) as e:
                # ValueError: write to a pipe closed mid-Popen teardown
                if (self.transport_kind == "socket"
                        and self._proc.poll() is None):
                    self._mark_link_down(
                        f"{op} rpc failed: {type(e).__name__}: {e}")
                else:
                    self._mark_dead(
                        f"{op} rpc failed: {type(e).__name__}: {e}")
                raise
        self.rpcs += 1
        if resp.get("inc") != self.incarnation:
            # incarnation fence: bytes from a stale worker must never be
            # attributed to this incarnation's runs
            self._mark_dead(
                f"fenced: response incarnation {resp.get('inc')!r} != "
                f"{self.incarnation}")
            raise WireEOF(self._dead_evidence)
        if resp.get("id") != req["id"]:
            self._mark_dead(
                f"protocol desync: response id {resp.get('id')!r} != "
                f"{req['id']}")
            raise WireEOF(self._dead_evidence)
        if resp.get("hb") is not None:
            self.last_heartbeat = int(resp["hb"])
        tel = resp.get("tel")
        if tel is not None:
            # past both fences: this payload provably belongs to this
            # incarnation's worker
            self._ingest_telemetry(tel)
        err = resp.get("err")
        if err is not None:
            if err.get("type") == "BudgetError":
                raise BudgetError(err.get("msg", ""))
            raise WorkerError(
                f"proc replica {self.replica_id} worker {op} failed: "
                f"{err.get('type')}: {err.get('msg')}")
        return resp

    def _ingest_telemetry(self, payload: Dict[str, Any]) -> None:
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        self._tel_more = bool(payload.get("more"))
        tr = obs_trace.active()
        if tr is None:
            return
        n = tr.ingest_remote(self.replica_id, self.incarnation, payload)
        self.telemetry_frames += 1
        self.telemetry_items += n
        if n:
            obs_trace.event("cluster.telemetry.ship",
                            replica=self.replica_id,
                            incarnation=self.incarnation, items=n)

    def drain_telemetry(self, max_frames: int = 64) -> int:
        """Flush the worker's remaining buffered telemetry with
        dedicated ``drain_telemetry`` ops (each polls NO fault sites).
        Called by ``close()`` and by the router's relink-heal path; a
        transport failure mid-drain is swallowed — the at-most-bounded-
        loss contract already covers whatever stayed in the ring.
        Returns the number of items recovered this flush."""
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        if not self.telemetry:
            return 0
        before = self.telemetry_items
        if (self.proc_liveness() is None
                and self.link_liveness() is None):
            for _ in range(max_frames):
                try:
                    self._rpc("drain_telemetry")
                except (WireError, OSError, WorkerError):
                    break
                if not self._tel_more:
                    break
        n = self.telemetry_items - before
        obs_trace.event("cluster.telemetry.drain",
                        replica=self.replica_id,
                        incarnation=self.incarnation, items=n)
        return n

    # -------------------------------------------------------------- backend

    def start(self, prompt: str, opts) -> int:
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.serve.backend import BudgetError
        from k8s_llm_rca_tpu.serve.journal import encode_gen

        if self.kind == "engine":
            # the armed plan lives in THIS process: poll exactly where
            # EngineBackend.start would, so injected runs never reach the
            # worker (and the plan's poll counters match the in-process
            # cluster run draw for draw)
            fault = None
            if inject._ARMED is not None:
                fault = inject._ARMED.poll(inject.SITE_BACKEND)
            if fault is not None and fault.kind == "budget":
                raise BudgetError(
                    f"injected budget fault at {fault.site}[{fault.index}]: "
                    f"no valid output exists under this budget")
            if fault is not None and fault.kind == "error":
                handle = next(self._local_handles)
                self._failed[handle] = (
                    f"injected engine-run failure at "
                    f"{fault.site}[{fault.index}]")
                self._live[handle] = False
                return handle
            if fault is not None and fault.kind == "stall":
                handle = next(self._local_handles)
                self._stalled.add(handle)
                self._live[handle] = False
                return handle
        if self.proc_liveness() is not None:
            # routed here between the process death and the watchdog's
            # verdict: black-hole the run like a request on the wire to
            # a dead box — the failover re-start (same global handle)
            # recovers it on a survivor
            handle = next(self._local_handles)
            self._live[handle] = False
            return handle
        try:
            resp = self._rpc("start", prompt=prompt, gen=encode_gen(opts))
        except (WireError, OSError):
            handle = next(self._local_handles)
            self._live[handle] = False
            return handle
        handle = int(resp["handle"])
        self._live[handle] = True
        return handle

    def pump(self) -> Dict[int, Any]:
        from k8s_llm_rca_tpu.faults import inject
        from k8s_llm_rca_tpu.serve.backend import BackendResult

        results: Dict[int, BackendResult] = {}
        for handle in list(self._failed):
            msg = self._failed.pop(handle)
            if self._live.pop(handle, None) is not None:
                results[handle] = BackendResult("", 0, error=msg)
        if self._stalled and inject._ARMED is not None:
            # EngineBackend.pump's deterministic-deadline discipline: a
            # stalled run ends only via the serve deadline, which must
            # arrive after a fixed number of pumps, not wall seconds
            inject._ARMED.clock.sleep(0.05)
        if self.proc_liveness() is not None:
            return results
        try:
            resp = self._rpc("pump")
        except (WireError, OSError):
            return results
        self._occupancy = float(resp.get("occupancy", 0.0))
        for h_str, r in resp.get("results", {}).items():
            handle = int(h_str)
            if self._live.pop(handle, None) is None:
                continue          # settled after a local cancel: drop
            results[handle] = BackendResult(
                text=r["text"], completion_tokens=r["completion_tokens"],
                prompt_tokens=r.get("prompt_tokens"),
                error=r.get("error"), expired=bool(r.get("expired")))
        return results

    def busy(self, handle: int) -> bool:
        return handle in self._live

    def cancel(self, handle: int) -> None:
        remote = self._live.pop(handle, None)
        self._failed.pop(handle, None)
        self._stalled.discard(handle)
        if not remote or self.proc_liveness() is not None:
            return
        try:
            self._rpc("cancel", handle=handle)
        except (WireError, OSError):
            pass          # dying worker: its state is gone anyway

    def count_tokens(self, text: str) -> int:
        return self._tokenizer.count(text)

    def queue_depth(self) -> int:
        return len(self._live)

    def occupancy(self) -> float:
        return self._occupancy if self.kind == "engine" else 0.0

    def proc_stats(self) -> Dict[str, Any]:
        """Per-process gauges for obs/export.py prometheus_text."""
        return {"pid": self.pid, "incarnation": self.incarnation,
                "alive": 0 if self.proc_liveness() is not None else 1,
                "rpcs": self.rpcs}

    # ------------------------------------------- drain/adopt seam (engine)

    def _snapshot_sequences(self) -> Tuple[Dict[str, Any], List[int]]:
        resp = self._rpc("snapshot")
        return resp["snap"], [int(h) for h in resp["handles"]]

    def _adopt_sequences(self, snap: Dict[str, Any],
                         opts: Sequence[Any]) -> List[int]:
        from k8s_llm_rca_tpu.serve.journal import encode_gen

        resp = self._rpc("adopt", snap=snap,
                         gens=[encode_gen(o) for o in opts])
        handles = [int(h) for h in resp["handles"]]
        for h in handles:
            self._live[h] = True
        return handles

    def _export_run(self, handle: int) -> Optional[Dict[str, Any]]:
        """Per-run EXPORT over the wire (cluster/disagg.py).  A handle
        that is parent-local (injected fault) or no longer live exports
        as None — the run settled between pumps, which is a self-clean
        for the handoff queue, never a retry."""
        if handle < 0 or not self._live.get(handle, False):
            return None
        resp = self._rpc("export_run", handle=handle)
        return resp.get("frame")

    def _adopt_run(self, frame: Dict[str, Any], opts: Any) -> int:
        """Per-run ADOPT over the wire: the worker validates the whole
        frame before touching engine state; a torn frame surfaces here
        as WorkerError(ValueError) with nothing adopted.  The reply
        rides the incarnation(+nonce) fence like every RPC — a late ack
        from a dead incarnation can never register a handle."""
        from k8s_llm_rca_tpu.serve.journal import encode_gen

        resp = self._rpc("adopt_run", frame=frame, gen=encode_gen(opts))
        handle = int(resp["handle"])
        self._live[handle] = True
        return handle

    # ------------------------------------------------------------ lifecycle

    def kill(self) -> None:
        """Real SIGKILL — the ProcKiller fault path.  No teardown, no
        cleanup: the point is that the parent finds out the hard way."""
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        self._proc.wait()         # reap immediately; poll() now has rc

    def close(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown: drain frame -> bounded wait -> TERM ->
        KILL.  Idempotent; never raises over a corpse."""
        from k8s_llm_rca_tpu.obs import trace as obs_trace

        if self._proc.poll() is None and self._dead_evidence is None:
            if self._link_evidence is not None:
                # no link to carry the drain frame: drop the stdin leash
                # instead — the worker exits 0 on leash EOF
                try:
                    self._proc.stdin.close()
                except OSError:
                    pass
            else:
                if self.telemetry:
                    # last flush before the worker exits — the drain
                    # reply below carries one more big payload too
                    self.drain_telemetry()
                try:
                    self._rpc("drain", timeout_s=timeout_s)
                except (WireError, OSError, WorkerError):
                    pass
            try:
                self._proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
        self._reap()
        obs_trace.event("cluster.proc.exit", replica=self.replica_id,
                        rc=self._proc.poll(),
                        incarnation=self.incarnation)

    def _reap(self) -> None:
        try:
            if self._proc.poll() is None:
                self._proc.kill()
            self._proc.wait()
        except Exception:         # noqa: BLE001 — teardown best-effort
            pass
        if self._transport is not None:
            self._transport.close()
        for stream in (self._proc.stdin, self._proc.stdout):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass


class ProcReplica(Replica):
    """A ``Replica`` whose backend lives in its own OS process.

    Presents the exact Replica surface (so ClusterRouter and the
    watchdog plug in unchanged) plus:

    - ``proc_liveness()``: hard death evidence the router's pump skip
      and the watchdog's hard-evidence escalation consume;
    - ``kill_process()``: deliver a real SIGKILL (the ProcKiller path);
    - ``close()``: the graceful drain -> TERM -> KILL ladder;
    - a ``rebuild`` recipe that spawns a FRESH process at incarnation+1
      — ``ReplicaSupervisor.restart`` therefore restarts the actual OS
      process and rejoins it, with the old corpse reaped first.
    """

    def __init__(self, replica_id: int, kind: str = "oracle",
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 rpc_timeout_s: float = DEFAULT_RPC_TIMEOUT_S,
                 **spec: Any):
        if os.environ.get(WORKER_ENV):
            raise ValueError(
                "nested proc-in-proc: a proc worker must not spawn its "
                "own proc replicas (one process boundary per replica; "
                "compose scale with more replicas, not deeper trees)")
        spec = dict(spec, kind=kind, replica_id=replica_id)
        spec.setdefault("incarnation", 0)
        backend = ProcBackend(spec, spawn_timeout_s=spawn_timeout_s,
                              rpc_timeout_s=rpc_timeout_s)

        def _rebuild() -> ProcBackend:
            old = self.backend
            if isinstance(old, ProcBackend):
                old._reap()       # never leak the corpse's pipes/zombie
                next_inc = old.incarnation + 1
            else:
                next_inc = 1
            return ProcBackend(dict(spec, incarnation=next_inc),
                               spawn_timeout_s=spawn_timeout_s,
                               rpc_timeout_s=rpc_timeout_s)

        super().__init__(replica_id, backend, mesh=None, rebuild=_rebuild)

    def healthy(self) -> bool:
        return (super().healthy()
                and self.backend.proc_liveness() is None
                and self.backend.link_liveness() is None)

    def proc_liveness(self) -> Optional[str]:
        return self.backend.proc_liveness()

    def link_liveness(self) -> Optional[str]:
        return self.backend.link_liveness()

    def relink(self) -> bool:
        return self.backend.relink()

    def partition_link(self, halfopen: bool = False) -> None:
        self.backend.drop_link(halfopen=halfopen)

    @property
    def supports_relink(self) -> bool:
        return self.backend.transport_kind == "socket"

    def evidence_kind(self) -> str:
        """``"link"`` when the death verdict came from relink-budget
        exhaustion, ``"proc"`` otherwise (health.hard_kinds)."""
        return self.backend.death_kind or "proc"

    def kill_process(self) -> None:
        self.backend.kill()

    def close(self, timeout_s: float = 5.0) -> None:
        self.backend.close(timeout_s=timeout_s)


def build_proc_replicas(n_replicas: int, kind: str = "oracle",
                        **spec: Any) -> List[ProcReplica]:
    """N out-of-process replicas of one kind.

    ``transport="socket"`` in the spec puts each worker behind a TCP
    loopback listener with session-nonce link fencing (the cross-host
    shape; link death relinks instead of respawning); the default
    ``"pipe"`` keeps the PR 12 stdio protocol byte-identical.

    Loud exclusions (repo convention): proc replicas compose with the
    router/watchdog/supervisor stack, NOT with cross-worker sharding —
    a worker owns its whole engine, so CP/PP/mesh arguments are
    rejected here instead of failing deep in a worker.

    ``layout`` (a ``runtime.rules.SpecLayout`` or its ``to_dict`` form)
    plus ``mesh_shape`` (axis-size dict over data/fsdp/model) give each
    ENGINE worker a per-tier weight layout over its own virtual CPU
    devices: the worker builds the mesh, rule-shards the shared-seed
    params under the layout, and places its KV pool accordingly — the
    proc-fleet face of the per-tier layouts ``build_replicas`` offers
    in-process.  Validated HERE (typo'd axes, non-engine kinds,
    device-count mismatches, fsdp layouts without an fsdp axis) so a
    bad spec fails in the parent, not as a worker spawn corpse.
    """
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    for key in ("mesh", "meshes", "devices_list", "context_parallel",
                "pipeline_parallel", "cp", "pp", "data", "model"):
        if key in spec:
            raise ValueError(
                f"proc replicas do not compose with {key!r}: each worker "
                f"owns its whole single-process engine (CP/PP/submesh "
                f"sharding is the in-process build_replicas path); spawn "
                f"more replicas instead")
    layout = spec.get("layout")
    mesh_shape = spec.get("mesh_shape")
    if layout is not None or mesh_shape is not None:
        from k8s_llm_rca_tpu.runtime.rules import SpecLayout

        if kind != "engine":
            raise ValueError(
                f"layout/mesh_shape compose with kind='engine' proc "
                f"workers only (kind={kind!r} carries no params to lay "
                f"out)")
        if isinstance(layout, SpecLayout):
            layout = spec["layout"] = layout.to_dict()
        if layout is not None:
            SpecLayout.from_dict(layout)      # typo'd axes die parent-side
        shape = dict(mesh_shape or {})
        bad = sorted(set(shape) - {"data", "fsdp", "model"})
        if bad:
            raise ValueError(
                f"proc worker mesh_shape supports data/fsdp/model axes "
                f"only, got {bad}: CP/PP/EP do not compose with proc "
                f"replicas")
        n_dev = 1
        for v in shape.values():
            n_dev *= int(v)
        if int(spec.get("devices", n_dev)) != n_dev:
            raise ValueError(
                f"spec devices={spec.get('devices')} does not match the "
                f"mesh_shape device product {n_dev}")
        spec["devices"] = n_dev
        if (layout or {}).get("fsdp") and shape.get("fsdp", 1) <= 1:
            raise ValueError(
                f"layout maps fsdp to axis {layout['fsdp']!r} but "
                f"mesh_shape carries no fsdp axis > 1: the layout "
                f"requests sharding that cannot happen")
    return [ProcReplica(rid, kind=kind, **spec)
            for rid in range(n_replicas)]


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1:]))
