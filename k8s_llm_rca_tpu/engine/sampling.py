"""On-device token sampling: greedy / temperature / top-k / top-p.

All branches are static-shape and jit-friendly; the per-slot PRNG key is
split on device so a batched decode step stays one fused XLA computation.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => disabled
    top_p: float = 1.0         # 1 => disabled

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    vals, _ = jax.lax.top_k(logits, k)                    # [B, k]
    kth = vals[:, -1:]                                     # [B, 1]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]     # desc
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens whose cumulative prob (exclusive) is < p; always keep top-1
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < p], axis=-1)
    # threshold logit: smallest kept logit per row
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < thresh, NEG_INF, logits)


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] fp32
    key: jax.Array,
    params: SamplingParams,
) -> jnp.ndarray:
    """Return sampled token ids [B].  ``params`` is static (baked into jit)."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.float32(params.temperature)
    if params.top_k > 0:
        scaled = _apply_top_k(scaled, params.top_k)
    if params.top_p < 1.0:
        scaled = _apply_top_p(scaled, params.top_p)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def sample_tokens_masked(
    logits: jnp.ndarray,        # [B, V] fp32
    key: jax.Array,
    params: SamplingParams,
    allow: jnp.ndarray,         # [B, V] bool; True = token permitted
) -> jnp.ndarray:
    """Grammar-constrained variant: disallowed tokens are masked to -inf
    BEFORE top-k/top-p, so the renormalized distribution stays inside the
    grammar (engine/constrain.py builds the masks)."""
    return sample_tokens(jnp.where(allow, logits, NEG_INF), key, params)
