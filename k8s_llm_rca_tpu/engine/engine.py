"""Continuous-batching inference engine.

The reference's serving model is one blocking OpenAI run at a time with an
escalating 5 s poll (common/openai_generic_assistant.py:92-115) — strictly
serial.  This engine replaces it with slot-based continuous batching
(Orca/vLLM-style, re-designed for XLA's static shapes):

- a fixed ``max_batch``-wide KV cache (models/llama.KVCache);
- admission = per-sequence prefill into a free slot, padded to a static
  bucket length (one compile per bucket, cached for the process lifetime);
- every tick runs ONE jitted decode step for ALL active slots; sequences
  join and leave the batch at token granularity;
- completed slots are freed immediately and re-admitted from the pending
  queue the same tick.

Host<->device traffic per tick is one [B] token vector each way — everything
else stays on device.  ``decode_scan`` amortizes even that for throughput
benches by scanning N decode steps on device.

Slot bookkeeping lives here on the host; it is the only writer of slot
indices, which guards the silent-clamp semantics of dynamic_update_slice
(see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import functools
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_rca_tpu.config import EngineConfig, ModelConfig
from k8s_llm_rca_tpu.engine.sampling import (
    SamplingParams, sample_tokens, sample_tokens_masked,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.runtime import profiling
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger
from k8s_llm_rca_tpu.utils.tokenizer import Tokenizer

log = get_logger(__name__)


def host_np(x) -> np.ndarray:
    """Device->host fetch that also works on arrays spanning
    NON-ADDRESSABLE devices (multi-process serving: the global mesh
    covers other processes' devices, so plain ``np.asarray`` raises).
    Fully-addressable values (incl. plain host arrays) fetch directly;
    otherwise every process participates in a ``process_allgather`` —
    safe because the engine's host driver runs SPMD-identically in all
    processes (same prompts, same deterministic schedule), so the
    collective lines up across the cluster.  ONE definition for both
    engines and the speculative path: every per-tick sync routes
    through here."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def flash_prefill_safe(params) -> bool:
    """Whether inference prefill may use the Pallas flash kernel: TPU
    backend and no multi-device (TP/EP) param sharding — pallas_call has
    no SPMD partitioning rule, so a sharded run would silently replicate
    attention on every device (and it has no VJP, but prefill is
    inference-only here)."""
    if jax.default_backend() != "tpu":
        return False
    for leaf in jax.tree.leaves(params):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and getattr(sharding, "num_devices", 1) > 1:
            return False
    return True


def flash_prefill_plan(params, tp_mesh, model_cfg,
                       ep_mesh=None) -> Tuple[bool, object]:
    """(use_flash, flash_mesh) for the prefill jits: the plain Pallas
    kernel when params are unsharded on TPU (flash_prefill_safe), the
    PER-SHARD kernel (ops.flash_attention_sharded under ``tp_mesh``) when
    TP-sharded with head counts divisible by the model axis — sharded
    prefill no longer concedes the kernel to XLA.  (False, None)
    otherwise (CPU, indivisible heads, or EP: MoE prefill shards TOKENS
    over data×expert, a layout the head-sharded shard_map wrapper would
    replicate every layer)."""
    if flash_prefill_safe(params):
        return True, None
    if ep_mesh is not None:
        return False, None
    if (tp_mesh is not None and jax.default_backend() == "tpu"
            and model_cfg.n_heads % tp_mesh.shape["model"] == 0
            and model_cfg.n_kv_heads % tp_mesh.shape["model"] == 0):
        return True, tp_mesh
    return False, None


def params_multi_device(params) -> bool:
    """True when any param leaf carries a >1-device sharding (TP/EP)."""
    for leaf in jax.tree.leaves(params):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and getattr(sharding, "num_devices", 1) > 1:
            return True
    return False


def validate_tp_mesh(tp_mesh, model_cfg, engine_cfg, cp_mesh=None,
                     cp_seq_axis: str = "seq") -> None:
    """TP cache-sharding preconditions: the merged kv axis splits over
    "model" head-aligned (see runtime.sharding.kv_cache_specs) and the
    slot batch over "data".

    CP composes with TP only on ONE mesh carrying both axes (the cache
    takes the composed seq-major × head-minor layout and the ring/Ulysses
    prefill runs per head shard — SURVEY §7 hard part 6); two DIFFERENT
    mesh objects cannot both own the cache."""
    if tp_mesh is None:
        return
    for axis in ("data", "model"):
        if axis not in tp_mesh.shape:
            raise ValueError(f"tp_mesh needs a '{axis}' axis, has "
                             f"{dict(tp_mesh.shape)}")
    if cp_mesh is not None:
        if cp_mesh is not tp_mesh:
            raise ValueError(
                "cp_mesh and tp_mesh must be the SAME composed mesh "
                "(one Mesh carrying 'data', 'model' and the seq axis); "
                "two distinct meshes cannot both lay out the cache")
        if cp_seq_axis not in tp_mesh.shape:
            raise ValueError(f"composed mesh lacks the '{cp_seq_axis}' axis")
        n_tp = tp_mesh.shape["model"]
        if model_cfg.n_heads % n_tp or model_cfg.n_kv_heads % n_tp:
            # the CP attention shards HEADS over "model" (unexpanded GQA
            # KV rides the ring), so both head counts must split evenly
            raise ValueError(
                f"n_heads={model_cfg.n_heads}/n_kv_heads="
                f"{model_cfg.n_kv_heads} not divisible by model axis "
                f"{n_tp} (required for CP×TP prefill)")
    if model_cfg.kv_dim % (2 * tp_mesh.shape["model"]):
        # the factor 2 keeps the nibble-packed int4 layout shardable too
        raise ValueError(
            f"kv_dim={model_cfg.kv_dim} not shardable over model axis "
            f"{tp_mesh.shape['model']}")
    if engine_cfg.max_batch % tp_mesh.shape["data"]:
        raise ValueError(
            f"max_batch={engine_cfg.max_batch} not divisible by data axis "
            f"{tp_mesh.shape['data']}")


def validate_fsdp_mesh(fsdp_mesh, model_cfg, engine_cfg, tp_mesh=None,
                       cp_mesh=None, ep_mesh=None, pp_mesh=None,
                       sp: bool = False) -> None:
    """FSDP serving preconditions (shared by both engines): parameters
    shard along the "fsdp" axis (runtime/rules.py FSDP_LAYOUT — the
    non-TP matmul dim: hidden for the blocks, vocab for the embeddings)
    and GSPMD all-gathers each weight on use, so prefill and decode run
    unchanged and greedy parity is byte-identical.

    Composes with TP on ONE mesh carrying both "fsdp" and "model"
    (fsdp×tp — the 8-virtual-device parity row).  PP/CP/EP and SP are
    refused loudly until their greedy-parity matrix lands: each of those
    modes hand-places weights or activations (stage bodies, ring
    attention, all-to-all dispatch) and would silently gather the full
    weight per device without a proven composition rule.  KV caches never
    shard on fsdp (kv_cache_specs) — only the weights do."""
    if fsdp_mesh is None:
        return
    for axis in ("data", "fsdp", "model"):
        if axis not in fsdp_mesh.shape:
            raise ValueError(f"fsdp_mesh needs a '{axis}' axis, has "
                             f"{dict(fsdp_mesh.shape)}")
    if tp_mesh is not None and tp_mesh is not fsdp_mesh:
        raise ValueError(
            "fsdp_mesh and tp_mesh must be the SAME composed mesh (one "
            "Mesh carrying 'fsdp' and 'model'); two distinct meshes "
            "cannot both lay out the weights")
    for other, what in ((cp_mesh, "CP"), (ep_mesh, "EP"), (pp_mesh, "PP")):
        if other is not None:
            raise ValueError(
                f"fsdp×{what} is unsupported until its greedy-parity "
                f"matrix lands (tests/test_sharding_rules.py): compose "
                f"fsdp with TP only")
    if sp:
        raise ValueError(
            "fsdp×SP is unsupported until its greedy-parity matrix lands: "
            "compose fsdp with TP only")
    n_f = fsdp_mesh.shape["fsdp"]
    for dim, what in ((model_cfg.hidden_size, "hidden_size"),
                      (model_cfg.vocab_size, "vocab_size")):
        if dim % n_f:
            raise ValueError(
                f"{what}={dim} not divisible by fsdp axis {n_f} "
                f"(fsdp shards the hidden/vocab dim of every weight)")
    if engine_cfg.max_batch % fsdp_mesh.shape["data"]:
        raise ValueError(
            f"max_batch={engine_cfg.max_batch} not divisible by data axis "
            f"{fsdp_mesh.shape['data']}")


def validate_replica_mesh(mesh, model_cfg, engine_cfg) -> None:
    """Cluster-replica preconditions (cluster/submesh.py): a replica
    submesh is a plain dp×tp carve of the global device list.  The
    replica axis already multiplies throughput by running N independent
    engines, so any composition whose collectives would have to span
    replicas — CP sequence sharding, PP stages, EP dispatch — is excluded
    loudly at construction rather than silently computing on a submesh
    that cannot see the other replicas' devices."""
    if mesh is None:
        return
    for axis, what in (("seq", "CP"), ("stage", "PP"), ("expert", "EP")):
        if mesh.shape.get(axis, 1) > 1:
            raise ValueError(
                f"{what}×replica is unsupported: a cluster replica owns a "
                f"DISJOINT submesh and its collectives cannot span "
                f"replicas (axis '{axis}'={mesh.shape[axis]}); replica "
                f"submeshes carve dp×tp only (cluster/submesh.py) — run "
                f"{what} inside ONE engine on the full mesh instead")
    validate_tp_mesh(mesh, model_cfg, engine_cfg)
    if mesh.shape.get("fsdp", 1) > 1:
        # dp×fsdp×tp carve (cluster/submesh.py fsdp=): same-mesh compose
        validate_fsdp_mesh(mesh, model_cfg, engine_cfg, tp_mesh=mesh)


def validate_disjoint_submeshes(meshes) -> None:
    """Replica submeshes must not share a single device: two engines
    dispatching onto one chip would serialize (and on TPU fight over the
    chip grant), silently destroying the throughput the cluster layer
    exists to multiply.  Loud ValueError names the overlapping device."""
    seen: Dict[int, int] = {}
    for i, mesh in enumerate(meshes):
        if mesh is None:
            continue
        for d in mesh.devices.flat:
            if d.id in seen:
                raise ValueError(
                    f"replica submeshes overlap: device {d.id} belongs to "
                    f"both replica {seen[d.id]} and replica {i}; carve "
                    f"disjoint contiguous device groups "
                    f"(cluster.carve_replica_meshes)")
            seen[d.id] = i


def validate_ep_mesh(ep_mesh, model_cfg, engine_cfg, cp_mesh,
                     cp_seq_axis: str = "seq") -> None:
    """EP serving preconditions: MoE model; mesh carries "data" and
    "expert" axes; decode batch and prefill buckets divide by the token
    sharding (tokens shard over data*expert, parallel/moe.py).

    CP composes with EP on ONE mesh carrying "data", "expert" and the
    seq axis: CP prefill then shards MoE tokens over (seq, expert) — the
    sequence stays put, dispatch rides the expert axis (models/llama.py
    prefill_kv_cp) — and decode tokens shard over (data, expert) as in
    plain EP, over the seq-sharded cache."""
    if ep_mesh is None:
        return
    if model_cfg.n_experts <= 0:
        raise ValueError("ep_mesh requires an MoE model (n_experts > 0)")
    if cp_mesh is not None and cp_mesh is not ep_mesh:
        raise ValueError(
            "cp_mesh and ep_mesh must be the SAME composed mesh (one "
            "Mesh carrying 'data', 'expert' and the seq axis); two "
            "distinct meshes cannot both lay out the token sharding")
    for axis in ("data", "expert"):
        if axis not in ep_mesh.shape:
            raise ValueError(f"ep_mesh needs a '{axis}' axis, has "
                             f"{dict(ep_mesh.shape)}")
    p_tok = ep_mesh.shape["data"] * ep_mesh.shape["expert"]
    if model_cfg.n_experts % ep_mesh.shape["expert"]:
        raise ValueError(
            f"n_experts={model_cfg.n_experts} not divisible by expert "
            f"axis {ep_mesh.shape['expert']}")
    if engine_cfg.max_batch % p_tok:
        raise ValueError(
            f"max_batch={engine_cfg.max_batch} not divisible by "
            f"data*expert={p_tok} (decode tokens shard over both)")
    if cp_mesh is not None:
        # CP prefill is per-sequence (b=1): its MoE token dim is the
        # padded sequence itself, sharded over (seq, expert)
        p_pref = ep_mesh.shape[cp_seq_axis] * ep_mesh.shape["expert"]
    else:
        p_pref = p_tok
    for b in tuple(engine_cfg.prefill_buckets) + (engine_cfg.max_seq_len,):
        if b % p_pref:
            raise ValueError(
                f"prefill bucket {b} not divisible by the prefill token "
                f"sharding {p_pref}")
    if engine_cfg.paged and engine_cfg.prefix_cache \
            and engine_cfg.page_size % p_tok:
        # the prefix-cache chunked prefill runs at ANY page-multiple width
        # (capped by remaining pages), so every width is divisible only if
        # one page already is — fail at construction, not mid-serve
        raise ValueError(
            f"page_size={engine_cfg.page_size} not divisible by "
            f"data*expert={p_tok}: the prefix-cache chunked prefill can "
            f"emit any page-multiple width; use a larger page_size or "
            f"prefix_cache=False")


def validate_pp_mesh(pp_mesh, model_cfg, engine_cfg, cp_mesh, ep_mesh,
                     tp_mesh, microbatches: Optional[int],
                     stage_axis: str = "stage",
                     params=None) -> Optional[int]:
    """PP serving preconditions (shared by both engines).  Returns the
    resolved microbatch count (None when pp_mesh is None).

    PP composes with TP on ONE mesh carrying "stage" and "model" (the
    multi-host pod topology: stages over DCN, heads/hidden over ICI; the
    stage bodies run the manual-TP block with psum combines —
    parallel/pipeline.py).  Quantized KV composes with PP×TP on both
    engines: the per-token scale is the full-row scale recovered by pmax
    over the TP group (llama._quantize_kv axis_name), so scale caches
    replicate across TP and numerics match the plain quantized paths
    exactly.  Quantized WEIGHTS compose too: int8 payloads shard on the
    weight spec with per-channel scales replicating their reduced dims,
    and int4 payloads are re-packed per shard at the sharding boundary
    ("shard first, pack second") so the stage bodies' shard-local
    dequant is exact — see pipeline.shard_stacked_layers.

    PP composes with EP on ONE mesh carrying "stage" and "expert"
    (Mixtral across pods: stages over DCN, expert dispatch over ICI
    within each stage).  Stage bodies run dense attention on the
    replicated stream and route each expert peer's token slice through
    the shared all-to-all dispatch (parallel/pipeline._moe_mlp_ep);
    PP×TP×EP is not composed (the manual-TP stage block computes a
    dense MLP).  Speculative decoding composes: the verify step runs the
    pipelined multi-token decode (parallel/pipeline.llama_pp_decode_multi
    / paged_pp_decode_multi), so n-gram and draft-model speculation work
    under PP, PP×TP and PP×EP.  CP remains exclusive."""
    if pp_mesh is None:
        return None
    if cp_mesh is not None:
        raise ValueError(
            "pp_mesh and cp_mesh are mutually exclusive by design: "
            "stage-local CP replicates the matmul FLOPs and weight "
            "streaming that stage-local TP divides (1.5-3.6x the FLOPs, "
            "1.2-2.9x the HBM bytes per device at 4k-128k contexts — "
            "runtime.profiling.stage_local_cp_vs_tp and "
            "docs/parallelism.md 'PP×CP: a quantified no'); use PP×TP, "
            "or CP×TP for GQA-limited long contexts")
    if ep_mesh is not None:
        if ep_mesh is not pp_mesh:
            raise ValueError(
                "pp_mesh and ep_mesh must be the SAME composed mesh "
                "(one Mesh carrying 'stage' and 'expert'); two distinct "
                "meshes cannot both lay out the weights")
        if tp_mesh is not None:
            raise ValueError(
                "PP×TP×EP is unsupported (the manual-TP stage block "
                "computes a dense MLP; compose PP×EP or PP×TP)")
        n_ep = ep_mesh.shape["expert"]
        m_ep = microbatches or pp_mesh.shape[stage_axis]
        if (engine_cfg.max_batch // max(1, m_ep)) % n_ep:
            raise ValueError(
                f"PP×EP needs the microbatch size "
                f"{engine_cfg.max_batch}//{m_ep} divisible by the expert "
                f"axis {n_ep} (each expert peer routes a distinct token "
                f"slice of the microbatch)")
    if tp_mesh is not None:
        if tp_mesh is not pp_mesh:
            raise ValueError(
                "pp_mesh and tp_mesh must be the SAME composed mesh "
                "(one Mesh carrying 'stage' and 'model'); two distinct "
                "meshes cannot both lay out the weights and cache")
        n_tp = tp_mesh.shape["model"]
        if (model_cfg.n_heads % n_tp or model_cfg.n_kv_heads % n_tp):
            raise ValueError(
                f"n_heads={model_cfg.n_heads}/n_kv_heads="
                f"{model_cfg.n_kv_heads} not divisible by model axis "
                f"{n_tp} (required for PP×TP stage bodies)")
        if params is not None:
            from k8s_llm_rca_tpu.models.quant import QuantTensor4

            # int8 (QuantTensor) composes: the stacked spec tree expands
            # per-leaf so payloads shard on the weight spec and
            # per-channel scales replicate their reduced dims
            # (pipeline._stacked_in_specs).  int4 composes by PER-SHARD
            # packing: shard_stacked_layers re-packs every column-sharded
            # QuantTensor4 so each TP shard is a self-contained
            # split-half buffer ("shard first, pack second",
            # quant.repack_nibbles_grouped) — which needs every sharded
            # channel dim divisible by 2*n_tp.
            if any(isinstance(leaf, QuantTensor4)
                   for leaf in jax.tree.leaves(
                       params, is_leaf=lambda x: isinstance(
                           x, QuantTensor4))):
                for dim, what in ((model_cfg.q_dim, "q_dim"),
                                  (model_cfg.kv_dim, "kv_dim"),
                                  (model_cfg.intermediate_size,
                                   "intermediate_size")):
                    if dim % (2 * n_tp):
                        raise ValueError(
                            f"PP×TP with int4 weights needs {what}={dim} "
                            f"divisible by 2*model axis={2 * n_tp} "
                            f"(per-shard split-half nibble packing)")
        if model_cfg.n_experts > 0:
            raise ValueError(
                "PP×TP does not support MoE models (the manual-TP stage "
                "block computes a dense MLP; expert-stacked weights need "
                "the EP dispatch, which PP excludes)")
    if stage_axis not in pp_mesh.shape:
        raise ValueError(f"pp_mesh needs a '{stage_axis}' axis, has "
                         f"{dict(pp_mesh.shape)}")
    n_stages = pp_mesh.shape[stage_axis]
    if model_cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={model_cfg.n_layers} not divisible into "
            f"{n_stages} pipeline stages")
    m = microbatches or n_stages
    if engine_cfg.max_batch % m:
        raise ValueError(
            f"max_batch={engine_cfg.max_batch} not divisible into "
            f"{m} PP microbatches")
    return m


def setup_draft(draft_model, model_cfg, engine_cfg):
    """Validate + build the ModelDraft for ``draft_model=(cfg, params)``
    (shared by both engine constructors); None passes through."""
    if draft_model is None:
        return None
    if engine_cfg.speculative_k <= 0:
        raise ValueError("draft_model requires speculative_k > 0 "
                         "(the draft only exists to fill draft slots)")
    dcfg, dparams = draft_model
    if dcfg.vocab_size != model_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {dcfg.vocab_size} != target vocab "
            f"{model_cfg.vocab_size} (draft tokens must be target tokens)")
    from k8s_llm_rca_tpu.engine.speculative import ModelDraft

    return ModelDraft(dcfg, dparams, engine_cfg)


def validate_cp_divisibility(cp_seq_axis: str, n_cp: int, sizes) -> None:
    """CP prefill shards the padded sequence over the mesh axis; every
    prefill bucket (and max_seq_len — paged callers pass page-rounded
    sizes) must split evenly across it.  Shared by both engines."""
    bad = [s for s in sizes if s % n_cp]
    if bad:
        raise ValueError(
            f"cp mesh axis '{cp_seq_axis}' size {n_cp} must divide "
            f"every prefill bucket and max_seq_len; offending sizes: {bad}")


@dataclass
class SequenceResult:
    seq_id: int
    token_ids: List[int]
    text: str
    finish_reason: str          # "stop" | "eos" | "length" | "expired"
    prompt_tokens: int
    completion_tokens: int


@dataclass
class _Active:
    seq_id: int
    slot: int
    prompt_tokens: int
    generated: List[int] = field(default_factory=list)
    max_new_tokens: int = 256
    stop_strings: Tuple[str, ...] = ()
    grammar: Optional[object] = None    # engine/constrain.py FSM (stateful)
    n_shared: int = 0   # leading block-table pages owned by the prefix cache
    # scheduling class (serve.backend.Priority; lower = more urgent):
    # orders admission and preemption-victim selection.  Deadlines live in
    # the engine's _deadlines registry, not on the sequence records.
    priority: int = 1


@dataclass
class _Pending:
    seq_id: int
    prompt_ids: List[int]
    max_new_tokens: int
    stop_strings: Tuple[str, ...]
    grammar: Optional[object] = None
    priority: int = 1


class EngineBase:
    """Shared continuous-batching engine surface.

    Subclasses (contiguous InferenceEngine, paged.PagedInferenceEngine)
    implement ``step()`` and their own slot/cache bookkeeping; everything
    the agent layer sees — submit/generate semantics, prompt clamping,
    finish reasons, stop-string trimming — lives here so the two cache
    designs can't drift apart.
    """

    model_cfg: ModelConfig
    engine_cfg: EngineConfig
    tokenizer: Tokenizer
    # whether _scan_tick can run compiled-DFA grammar slots on device
    # (engine.decode_scan_dfa); the contiguous engine overrides to True
    _dfa_scan: bool = False
    # pipeline-parallel serving (pp_mesh=): admissions route through the
    # batched pipelined prefill, padded to _pp_m microbatch multiples
    _pp: bool = False
    _pp_m: Optional[int] = None
    # draft-model speculation (speculative.ModelDraft); None = n-gram drafts
    _draft = None
    # overlapped hot loop (engine_cfg.host_overlap; docs/performance.md).
    # _inflight: dispatched-but-uncommitted fast-path ticks, oldest first;
    # each entry is {"slots": [(slot, seq_id)...], "toks": device [B],
    # "admits": deferred first-token records}.  _admit_pending: sequences
    # activated this tick whose sampled first token has not crossed to
    # host yet.  _flushed_out: results produced by an out-of-tick flush
    # (cancel/snapshot/fault barrier), surfaced by the next _tick so
    # step() callers never lose them.  All three are lazily re-bound to
    # real lists by the subclass constructors.
    _overlap: bool = False
    _overlap_lag: int = 2
    _inflight: Optional[List[dict]] = None
    _admit_pending: Optional[list] = None
    _flushed_out: Optional[list] = None
    # per-sequence absolute deadlines (seq_id -> time on ``_now``'s
    # clock), lazily created like ``_counts`` so engines without
    # deadlines pay one falsy check per tick.  ``clock``: injectable
    # time() source; None = the armed fault plan's VirtualClock when
    # present, else wall time — the same discipline as faults/ and
    # serve/api.py
    clock = None
    _deadlines: Optional[Dict[int, float]] = None
    # liveness heartbeat (cluster/health.py): ``heartbeat`` is the
    # monotonic tick serial every ``step`` bumps — probe-count liveness
    # stays deterministic under a frozen VirtualClock.  ``heartbeat_t``
    # is the clock stamp of the latest tick, taken only when a watchdog
    # registered this engine (``_hb_stamp``), keeping the unwatched hot
    # path to one falsy check.
    heartbeat: int = 0
    heartbeat_t: float = 0.0
    _hb_stamp: bool = False

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.time()
        if inject._ARMED is not None:
            return inject._ARMED.clock.time()
        return time.time()

    # -------------------------------------------------------- shared api

    def _clamp_prompt(self, prompt_ids: Sequence[int],
                      max_new_tokens: Optional[int]) -> Tuple[List[int], int]:
        """Fit prompt + generation into the per-sequence cache budget.

        First shrink max_new to what the cache can hold after the prompt;
        if the prompt alone overflows, keep its TAIL (the task statement
        sits at the end of RCA prompts) while reserving at least cap//4
        tokens of generation room.  (Long-context CP/ring-attention
        prefill lifts this limit later.)
        """
        max_new = (self.engine_cfg.max_new_tokens
                   if max_new_tokens is None else max_new_tokens)
        prompt_ids = list(prompt_ids)
        cap = self.engine_cfg.max_seq_len
        if len(prompt_ids) + max_new + 1 > cap:
            reserve = min(max_new, max(1, cap // 4))
            budget = cap - reserve - 1
            if len(prompt_ids) > budget:
                log.warning(
                    "truncating prompt %d -> %d tokens (cache cap %d)",
                    len(prompt_ids), budget, cap)
                had_bos = prompt_ids[0] == self.tokenizer.bos_id
                prompt_ids = prompt_ids[-budget:]
                if had_bos:   # keep BOS conditioning after tail-truncation
                    prompt_ids[0] = self.tokenizer.bos_id
            max_new = min(max_new, cap - len(prompt_ids) - 1)
        return prompt_ids, max_new

    @property
    def has_work(self) -> bool:
        return bool(self._active or self._pending)

    def submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: Optional[int] = None,
        stop_strings: Sequence[str] = (),
        grammar: Optional[object] = None,
        priority: int = 1,
        deadline_s: Optional[float] = None,
    ) -> int:
        """Queue a sequence; returns its seq_id.  Non-blocking.

        ``grammar``: optional constrain.py FSM owned by this sequence; the
        engine consults it every tick (forced tokens / logit masks).
        ``priority``: scheduling class (serve.backend.Priority; lower =
        more urgent) ordering admission and victim selection.
        ``deadline_s``: seconds from now on the injectable clock; past it
        the tick loop reaps the sequence (finish_reason "expired", pages
        freed the same tick — never held until the client polls)."""
        seq_id = next(self._seq_counter)
        prompt_ids, max_new = self._clamp_prompt(prompt_ids, max_new_tokens)
        self._register(seq_id, prompt_ids)
        self._enqueue(
            _Pending(seq_id, prompt_ids, max_new, tuple(stop_strings),
                     grammar, priority=int(priority)))
        if deadline_s is not None:
            self._deadline_set(seq_id, self._now() + float(deadline_s))
        return seq_id

    def _deadline_set(self, seq_id: int, deadline: float) -> None:
        if self._deadlines is None:
            self._deadlines = {}
        self._deadlines[seq_id] = float(deadline)

    def _enqueue(self, req: "_Pending", front: bool = False) -> None:
        """Deterministic priority insert into the pending queue: stable
        FIFO within a class (submission order is the tiebreak), lower
        ``priority`` ints ahead.  ``front=True`` (preemption requeue)
        puts the request ahead of its OWN class — a preempted sequence
        resumes before un-admitted peers, preserving the paged engine's
        always-makes-progress invariant.  All-NORMAL traffic degenerates
        to exactly the old append / insert(0) behavior."""
        pri = req.priority
        for i, r in enumerate(self._pending):
            if (r.priority > pri) if not front else (r.priority >= pri):
                self._pending.insert(i, req)
                return
        self._pending.append(req)

    def _reap_deadlines(self) -> List["SequenceResult"]:
        """Retire every sequence whose deadline has passed — called at
        the top of each tick, BEFORE the flush drain, so the expired
        results surface from the same ``step()``.  Pages/slots free NOW
        (the eager half of the serve-layer timeout: an expired run must
        not hold pool pages until its client polls).  Disarmed path cost:
        one falsy-dict check."""
        if not self._deadlines:
            return []
        now = self._now()
        expired = [sid for sid, dl in self._deadlines.items() if now >= dl]
        if not expired:
            return []
        self._overlap_barrier()   # commit in-flight tokens before retiring
        out: List[SequenceResult] = []
        for seq_id in expired:
            self._deadlines.pop(seq_id, None)
            done = False
            for i, req in enumerate(self._pending):
                if req.seq_id == seq_id:
                    del self._pending[i]
                    out.append(self._expired_result(seq_id, req))
                    self._drop_spill(seq_id)
                    self._prompts.pop(seq_id, None)
                    resumed = getattr(self, "_resumed", None)
                    if resumed is not None:
                        resumed.pop(seq_id, None)
                    done = True
                    break
            if not done:
                for slot, st in list(self._active.items()):
                    if st.seq_id == seq_id:
                        out.append(self._retire(slot, "expired"))
                        done = True
                        break
            if not done:
                res = self._expire_extra(seq_id)
                if res is not None:
                    out.append(res)
                    done = True
            if done:
                self._count("engine.deadline_expirations")
        return out

    def _expired_result(self, seq_id: int,
                        req: "_Pending") -> "SequenceResult":
        """Terminal result for a sequence that expired while QUEUED: its
        record is whatever it had generated before preemption (possibly
        nothing) — mirroring what snapshot_sequences exports for pending
        entries."""
        resumed = getattr(self, "_resumed", None) or {}
        gen = list(resumed.get(seq_id, ()))
        prompt = list(self._prompts.get(seq_id, req.prompt_ids))
        return SequenceResult(
            seq_id=seq_id, token_ids=list(gen),
            text=self._final_text(gen, "expired", req.stop_strings),
            finish_reason="expired", prompt_tokens=len(prompt),
            completion_tokens=len(gen))

    def _expire_extra(self, seq_id: int) -> Optional["SequenceResult"]:
        """Subclass hook: reap a deadline-expired sequence living outside
        the pending/active books (the paged engine's chunked-prefill
        slots)."""
        return None

    def _drop_spill(self, seq_id: int) -> None:
        """Subclass hook: discard a sequence's host-spilled KV record (no
        pages to free on the base engine)."""

    def _register(self, seq_id: int, prompt_ids: List[int]) -> None:
        """Subclass hook called once per submitted sequence."""

    def cancel_seq(self, seq_id: int) -> bool:
        """Abort a sequence NOW: a queued request leaves the pending list,
        an active one retires its slot immediately (the paged engine frees
        its pages through the normal ``_retire`` path, so an abandoned run
        cannot leak allocator blocks).  No result is produced — callers
        that already dropped the handle simply never see one.  Returns
        whether the sequence was still live."""
        self._overlap_barrier()   # commit in-flight tokens before retiring
        for i, req in enumerate(self._pending):
            if req.seq_id == seq_id:
                del self._pending[i]
                self._drop_spill(seq_id)
                if self._deadlines:
                    self._deadlines.pop(seq_id, None)
                self._prompts.pop(seq_id, None)
                resumed = getattr(self, "_resumed", None)
                if resumed is not None:
                    resumed.pop(seq_id, None)
                return True
        for slot, st in list(self._active.items()):
            if st.seq_id == seq_id:
                self._retire(slot, "cancelled")
                return True
        return False

    # ------------------------------------------------- snapshot / restore

    def snapshot_sequences(self) -> Dict[str, object]:
        """Export every live sequence's durable state for crash recovery
        (serve/recover.py, docs/durability.md).

        Raw KV is deliberately NOT dumped: pages are device memory laid
        out per-engine, worthless across a restart.  What IS durable —
        original prompt ids, every generated token (pre-preemption prefix
        included), remaining budget, stop strings, and the engine RNG key
        — is exactly what ``restore_sequences`` needs to re-admit the
        sequence through a normal prefill; with the prefix cache enabled
        the re-prefill of already-seen tokens is a mostly-HIT path.

        Grammar FSM state is exported as a bool marker only (compiled
        FSMs are stateful host objects); restore rebuilds it by advancing
        a freshly compiled FSM over the generated tokens.

        Ordering is the scheduler's own priority: active sequences (in
        admission order) first, then the pending queue front-to-back —
        restoring preserves relative progress order deterministically.
        """
        # the overlapped hot loop may hold 1-2 dispatched-but-uncommitted
        # tokens per slot; commit them first so st.generated is complete
        # (the single invalidation point durability rides through)
        self._overlap_barrier()
        resumed = getattr(self, "_resumed", None) or {}
        seqs = []
        for st in sorted(self._active.values(), key=lambda s: s.seq_id):
            gen = list(resumed.get(st.seq_id, [])) + list(st.generated)
            seqs.append({
                "seq_id": st.seq_id,
                "prompt_ids": list(self._prompts.get(st.seq_id, [])),
                "generated": gen,
                # an _Active at its budget retires within the same tick,
                # so between ticks remaining >= 1 always holds; the max()
                # mirrors _preempt_slot's defensive clamp
                "remaining_new_tokens": max(
                    1, st.max_new_tokens - len(st.generated)),
                "stop_strings": list(st.stop_strings),
                "grammar": st.grammar is not None,
                "priority": st.priority,
                "deadline": (self._deadlines or {}).get(st.seq_id),
            })
        for req in self._pending:
            gen = list(resumed.get(req.seq_id, ()))
            # a preempted request's prompt_ids already carry its generated
            # prefix; recover the ORIGINAL prompt from _prompts.  A
            # KV-spilled sequence (paged engine) sits in this queue too,
            # so it snapshots as exactly its token record — the spill
            # buffers themselves are process-local device-layout memory
            # and are never serialized
            prompt = list(self._prompts.get(req.seq_id, req.prompt_ids))
            seqs.append({
                "seq_id": req.seq_id,
                "prompt_ids": prompt,
                "generated": gen,
                "remaining_new_tokens": req.max_new_tokens,
                "stop_strings": list(req.stop_strings),
                "grammar": req.grammar is not None,
                "priority": req.priority,
                "deadline": (self._deadlines or {}).get(req.seq_id),
            })
        key = jax.device_get(self._key)
        return {"rng_key": [int(x) for x in key], "sequences": seqs}

    def restore_sequences(self, snap: Dict[str, object],
                          grammars: Optional[Dict[int, object]] = None
                          ) -> List[int]:
        """Re-admit sequences exported by ``snapshot_sequences`` — into
        this engine or a fresh same-model one.  Each sequence is queued
        for a normal prefill of prompt + generated-so-far (the paged
        preemption/resume path, ``_preempt_slot``), so the engine's
        greedy-parity guarantees carry over: a restored sequence finishes
        with exactly the tokens a never-interrupted run produces.

        ``grammars``: freshly compiled FSMs keyed by seq_id for sequences
        snapshotted with ``grammar: true``; each is advanced over the
        generated tokens so its state matches the resume point.  Missing
        a required FSM raises (loud exclusion) rather than silently
        dropping the constraint.  Returns the restored seq_ids.
        """
        self._overlap_barrier()
        resumed = getattr(self, "_resumed", None)
        if resumed is None:
            raise ValueError(
                f"{type(self).__name__} has no resume bookkeeping "
                f"(_resumed); restore_sequences requires an engine built "
                f"with preemption/resume support")
        cap = self.engine_cfg.max_seq_len
        restored: List[int] = []
        max_seen = -1
        for s in snap["sequences"]:
            seq_id = int(s["seq_id"])
            if (seq_id in self._prompts
                    or any(r.seq_id == seq_id for r in self._pending)):
                raise ValueError(
                    f"restore collision: seq {seq_id} is already live in "
                    f"this engine")
            prompt = [int(t) for t in s["prompt_ids"]]
            gen = [int(t) for t in s["generated"]]
            remaining = int(s["remaining_new_tokens"])
            room = cap - len(prompt) - len(gen) - 1
            if room < 1:
                raise ValueError(
                    f"seq {seq_id} needs {len(prompt) + len(gen) + 2} "
                    f"cache positions but this engine caps at {cap}; "
                    f"restore into an engine with max_seq_len >= the "
                    f"snapshotting engine's")
            remaining = min(remaining, room)
            g = (grammars or {}).get(seq_id)
            if s.get("grammar") and g is None:
                raise ValueError(
                    f"seq {seq_id} was grammar-constrained; pass a "
                    f"freshly compiled FSM via grammars={{{seq_id}: fsm}} "
                    f"(FSM state is rebuilt by advancing over the "
                    f"generated tokens, never serialized)")
            if g is not None:
                for t in gen:
                    g.advance(t)
            self._register(seq_id, prompt)
            if gen:
                resumed[seq_id] = list(gen)
            self._enqueue(_Pending(
                seq_id, prompt + gen, remaining,
                tuple(s["stop_strings"]), g,
                priority=int(s.get("priority", 1))))
            if s.get("deadline") is not None:
                self._deadline_set(seq_id, float(s["deadline"]))
            restored.append(seq_id)
            max_seen = max(max_seen, seq_id)
        # later submits must not reuse a restored id
        nxt = next(self._seq_counter)
        self._seq_counter = itertools.count(max(nxt, max_seen + 1))
        key = snap.get("rng_key")
        if key is not None:
            self._key = jnp.asarray(key, dtype=jnp.uint32)
        return restored

    # ------------------------------------------- per-run export / adopt

    def _export_entry(self, req: "_Pending",
                      resumed: Dict[int, List[int]]) -> Dict[str, object]:
        """One pending sequence as a ``snapshot_sequences``-shaped entry
        (the handoff frame's durable half, cluster/disagg.py)."""
        return {
            "seq_id": req.seq_id,
            "prompt_ids": list(self._prompts.get(req.seq_id,
                                                 req.prompt_ids)),
            "generated": list(resumed.get(req.seq_id, ())),
            "remaining_new_tokens": req.max_new_tokens,
            "stop_strings": list(req.stop_strings),
            "grammar": req.grammar is not None,
            "priority": req.priority,
            "deadline": (self._deadlines or {}).get(req.seq_id),
        }

    def export_run(self, seq_id: int
                   ) -> Optional[Tuple[Dict[str, object],
                                       Optional[Dict[str, object]]]]:
        """Per-run EXPORT half of the disaggregated handoff: freeze ONE
        sequence and return ``(entry, kv_record)`` — the snapshot-shaped
        token entry plus (paged engine only) the host page record of its
        computed KV.  The sequence STAYS live here, pinned in the pending
        queue with its spill record, until the adopter acks and the
        caller cancels it (RELEASE) — so a death anywhere mid-handoff
        leaves a re-runnable source, never a torn sequence.

        Returns None when the run is not exportable THIS pump (base
        engine: actively decoding — it will settle here instead; paged:
        mid-chunked-prefill or holding uncommitted first tokens).  A
        settled/unknown seq_id raises.
        """
        self._overlap_barrier()
        resumed = getattr(self, "_resumed", None)
        for req in self._pending:
            if req.seq_id == seq_id:
                return self._export_entry(req, resumed or {}), None
        for st in self._active.values():
            if st.seq_id == seq_id:
                # the base engine cannot preempt mid-decode; let the run
                # settle locally — the handoff queue self-cleans
                return None
        raise ValueError(f"export_run: seq {seq_id} is not live")

    def adopt_run(self, entry: Dict[str, object], kv=None,
                  grammar=None) -> int:
        """Per-run ADOPT half: re-admit ONE exported entry (optionally
        with its KV page record — ignored on the base engine, which
        re-prefills byte-identically).  Returns the seq_id adopted."""
        sid = int(entry["seq_id"])
        self.restore_sequences(
            {"rng_key": None, "sequences": [entry]},
            grammars={sid: grammar} if grammar is not None else None)
        return sid

    # -------------------------------------------------- fault injection

    FAULT_SITE = inject.SITE_ENGINE_TICK

    def _tick_fault(self) -> None:
        """Apply this tick's scheduled fault (faults/plan.py).  Only ever
        called behind ``inject._ARMED is not None`` at the top of
        ``step()`` — the disarmed hot path pays exactly that one check."""
        plan = inject._ARMED
        if plan is None:
            return
        fault = plan.poll(self.FAULT_SITE)
        if fault is not None:
            # fault kinds that preempt/crash slots must see committed
            # host state, not a 1-2 token stale mirror
            self._overlap_barrier()
            self._apply_tick_fault(fault, plan)

    def _apply_tick_fault(self, fault, plan) -> None:
        """Base engine tick faults: host stall (virtual-clock delay).  The
        paged engine overrides to add allocator exhaustion and forced
        preemption waves; page-pool kinds scheduled against the contiguous
        engine are ignored with a warning (no pool to exhaust)."""
        if fault.kind in ("stall", "slow"):
            plan.clock.sleep(fault.delay_s or 0.05)
        elif fault.kind in ("oom", "preempt", "crash"):
            log.warning("tick fault %r ignored: contiguous engine has no "
                        "preemption/requeue machinery", fault.kind)
        else:
            log.warning("tick fault %r not applicable to engine ticks",
                        fault.kind)

    # ------------------------------------------------ grammar application

    def _grammar_first_token(self, grammar, logits, sampled: int,
                             remaining: int) -> int:
        """Constrain the first post-prefill token.  Resampling goes through
        the same device sampler as every later token (identical
        temperature/top-k/top-p semantics); admission is per-sequence, so
        the extra [1, V] sample costs one small dispatch once per
        sequence."""
        c = grammar.constraint(remaining)
        if c.force is not None:
            return c.force
        if c.allow is not None and not bool(c.allow[sampled]):
            self._key, sub = jax.random.split(self._key)
            masked = self._sample_masked(
                logits, sub, self.sampling, jnp.asarray(c.allow[None]))
            return int(self._fetch(masked)[0][0])
        return sampled

    def _budget_remaining(self, st: _Active) -> int:
        """Tokens this sequence can still emit: min of its max_new budget
        and the cache capacity left (both can trigger 'length').  Pure host
        arithmetic — prompt_tokens + generated tracks the device length
        (one behind mid-tick, which only closes the grammar one token
        early)."""
        cache_room = (self.engine_cfg.max_seq_len
                      - (st.prompt_tokens + len(st.generated)) - 1)
        return min(st.max_new_tokens - len(st.generated), cache_room)

    def _tick_constraints(self, active_slots, n_slots: int, vocab: int):
        """Collect per-slot constraints for this tick.  Returns
        (forced {slot: token}, allow [B, V] bool or None)."""
        forced = {}
        allow = None
        for slot in active_slots:
            st = self._active[slot]
            if st.grammar is None:
                continue
            c = st.grammar.constraint(self._budget_remaining(st))
            if c.force is not None:
                forced[slot] = c.force
            elif c.allow is not None:
                if allow is None:
                    allow = np.ones((n_slots, vocab), bool)
                allow[slot] = c.allow
        return forced, allow

    # -------------------------------------------------- tick + observability

    # per-engine mirror of the engine.* METRICS counters (lazily created):
    # the tick timeline reads THIS, not the process-global METRICS, so a
    # traced run's gauges are a pure function of the engine's own activity
    # even when METRICS carries other engines'/tests' history
    _counts: Optional[Dict[str, float]] = None

    # cluster attribution (cluster/replica.py): the replica id this engine
    # serves under, None outside a cluster.  When set, engine.tick spans
    # carry a ``replica`` arg and TickSample.engine_id routes the Chrome
    # counter tracks onto a per-replica tid — attribution rides existing
    # span names, so the SITES registry stays closed.
    obs_replica: Optional[int] = None
    # router-written gauges (cluster/router.py writes queue_depth /
    # occupancy before pumping this replica); mirrored into TickSample so
    # the router's view rides the same per-tick recorder as pool pressure
    _cluster_gauges: Optional[Dict[str, float]] = None

    def _count(self, name: str, value: float = 1.0) -> None:
        """Increment a counter in METRICS and this engine's private
        mirror (both cheap; the mirror is a plain dict add)."""
        METRICS.inc(name, value)
        c = self._counts
        if c is None:
            c = self._counts = {}
        c[name] = c.get(name, 0.0) + value

    # ---------------------------------------- overlapped hot loop (shared)
    #
    # docs/performance.md is the design note.  Invariants enforced here:
    #  - host commit order per sequence is exactly the plain engine's
    #    (admission first token, then decode tokens in dispatch order);
    #  - _inflight entries only exist across fast-path ticks; every other
    #    path (grammar, speculation, chunked scan, cancel, snapshot,
    #    restore, faults) flushes FIRST, so it observes committed state;
    #  - a slot retired/preempted while its tokens were in flight simply
    #    drops them at flush (the seq_id guard below) — greedy re-prefill
    #    regenerates identical tokens, so parity is preserved.

    def _fetch(self, *arrays) -> Tuple[np.ndarray, ...]:
        """ONE coalesced device->host sync: start async copies for every
        device array, then materialize all of them.  Counted as a single
        ``engine.d2h_syncs`` when any input actually lives on device —
        the counter measures sync POINTS (each costs one ~0.25 s tunnel
        round-trip regardless of payload count), not arrays moved."""
        if any(not isinstance(a, np.ndarray) for a in arrays):
            self._count("engine.d2h_syncs")
        for a in arrays:
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                start()
        return tuple(host_np(a) for a in arrays)

    def _overlap_fast(self) -> bool:
        """Whether THIS tick may dispatch without waiting to commit (the
        one-tick-lagged fast path).  Chunked-scan engines amortize host
        work in-scan already; speculation and live/queued grammar slots
        need host tokens (drafts, FSM masks) before the next dispatch, so
        they take the flush-first synchronous path — per the tentpole
        contract, grammar forces sync per-batch composition, never by
        disabling overlap globally."""
        if not self._overlap:
            return False
        cfg = self.engine_cfg
        if cfg.decode_chunk > 1 or cfg.speculative_k > 0:
            return False
        if any(st.grammar is not None for st in self._active.values()):
            return False
        if any(r.grammar is not None for r in self._pending):
            return False
        return True

    def _defer_first(self, st: _Active, first_dev, idx: int) -> None:
        """Queue an admitted sequence's on-device first token; the host
        value lands at the next drain/flush (one coalesced fetch for ALL
        admissions instead of one blocking fetch per admission group)."""
        self._admit_pending.append((st, first_dev, idx))

    def _take_admit_pending(self) -> list:
        pend, self._admit_pending = self._admit_pending, []
        return pend

    def _note_first_token(self, slot: int, token: int,
                          update_dev: bool) -> None:
        """Subclass hook: reflect an admission's first committed token
        into the engine's token state.  ``update_dev`` is False when the
        commit happens at a lagged flush — the device array has already
        advanced past the first token, so only host mirrors may move."""

    def _commit_first(self, st: _Active, token: int,
                      update_dev: bool = True) -> Optional[SequenceResult]:
        """Host-side commit of an admission's first token (the deferred
        half of _activate).  ``update_dev=False`` at a lagged flush: the
        device token array has advanced past the first token, so only
        host mirrors may move.  The liveness guard drops the token when
        the slot was preempted before the fetch landed: the requeued
        prompt then re-prefills and greedily re-samples the SAME token,
        so nothing is lost (docs/performance.md)."""
        live = self._active.get(st.slot) is st
        if not live:
            return None
        st.generated.append(token)
        self._note_first_token(st.slot, token, update_dev=update_dev)
        reason = self._finish_reason(st, token, st.prompt_tokens)
        if reason is not None:
            return self._retire(st.slot, reason)
        return None

    def _drain_admission_commits(self) -> List[SequenceResult]:
        """Fetch every deferred admission first token in ONE sync and
        commit them in admission order."""
        pend = self._take_admit_pending()
        if not pend:
            return []
        uniq: Dict[int, int] = {}
        order = []
        for _, a, _ in pend:
            if id(a) not in uniq:
                uniq[id(a)] = len(order)
                order.append(a)
        hosts = self._fetch(*order)
        out: List[SequenceResult] = []
        for st, a, i in pend:
            r = self._commit_first(st, int(hosts[uniq[id(a)]][i]))
            if r is not None:
                out.append(r)
        return out

    def _note_flush_entry(self, entry: dict) -> None:
        """Subclass hook, called once per flushed entry BEFORE its commits
        (the paged engine decrements its per-slot in-flight counters)."""

    def _overlap_post_commit(self, slot: int, token: int) -> None:
        """Subclass hook: per-token host-mirror update during a lagged
        flush commit (the paged engine advances lengths/cur_tokens)."""

    def _overlap_flush(self) -> List[SequenceResult]:
        """Commit every in-flight fast-path tick: one coalesced fetch for
        all entries' token vectors + deferred admission firsts, then the
        plain commit loop per entry in dispatch order.  Safe to call any
        time; a no-op when nothing is in flight."""
        entries, self._inflight = self._inflight, []
        finished: List[SequenceResult] = []
        if entries:
            uniq: Dict[int, int] = {}
            order = []
            for e in entries:
                for a in [e["toks"]] + [rec[1] for rec in e["admits"]]:
                    if id(a) not in uniq:
                        uniq[id(a)] = len(order)
                        order.append(a)
            hosts = self._fetch(*order)
            for e in entries:
                self._note_flush_entry(e)
                for st, a, i in e["admits"]:
                    r = self._commit_first(st, int(hosts[uniq[id(a)]][i]),
                                           update_dev=False)
                    if r is not None:
                        finished.append(r)
                toks_host = hosts[uniq[id(e["toks"])]]
                # only slots still owned by the sequence that was active
                # at dispatch time commit; retired/preempted slots' tokens
                # are dropped (see class invariants above)
                slots = [s for s, sid in e["slots"]
                         if s in self._active
                         and self._active[s].seq_id == sid]
                finished.extend(self._commit_scanned(
                    slots, toks_host[None, :], 1,
                    self._overlap_post_commit))
        finished.extend(self._drain_admission_commits())
        return finished

    def _overlap_barrier(self) -> None:
        """Flush outside a tick (cancel/snapshot/restore/fault).  Results
        finished by the flush are stashed and surfaced by the NEXT tick,
        so step() callers never lose them."""
        if self._inflight or self._admit_pending:
            out = self._overlap_flush()
            if out:
                self._flushed_out.extend(out)
            self._invalidate_device_state()

    def _invalidate_device_state(self) -> None:
        """Subclass hook — the single invalidation point: host mirrors
        changed behind the device-resident cache, re-upload before the
        next dispatch.  No-op for engines whose token state IS the device
        array (contiguous) and for the plain path."""

    def step(self) -> List[SequenceResult]:
        """One engine tick (the public pump surface): apply this tick's
        scheduled fault, run the subclass tick body (``_tick``), and —
        only when a tracer is active — wrap the tick in an
        ``engine.tick`` span and record a TickSample of the scheduler/
        pool gauges.  The untraced, disarmed, unwatched hot path pays
        exactly two module-slot identity checks plus the heartbeat bump
        (one int add and one falsy check)."""
        self.heartbeat += 1                    # liveness tick serial
        if self._hb_stamp:                     # unwatched cost: this check
            self.heartbeat_t = self._now()
        if inject._ARMED is not None:          # disarmed cost: this check
            self._tick_fault()
        tr = obs_trace._ACTIVE
        if tr is None:                         # untraced cost: this check
            return self._tick()
        targs = ({} if self.obs_replica is None
                 else {"replica": self.obs_replica})
        with tr.span("engine.tick", cat="engine", **targs):
            finished = self._tick()
        self._record_tick(tr)
        return finished

    def _tick(self) -> List[SequenceResult]:
        raise NotImplementedError

    def _tick_gauges(self) -> Dict[str, Optional[int]]:
        """Scheduler gauges for the tick timeline; the paged engine
        overrides to add pool pressure (free/evictable pages)."""
        crit = norm = batch = 0
        for r in self._pending:
            if r.priority <= 0:
                crit += 1
            elif r.priority == 1:
                norm += 1
            else:
                batch += 1
        return {"running": len(self._active),
                "queued": len(self._pending),
                "queued_critical": crit, "queued_normal": norm,
                "queued_batch": batch,
                "free_pages": None, "evictable_pages": None}

    def _record_tick(self, tr) -> None:
        from k8s_llm_rca_tpu.obs.timeline import TickSample

        g = self._tick_gauges()
        c = self._counts or {}
        tl = tr.timeline
        tl.record(TickSample(
            tick=tl.total, ts=tr.now(),
            running=g["running"], queued=g["queued"],
            free_pages=g["free_pages"],
            evictable_pages=g["evictable_pages"],
            prefill_tokens=c.get("engine.prefill_tokens", 0.0),
            decode_tokens=c.get("engine.decode_tokens", 0.0),
            prefix_hit_tokens=c.get("engine.prefix_hit_tokens", 0.0),
            preemptions=c.get("engine.preemptions", 0.0),
            admission_rejections=c.get("engine.admission_rejections",
                                       0.0),
            h2d_uploads=c.get("engine.h2d_uploads", 0.0),
            d2h_syncs=c.get("engine.d2h_syncs", 0.0),
            dispatches=c.get("engine.dispatches", 0.0),
            prefill_chunks=c.get("engine.prefill_chunks", 0.0),
            spilled_pages=c.get("engine.spilled_pages", 0.0),
            restored_pages=c.get("engine.restored_pages", 0.0),
            deadline_expirations=c.get("engine.deadline_expirations", 0.0),
            prefix_hits_l0=c.get("engine.prefix_hits_l0", 0.0),
            prefix_hits_l1=c.get("engine.prefix_hits_l1", 0.0),
            prefix_hits_l2=c.get("engine.prefix_hits_l2", 0.0),
            prefix_demotions=c.get("engine.prefix_demotions", 0.0),
            prefix_promoted_pages=c.get("engine.prefix_promoted_pages",
                                        0.0),
            prefix_bytes_restored=c.get("engine.prefix_bytes_restored",
                                        0.0),
            prefix_store_misses_remote=c.get(
                "engine.prefix_store_misses_remote", 0.0),
            prefix_watermark_demotions=c.get(
                "engine.prefix_watermark_demotions", 0.0),
            idle_ticks=c.get("engine.idle_ticks", 0.0),
            queued_critical=g.get("queued_critical", 0),
            queued_normal=g.get("queued_normal", 0),
            queued_batch=g.get("queued_batch", 0),
            engine_id=self.obs_replica or 0,
            cluster_queue_depth=(self._cluster_gauges or {}).get(
                "queue_depth", 0.0),
            cluster_occupancy=(self._cluster_gauges or {}).get(
                "occupancy", 0.0)))

    # ---------------------------------------- chunked scan tick (shared)

    def _chunk_bound(self, slot: int) -> int:
        """Subclass hook: extra per-slot cap on the scan chunk (the paged
        engine bounds by distance to the slot's next page boundary)."""
        return self.engine_cfg.decode_chunk

    def _dfa_device_tables(self, tables):
        """Upload one grammar's DFA tables once; reuse across scans."""
        dev_cache = getattr(self, "_dfa_dev", None)
        if dev_cache is None:
            dev_cache = self._dfa_dev = {}
        dev = dev_cache.get(id(tables))
        if dev is None:
            dev = (jnp.asarray(tables.allow), jnp.asarray(tables.token_next),
                   jnp.asarray(tables.dist), jnp.asarray(tables.close_tok),
                   jnp.asarray(tables.complete), tables)
            # bound device-table residency (the tuple keeps `tables` alive,
            # so id() cannot be reused while an entry lives)
            while len(dev_cache) >= 4:
                dev_cache.pop(next(iter(dev_cache)))
            dev_cache[id(tables)] = dev
        return dev

    def _dfa_scan_vectors(self, tables):
        """[B] DFA state + remaining-budget vectors for a scan batch:
        grammar slots carry their state, free slots the FREE row."""
        b = self.engine_cfg.max_batch
        states = np.full((b,), tables.free_state, np.int32)
        remaining = np.full((b,), np.int32(1 << 30), np.int32)
        for slot, st in self._active.items():
            if st.grammar is not None:
                states[slot] = st.grammar.state
                remaining[slot] = self._budget_remaining(st)
        return states, remaining

    _DFA_FUSE_BUCKET = 1024   # fused state-count rounding (compile reuse)

    def _scan_dfa_setup(self):
        """Fused DFA tables + per-slot state/budget vectors for this tick.

        DISTINCT compiled grammars fuse into ONE scan state space: each
        table's states are relabeled by a fixed offset (token_next entries
        are in-table state ids, so adding the offset keeps every
        transition inside its own region), the [S_i, V] tables stack along
        the state axis, and each slot's scan state carries its table's
        offset.  A mixed batch — e.g. planner, Cypher-skeleton and
        reporter schemas in flight at once from different sweep workers —
        then decodes inside one jitted scan instead of degrading to
        per-token host ticks.  The stacked size rounds up to
        ``_DFA_FUSE_BUCKET`` with dead rows (never indexed) so distinct
        grammar combinations share scan compilations.

        Returns None when no grammar slot is active, else
        ((allow, next, dist, close, complete) device arrays,
        states [B] int32, remaining [B] int32)."""
        tabs, seen = [], set()
        for st in self._active.values():
            if st.grammar is not None:
                t = st.grammar.tables
                if id(t) not in seen:
                    seen.add(id(t))
                    tabs.append(t)
        if not tabs:
            return None
        tabs.sort(key=id)
        key = tuple(id(t) for t in tabs)
        cache = getattr(self, "_dfa_fused", None)
        if cache is None:
            cache = self._dfa_fused = {}
        entry = cache.get(key)
        if entry is not None:
            cache[key] = cache.pop(key)   # LRU refresh: the hot combo must
            # survive one-shot per-incident skeleton combos churning by
        if entry is None:
            offsets, off = {}, 0
            allow, nxt, dist, close, complete = [], [], [], [], []
            for t in tabs:
                offsets[id(t)] = off
                allow.append(t.allow)
                nxt.append(t.token_next.astype(np.int32) + np.int32(off))
                dist.append(t.dist)
                close.append(t.close_tok)
                complete.append(t.complete)
                off += t.n_states
            v = allow[0].shape[1]
            pad = -(-off // self._DFA_FUSE_BUCKET) * self._DFA_FUSE_BUCKET \
                - off
            if pad:
                allow.append(np.zeros((pad, v), bool))
                nxt.append(np.zeros((pad, v), np.int32))
                dist.append(np.zeros((pad,), np.int32))
                close.append(np.zeros((pad,), np.int32))
                complete.append(np.zeros((pad,), bool))
            entry = ((jnp.asarray(np.concatenate(allow)),
                      jnp.asarray(np.concatenate(nxt)),
                      jnp.asarray(np.concatenate(dist)),
                      jnp.asarray(np.concatenate(close)),
                      jnp.asarray(np.concatenate(complete))),
                     offsets, tabs[0].free_state, tuple(tabs))
            # bound device residency; the kept tabs tuple pins id()s
            while len(cache) >= 4:
                cache.pop(next(iter(cache)))
            cache[key] = entry
        dev, offsets, free, _pin = entry
        b = self.engine_cfg.max_batch
        states = np.full((b,), free, np.int32)
        remaining = np.full((b,), np.int32(1 << 30), np.int32)
        for slot, st in self._active.items():
            if st.grammar is not None:
                states[slot] = (offsets[id(st.grammar.tables)]
                                + st.grammar.state)
                remaining[slot] = self._budget_remaining(st)
        return dev, states, remaining

    def _grammar_post_commit(self, slot: int, token: int) -> None:
        """Keep host grammar FSMs in lockstep with scan-emitted tokens."""
        st = self._active.get(slot)
        if st is not None and st.grammar is not None:
            st.grammar.advance(token)

    def _scan_chunk(self) -> int:
        """Device decode steps to run in ONE dispatch this tick.

        The scan path amortizes per-dispatch host latency over many
        steps; only an interpreted (non-DFA) grammar forces stepwise
        ticks (it needs per-token host masks).  Mixed DFA grammars fuse
        into one scan state space (_scan_dfa_setup).  Queued admissions
        force stepwise ticks only when ``prompt_admission`` is set:
        admission happens at the next step() either way, so by default
        draining the queue with per-token ticks would only add dispatches
        (pathological on dispatch-latency-dominated hosts), but on
        directly-attached chips the knob trades those cheap dispatches
        for up to decode_chunk-1 steps of TTFT.  The chunk is the
        largest power of two <=
        decode_chunk that fits every slot's CACHE headroom and subclass
        bound; per-slot token budgets deliberately do NOT bound it (DFA
        slots force-close in-scan, plain slots' over-decoded tokens are
        never committed — see the inline comment), and stop strings/EOS
        inside a chunk are trimmed after the fact, same text semantics
        as the stepwise path."""
        limit = self.engine_cfg.decode_chunk
        if limit <= 1:
            return 1
        if self.engine_cfg.prompt_admission and self._pending:
            return 1       # admit promptly: a retirement frees a slot within
            # one step instead of up to decode_chunk-1 steps (config knob —
            # low-dispatch-latency hosts only)
        for slot, st in self._active.items():
            if st.grammar is not None:
                t = getattr(st.grammar, "tables", None)
                if t is None or not self._dfa_scan:
                    return 1           # interpreted FSM: per-token host work
            # bound by CACHE headroom (never write past max_seq_len), NOT
            # by the slot's token budget: DFA slots enforce budgets
            # in-scan (the `remaining` vector force-closes), and a plain
            # slot's tokens past its budget are simply never committed
            # (_commit_scanned stops at the finish reason).  Min-ing the
            # budget here let any near-finished straggler collapse the
            # whole batch's chunk to 1 — with B staggered short-budget
            # runs, SOME slot is almost always in its tail, so the scan
            # degenerated to per-token dispatches exactly when the batch
            # was busiest (observed on the shared-engine sweep).
            headroom = self.engine_cfg.max_seq_len - (
                st.prompt_tokens + len(st.generated))
            limit = min(limit, max(1, headroom), self._chunk_bound(slot))
        chunk = 1
        while chunk * 2 <= limit:
            chunk *= 2
        return chunk

    def _commit_scanned(self, active_slots, toks_host, chunk: int,
                        post_commit=None) -> List[SequenceResult]:
        """Shared commit loop for scanned tokens: append, per-token finish
        check at the stepwise-equivalent device length (prompt +
        len(generated) - 1), metrics, mid-chunk retirement.  ``post_commit``
        lets a subclass update its host-side length/token arrays per
        commit."""
        finished: List[SequenceResult] = []
        for slot in active_slots:
            st = self._active[slot]
            base_len = st.prompt_tokens + len(st.generated)
            committed = 0
            reason = None
            for j in range(chunk):
                token = int(toks_host[j, slot])
                st.generated.append(token)
                committed += 1
                if post_commit is not None:
                    post_commit(slot, token)
                reason = self._finish_reason(st, token, base_len + j)
                if reason is not None:
                    break
            self._count("engine.decode_tokens", committed)
            if reason is not None:
                finished.append(self._retire(slot, reason))
        return finished

    def run_to_completion(self) -> List[SequenceResult]:
        """Pump until queue and slots drain; returns all finished sequences."""
        out: List[SequenceResult] = []
        while self.has_work:
            out.extend(self.step())
        return out

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
        stop_strings: Sequence[str] = (),
    ) -> List[SequenceResult]:
        """Batch convenience: submit all, pump, return in submit order."""
        ids = [self.submit(p, max_new_tokens, stop_strings) for p in prompts]
        results = {r.seq_id: r for r in self.run_to_completion()}
        return [results[i] for i in ids]

    # ------------------------------------------------- shared termination

    def _finish_reason(self, st: _Active, token: int,
                       length: int) -> Optional[str]:
        if token == self.tokenizer.eos_id:
            return "eos"
        if len(st.generated) >= st.max_new_tokens:
            return "length"
        if length + 1 >= self.engine_cfg.max_seq_len:
            return "length"
        if st.stop_strings:
            # decode only a bounded tail window: a token covers >= 1 char,
            # so a window of max_stop_chars + 8 tokens always contains any
            # stop string that just completed (avoids O(n^2) re-decoding).
            # _stop_context (not st.generated directly) so a stop string
            # spanning a preemption/resume boundary is still seen.
            window = max(len(s) for s in st.stop_strings) + 8
            text = self.tokenizer.decode(self._stop_context(st)[-window:])
            for s in st.stop_strings:
                if s in text:
                    return "stop"
        return None

    def _stop_context(self, st: _Active) -> List[int]:
        """Tokens eligible for stop-string matching, with any
        pre-preemption/pre-restore generation prepended so matches can
        span a resume boundary."""
        resumed = getattr(self, "_resumed", None)
        if resumed:
            prefix = resumed.get(st.seq_id)
            if prefix:
                return prefix + st.generated
        return st.generated

    def _final_text(self, generated: List[int], reason: str,
                    stop_strings: Tuple[str, ...]) -> str:
        text = self.tokenizer.decode(generated)
        if reason == "eos":
            text = self.tokenizer.decode(generated[:-1])
        elif reason == "stop":
            for s in stop_strings:
                idx = text.find(s)
                if idx >= 0:
                    text = text[:idx]
                    break
        return text

    # --------------------------------------------- speculative decoding

    def _spec_room_ok(self, slot: int, t: int, lengths_host) -> bool:
        """Subclass hook: whether slot can take a T-token write this tick."""
        return int(lengths_host[slot]) + t <= self.engine_cfg.max_seq_len

    def _speculation_applies(self) -> bool:
        """Speculate only when exact-equivalence is guaranteed and every
        slot has cache room for the full T = k+1 token write."""
        k = self.engine_cfg.speculative_k
        if k <= 0 or self.engine_cfg.temperature != 0.0:
            return False
        # ONE device sync per tick (free on the paged engine: its lengths
        # mirror is host numpy, which _fetch passes through uncounted)
        (lengths_host,) = self._fetch(self.lengths)
        return all(self._spec_room_ok(s, k + 1, lengths_host)
                   for s in self._active)

    def _greedy_with_grammar(self, st: _Active, greedy_token: int,
                             logits_row) -> int:
        """The token a plain greedy tick would commit: grammar force /
        allow-mask applied to argmax, identically to the regular path.
        ``logits_row`` is fetched lazily — only grammar slots pay for it."""
        if st.grammar is None:
            return greedy_token
        c = st.grammar.constraint(self._budget_remaining(st))
        if c.force is not None:
            return c.force
        if c.allow is not None:
            masked = np.where(np.asarray(c.allow), host_np(logits_row),
                              -np.inf)
            return int(np.argmax(masked))
        return greedy_token

    def _build_drafts(self, active_slots, cur_host
                      ) -> Tuple[np.ndarray, Dict[int, List[int]]]:
        """Per-slot draft proposals: (tokens_in [B, k+1], drafts {slot:
        draft}).  Drafts come from the draft MODEL when one is attached
        (constructor ``draft_model=``), else n-gram prompt lookup."""
        from k8s_llm_rca_tpu.engine.speculative import ngram_draft

        k_spec = self.engine_cfg.speculative_k
        tokens_in = np.zeros((self.engine_cfg.max_batch, k_spec + 1),
                             np.int32)
        drafts: Dict[int, List[int]] = {}
        if self._draft is not None:
            for slot in active_slots:
                st = self._active[slot]
                ctx = (self._prompts.get(st.seq_id, [])
                       + self._stop_context(st))
                self._draft.sync(slot, st.seq_id, ctx)
            drafts = self._draft.draft(active_slots, k_spec,
                                       self.tokenizer.eos_id)
            for slot in active_slots:
                tokens_in[slot, 0] = cur_host[slot]
                d = drafts[slot]
                tokens_in[slot, 1:1 + len(d)] = d
            return tokens_in, drafts
        for slot in active_slots:
            st = self._active[slot]
            # _stop_context (not st.generated) so a resumed sequence's
            # pre-preemption tokens keep the lookup context contiguous
            ctx = self._prompts.get(st.seq_id, []) + self._stop_context(st)
            d = ngram_draft(ctx, self.engine_cfg.speculative_ngram, k_spec)
            drafts[slot] = d
            tokens_in[slot, 0] = cur_host[slot]
            tokens_in[slot, 1:1 + len(d)] = d
        return tokens_in, drafts

    def _uniform_dfa_tables(self):
        """The single DFA table set shared by ALL grammar slots, or None
        (no grammar slots, an interpreted FSM, or mixed tables).  When
        non-None, grammar work can run fully on device — the scan tick
        and the speculative verify both key off this."""
        tables = None
        for st in self._active.values():
            if st.grammar is None:
                continue
            t = getattr(st.grammar, "tables", None)
            if t is None:
                return None
            if tables is None:
                tables = t
            elif t is not tables:
                return None
        return tables

    def _verify_and_commit(self, active_slots, drafts, greedy_host,
                           logits_host, post_commit=None,
                           constrained: bool = False
                           ) -> List[SequenceResult]:
        """Shared draft verification: commit the longest prefix of each
        slot's draft that agrees with the model's own greedy (grammar-
        constrained) choice, plus one bonus token from the first
        disagreeing position.  Greedy-exact by construction.

        ``constrained``: the greedy choices were already grammar-
        constrained ON DEVICE (dfa_greedy_multi) — skip the host-side
        re-application (the FSM still advances per commit, which also
        validates the device transition)."""
        finished: List[SequenceResult] = []
        for slot in active_slots:
            st = self._active[slot]
            draft = drafts[slot]
            base_len = st.prompt_tokens + len(st.generated)
            committed: List[int] = []
            reason = None
            for j in range(len(draft) + 1):
                if constrained:
                    token = int(greedy_host[slot, j])
                else:
                    token = self._greedy_with_grammar(
                        st, int(greedy_host[slot, j]),
                        logits_host[slot, j]
                        if logits_host is not None else None)
                st.generated.append(token)
                if st.grammar is not None:
                    st.grammar.advance(token)
                committed.append(token)
                if post_commit is not None:
                    post_commit(slot, token)
                # cache now holds j+1 more tokens than before this commit:
                # tokens_in[0..j] are written; token itself is written on a
                # LATER tick (same as the regular path's current token)
                reason = self._finish_reason(st, token, base_len + j)
                accepted = (reason is None and j < len(draft)
                            and token == draft[j])
                if not accepted:
                    break
            self._count("engine.decode_tokens", len(committed))
            self._count("engine.spec_drafted", len(draft))
            self._count("engine.spec_accepted", max(0, len(committed) - 1))
            if reason is not None:
                finished.append(self._retire(slot, reason))
            elif self._draft is not None:
                self._draft.advance(slot, st.seq_id, committed)
        return finished

    def _need_spec_logits(self, active_slots) -> bool:
        # full logits cross the host boundary only when a grammar slot
        # needs a masked argmax (32000x smaller transfer otherwise)
        return any(self._active[s].grammar is not None
                   for s in active_slots)

    def _spec_constrained_greedy(self, greedy, logits, active_slots):
        """Shared verify-tick grammar handling: when every grammar slot
        shares one compiled DFA, re-derive the greedy choices CONSTRAINED
        on device (dfa_greedy_multi — spec×grammar keeps multi-token
        verify with no [B, T, V] transfer); otherwise fall back to the
        host path (ship logits, _greedy_with_grammar per position).
        Returns (greedy_host [B, T], logits_host or None, constrained)."""
        if not self._need_spec_logits(active_slots):
            return self._fetch(greedy)[0], None, False
        tables = self._uniform_dfa_tables()
        if tables is None:
            greedy_host, logits_host = self._fetch(greedy, logits)
            return greedy_host, logits_host, False
        (allow_t, next_t, dist_t, close_t, complete_t,
         _) = self._dfa_device_tables(tables)
        states, remaining = self._dfa_scan_vectors(tables)
        greedy = self._spec_dfa_greedy(
            logits, jnp.asarray(states), jnp.asarray(remaining),
            self.tokenizer.eos_id, allow_t, next_t, dist_t, close_t,
            complete_t)
        return self._fetch(greedy)[0], None, True


class InferenceEngine(EngineBase):
    """Single-host engine over one model replica (sharded or not)."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        params,
        tokenizer: Tokenizer,
        cp_mesh=None,
        cp_seq_axis: str = "seq",
        cp_mode: str = "ring",
        ep_mesh=None,
        tp_mesh=None,
        fsdp_mesh=None,
        pp_mesh=None,
        pp_microbatches: Optional[int] = None,
        pp_stage_axis: str = "stage",
        sp: bool = False,
        draft_model=None,
        prefix_store=None,
    ):
        """``draft_model``: optional (ModelConfig, params) of a small
        draft Llama (same vocabulary) — speculation then drafts with the
        model instead of n-gram prompt lookup (engine/speculative.py
        ModelDraft; requires ``speculative_k > 0``).  A distilled
        checkpoint (rca/distill.py) is the intended source.

        ``cp_mesh``: optional Mesh with a ``cp_seq_axis`` axis — prefill
        then runs context-parallel over it (long-context mode; the axis
        size must divide every prefill bucket and max_seq_len, validated
        below).  ``cp_mode``: "ring" (ppermute KV rotation) or "ulysses"
        (head<->seq all-to-all).  The KV cache is placed SEQUENCE-sharded
        over the same axis, so each device stores 1/P of a long context's
        KV; decode runs over the sharded cache via GSPMD-partitioned
        attention (combine collectives inserted per step).

        ``ep_mesh``: optional Mesh with "data" and "expert" axes — every
        MoE MLP (prefill AND decode) dispatches through the all-to-all
        expert-parallel path (parallel/moe.py) with experts sharded over
        "expert" (BASELINE configs[3]: Mixtral EP serving).  Requires an
        MoE model and token counts divisible by the mesh (validated
        below).

        ``sp``: Megatron-style sequence parallelism inside the TP prefill
        — the residual stream between matmul regions seq-shards over
        "model" (llama._sp_constrain), so norms/elementwise stop
        replicating across the TP group.  Requires ``tp_mesh``; the CP
        modes already seq-shard activations their own way (exclusive).

        ``fsdp_mesh``: optional Mesh with an "fsdp" axis — parameters
        arrive sharded along it (runtime/rules.py FSDP_LAYOUT; the non-TP
        matmul dim splits) and GSPMD all-gathers each weight on use in
        both prefill and decode.  Composes with TP on the SAME mesh
        (fsdp×tp); PP/CP/EP/sp are refused loudly (validate_fsdp_mesh).
        The KV cache never shards on fsdp."""
        if cp_mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown cp_mode {cp_mode!r}")
        if sp and (tp_mesh is None or cp_mesh is not None
                   or pp_mesh is not None):
            raise ValueError("sp=True (Megatron sequence parallelism) "
                             "requires tp_mesh, is exclusive with cp_mesh "
                             "(CP already seq-shards activations), and is "
                             "unsupported on the PP paths (the pipelined "
                             "prefill/decode do not thread sp_mesh)")
        if engine_cfg.host_overlap and cp_mesh is not None:
            raise ValueError(
                "host_overlap=True is unsupported with cp_mesh: CP admits "
                "per-sequence through prefill_cp and its multi-process "
                "host_np collectives must line up SPMD-identically across "
                "processes — a lagged commit would reorder them.  Run CP "
                "engines with host_overlap=False")
        if engine_cfg.prefill_chunk_budget:
            raise ValueError(
                "prefill_chunk_budget is a paged-engine feature: the "
                "contiguous cache has no chunked prefix-prefill path to "
                "spread a prompt across ticks (its prefill writes one "
                "monolithic slot slice).  Use paged=True "
                "(PagedInferenceEngine) or prefill_chunk_budget=0")
        if engine_cfg.max_spilled_pages:
            raise ValueError(
                "max_spilled_pages (KV spill-to-host preemption) requires "
                "the paged engine: the contiguous cache has no page pool "
                "to spill from and never preempts.  Use paged=True "
                "(PagedInferenceEngine) or max_spilled_pages=0")
        if (engine_cfg.prefix_host_pages or engine_cfg.prefix_disk_dir
                or engine_cfg.prefix_disk_pages or prefix_store is not None):
            raise ValueError(
                "the tiered prefix cache (prefix_host_pages / "
                "prefix_disk_dir / prefix_disk_pages / a shared "
                "prefix_store) requires the paged engine: the contiguous "
                "cache has no page pool to demote prefix pages from or "
                "promote them into.  Use paged=True "
                "(PagedInferenceEngine) or leave the tier knobs unset")
        if engine_cfg.prefix_hbm_watermark:
            raise ValueError(
                "prefix_hbm_watermark (pressure-driven prefix demotion) "
                "requires the paged engine: the contiguous cache has no "
                "page allocator whose free count could dip below a "
                "watermark.  Use paged=True (PagedInferenceEngine) or "
                "prefix_hbm_watermark=0")
        if engine_cfg.prefix_store_writethrough:
            raise ValueError(
                "prefix_store_writethrough requires the paged engine "
                "and a store: the contiguous cache has no prefix pages "
                "to publish.  Use paged=True (PagedInferenceEngine) or "
                "prefix_store_writethrough=False")
        if cp_mesh is not None:
            validate_cp_divisibility(
                cp_seq_axis, cp_mesh.shape[cp_seq_axis],
                tuple(engine_cfg.prefill_buckets)
                + (engine_cfg.max_seq_len,))
        validate_ep_mesh(ep_mesh, model_cfg, engine_cfg, cp_mesh,
                         cp_seq_axis)
        validate_tp_mesh(tp_mesh, model_cfg, engine_cfg, cp_mesh,
                         cp_seq_axis)
        validate_fsdp_mesh(fsdp_mesh, model_cfg, engine_cfg, tp_mesh=tp_mesh,
                           cp_mesh=cp_mesh, ep_mesh=ep_mesh, pp_mesh=pp_mesh,
                           sp=sp)
        self._pp_m = validate_pp_mesh(pp_mesh, model_cfg, engine_cfg,
                                      cp_mesh, ep_mesh, tp_mesh,
                                      pp_microbatches, pp_stage_axis,
                                      params=params)
        self._pp = pp_mesh is not None
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.params = params
        self.tokenizer = tokenizer
        self._draft = setup_draft(draft_model, model_cfg, engine_cfg)
        if self._draft is not None:
            # the draft model's own token fetch is a real sync point
            self._draft.on_sync = (
                lambda: self._count("engine.d2h_syncs"))
        self.sampling = SamplingParams(
            temperature=engine_cfg.temperature,
            top_k=engine_cfg.top_k,
            top_p=engine_cfg.top_p,
        )

        b = engine_cfg.max_batch
        if engine_cfg.kv_cache_dtype not in (None, "int8", "int4"):
            raise ValueError(
                f"unsupported kv_cache_dtype {engine_cfg.kv_cache_dtype!r} "
                f"(None, 'int8' or 'int4')")
        self.cache = llama.init_cache(
            model_cfg, b, engine_cfg.max_seq_len,
            kv_dtype={"int8": jnp.int8, "int4": "int4", None: None}[
                engine_cfg.kv_cache_dtype])
        if pp_mesh is not None and tp_mesh is not None:
            # PP×TP composed serving: the cache's LAYER axis shards over
            # "stage" AND its merged kv axis over "model" — each device
            # holds its stage's layers × its TP shard's kv heads.  The
            # spec comes from the pipeline module so the placement and
            # the shard_map in/out specs cannot drift.  Quantized scale
            # caches shard layer-over-stage and REPLICATE across model
            # (every TP shard writes the identical pmax full-row scale).
            from k8s_llm_rca_tpu.parallel.pipeline import (
                kv_cache_stage_specs, kv_scale_stage_specs,
            )
            from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

            kv_spec = kv_cache_stage_specs("model", pp_stage_axis)
            sc_spec = (kv_scale_stage_specs(pp_stage_axis) if self.cache.quantized
                       else None)
            self.cache = shard_pytree(
                self.cache,
                llama.KVCache(kv_spec, kv_spec, sc_spec, sc_spec), pp_mesh)
        elif tp_mesh is not None and cp_mesh is not None:
            # CP×TP composed serving (one mesh, validated above): the
            # cache takes the seq-major × head-minor layout — S over the
            # seq axis, the merged kv axis over "model", slots over
            # "data".  Prefill rides the TP-aware ring/Ulysses below;
            # decode needs no custom kernel (GSPMD partitions attention
            # over BOTH axes and inserts the combines)
            from k8s_llm_rca_tpu.runtime.sharding import (
                kv_cache_cp_specs, shard_pytree,
            )

            kv_spec, scale_spec = kv_cache_cp_specs(cp_seq_axis, "model",
                                                    "data")
            self.cache = shard_pytree(
                self.cache,
                llama.KVCache(kv_spec, kv_spec, scale_spec, scale_spec),
                tp_mesh)
        elif tp_mesh is not None or fsdp_mesh is not None:
            # place the cache sharded from the start (merged kv axis over
            # "model", slots over "data") so each device holds 1/P of the
            # KV bytes — the real memory win of serving TP.  fsdp never
            # shards KV (rules.kv_cache_specs): an fsdp-only mesh places
            # the cache on the same device set as the weights with the
            # "model" axis degenerate, so GSPMD keeps cache and gathered
            # weights co-resident
            from jax.sharding import PartitionSpec as _P

            from k8s_llm_rca_tpu.runtime.sharding import (
                kv_cache_specs, shard_pytree,
            )

            kv_spec = kv_cache_specs()
            self.cache = shard_pytree(
                self.cache,
                llama.KVCache(kv_spec, kv_spec,
                              _P(None, "data", None), _P(None, "data", None)),
                tp_mesh if tp_mesh is not None else fsdp_mesh)
        elif cp_mesh is not None:
            # context-parallel serving: the cache's SEQUENCE axis shards
            # over the CP mesh, so a context too large for one chip's HBM
            # spreads its KV across the ring.  Prefill already computes
            # context-parallel (ring/Ulysses); decode needs no custom
            # kernel — GSPMD partitions the attention reduction over S
            from k8s_llm_rca_tpu.runtime.sharding import (
                kv_cache_cp_specs, shard_pytree,
            )

            kv_spec, scale_spec = kv_cache_cp_specs(cp_seq_axis)
            self.cache = shard_pytree(
                self.cache,
                llama.KVCache(kv_spec, kv_spec, scale_spec, scale_spec),
                cp_mesh)
        elif pp_mesh is not None:
            # PP serving: the cache's LAYER axis shards over "stage" so
            # each device holds only its stage's layers' KV — the cache
            # half of the per-stage split (weights below)
            from k8s_llm_rca_tpu.parallel.pipeline import (
                kv_cache_stage_specs, kv_scale_stage_specs,
            )
            from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

            kv_spec = kv_cache_stage_specs()
            sc_spec = kv_scale_stage_specs(pp_stage_axis)
            self.cache = shard_pytree(
                self.cache,
                llama.KVCache(kv_spec, kv_spec, sc_spec, sc_spec), pp_mesh)
        self.lengths = jnp.zeros((b,), jnp.int32)
        self.cur_tokens = jnp.zeros((b,), jnp.int32)
        self._key = jax.random.PRNGKey(engine_cfg.seed)
        # overlapped hot loop state (EngineBase machinery)
        self._overlap = engine_cfg.host_overlap
        self._inflight = []
        self._admit_pending = []
        self._flushed_out = []
        # fused-step clamp: retired slots keep advancing until the flush
        # notices; their writes stay inside row capacity and are
        # overwritten by any re-admission's prefill before first attended
        self._overlap_cap = engine_cfg.max_seq_len - 1

        self._free_slots = list(range(b))
        self._active: Dict[int, _Active] = {}       # slot -> state
        self._pending: List[_Pending] = []
        self._seq_counter = itertools.count()

        pp_decode_fn = None
        if pp_mesh is not None:
            # PP serving: weights restacked [P, L/P, ...] and sharded over
            # "stage" (each device holds ONE stage's layers); self.params
            # becomes (non-layer params, stacked layers) — every PP entry
            # point unpacks the pair, and the stacked tree travels as a jit
            # ARGUMENT (a closure would inline the weights as constants).
            from k8s_llm_rca_tpu.parallel import pipeline as pp

            pp_tp_axis = "model" if tp_mesh is not None else None
            pp_ep_axis = "expert" if ep_mesh is not None else None
            n_stages = pp_mesh.shape[pp_stage_axis]
            stacked = pp.shard_stacked_layers(
                pp.stack_llama_stages(params, n_stages), pp_mesh,
                pp_stage_axis, cfg=model_cfg, tp_axis=pp_tp_axis,
                ep_axis=pp_ep_axis)
            light = {k: v for k, v in params.items() if k != "layers"}
            self.params = (light, stacked)
            m = self._pp_m

            def _pp_prefill_batch(cfg, params_t, cache, toks, lens, slots):
                p, stk = params_t
                return pp.llama_pp_prefill(cfg, p, cache, toks, lens,
                                           pp_mesh, m, pp_stage_axis, stk,
                                           slots, tp_axis=pp_tp_axis,
                                           ep_axis=pp_ep_axis)

            def pp_decode_fn(cfg, params_t, cache, toks, lens):
                p, stk = params_t
                return pp.llama_pp_decode_step(cfg, p, cache, toks, lens,
                                               pp_mesh, m, pp_stage_axis,
                                               stk, tp_axis=pp_tp_axis,
                                               ep_axis=pp_ep_axis)

            self._prefill = None        # PP admits through the batched path
            self._prefill_batch = jax.jit(_pp_prefill_batch, static_argnums=0)
        elif cp_mesh is not None:
            # composed CP×TP names "model" so the ring/all-to-all runs per
            # head shard instead of all-gathering TP-sharded heads;
            # composed CP×EP threads ep_mesh so MoE MLPs dispatch over
            # (seq, expert) instead of densifying
            cp_head_axis = "model" if tp_mesh is not None else None

            def _prefill_cp(cfg, params, cache, toks, n, slot):
                return llama.prefill_cp(cfg, params, cache, toks, n, slot,
                                        cp_mesh, cp_seq_axis, cp_mode,
                                        cp_head_axis, ep_mesh)

            self._prefill = jax.jit(_prefill_cp, static_argnums=0)
        else:
            # fsdp-sharded weights exclude the per-shard flash kernel (the
            # head-sharded shard_map would consume a weight shard as if it
            # were the full tensor) — the XLA path with GSPMD all-gathers
            # serves fsdp/fsdp×tp prefill
            use_flash, flash_mesh = flash_prefill_plan(
                params, None if fsdp_mesh is not None else tp_mesh,
                model_cfg, ep_mesh)
            sp_mesh = tp_mesh if sp else None
            self._prefill = jax.jit(
                functools.partial(llama.prefill, use_flash=use_flash,
                                  ep_mesh=ep_mesh, flash_mesh=flash_mesh,
                                  sp_mesh=sp_mesh),
                static_argnums=0)
            self._prefill_batch = jax.jit(
                functools.partial(llama.prefill_batch, use_flash=use_flash,
                                  ep_mesh=ep_mesh, flash_mesh=flash_mesh,
                                  sp_mesh=sp_mesh),
                static_argnums=0)
        # batched admission needs the plain prefill path (prefill_cp is
        # per-sequence)
        self._batch_admission = cp_mesh is None
        self._decode = jax.jit(
            pp_decode_fn if pp_decode_fn is not None
            else functools.partial(llama.decode_step, ep_mesh=ep_mesh),
            static_argnums=0)
        # fused overlapped step (engine.overlap_step): decode + key split
        # + sample + length advance in ONE dispatch.  The in-jit
        # jax.random.split computes the identical subkey stream as the
        # host split in the plain tick, so sampled tokens match exactly.
        self._overlap_decode = jax.jit(
            functools.partial(overlap_step, ep_mesh=ep_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 6, 7))
        if pp_mesh is not None:
            def _verify_step(cfg, params_t, cache, tokens, lengths):
                p, stk = params_t
                return pp.llama_pp_decode_multi(
                    cfg, p, cache, tokens, lengths, pp_mesh, self._pp_m,
                    pp_stage_axis, stk, tp_axis=pp_tp_axis,
                    ep_axis=pp_ep_axis)
        else:
            def _verify_step(cfg, params, cache, tokens, lengths):
                cache, logits = llama.decode_multi(cfg, params, cache,
                                                   tokens, lengths,
                                                   ep_mesh=ep_mesh)
                # greedy choices computed on device: the [B, T] int
                # transfer is 32000x smaller than the logits; full logits
                # leave the device only for grammar slots (fetched lazily)
                return cache, jnp.argmax(logits, axis=-1), logits

        self._decode_multi = jax.jit(_verify_step, static_argnums=0)
        self._spec_dfa_greedy = jax.jit(dfa_greedy_multi, static_argnums=3)
        self._sample = jax.jit(sample_tokens, static_argnums=2)
        self._sample_masked = jax.jit(sample_tokens_masked, static_argnums=2)
        self._decode_scan = jax.jit(
            functools.partial(decode_scan, ep_mesh=ep_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 6, 7, 8))
        self._dfa_scan = True
        self._decode_scan_dfa = jax.jit(
            functools.partial(decode_scan_dfa, ep_mesh=ep_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 6, 7, 8))
        self._dfa_dev: Dict[int, tuple] = {}   # id(tables) -> device arrays
        self._prompts: Dict[int, List[int]] = {}   # seq_id -> prompt (for
        # n-gram draft lookup; dropped at retirement)
        # pre-restore generated tokens (restore_sequences): the contiguous
        # engine never preempts, but a crash-restored sequence still needs
        # its already-generated prefix stitched back at retirement
        self._resumed: Dict[int, List[int]] = {}

        self._buckets = tuple(
            s for s in sorted(set(engine_cfg.prefill_buckets))
            if s <= engine_cfg.max_seq_len
        ) or (engine_cfg.max_seq_len,)

    # ------------------------------------------------------------------ api

    def _register(self, seq_id: int, prompt_ids: List[int]) -> None:
        self._prompts[seq_id] = list(prompt_ids)

    def _tick(self) -> List[SequenceResult]:
        """One engine tick: admit pending into free slots, then one decode
        step for all active slots.  Returns sequences finished this tick.
        (Fault polling and tracing live in EngineBase.step, the public
        pump surface.)

        With host_overlap on and no grammar/speculation/scan in play, the
        decode dispatch is the fused ``overlap_step`` and the host commit
        lags one-to-two ticks behind (_overlap_step_tick); every other
        path flushes the lag first, so it observes fully committed
        state."""
        finished: List[SequenceResult] = self._reap_deadlines()
        if self._flushed_out:
            finished.extend(self._flushed_out)
            self._flushed_out = []
        fast = self._overlap_fast()
        if self._inflight and not fast:
            finished.extend(self._overlap_flush())
        while self._pending and self._free_slots:
            group = self._admission_group()
            # PP has no single-sequence prefill: every admission goes
            # through the batched pipelined path (padded to a microbatch
            # multiple in _admit_batch)
            if len(group) == 1 and not self._pp:
                early = self._admit(group[0])
                if early is not None:    # first sampled token already terminal
                    finished.append(early)
            else:
                finished.extend(self._admit_batch(group))
        if not fast:
            # one coalesced fetch commits every deferred admission first
            # token before any state-dependent path (spec drafts, scan
            # chunk bounds) reads st.generated
            finished.extend(self._drain_admission_commits())
        if not self._active:
            finished.extend(self._overlap_flush())
            return finished

        if self._speculation_applies():
            finished.extend(self._speculative_tick())
            return finished

        chunk = self._scan_chunk()
        if chunk > 1:
            finished.extend(self._scan_tick(chunk))
            return finished

        if fast:
            finished.extend(self._overlap_step_tick())
            return finished

        active_slots = list(self._active)
        forced, allow = self._tick_constraints(
            active_slots, self.engine_cfg.max_batch,
            self.model_cfg.vocab_size)
        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.cache, logits = self._decode(
                self.model_cfg, self.params, self.cache,
                self.cur_tokens, self.lengths)
            self._key, sub = jax.random.split(self._key)
            if allow is not None:
                next_tokens = self._sample_masked(
                    logits, sub, self.sampling, jnp.asarray(allow))
            else:
                next_tokens = self._sample(logits, sub, self.sampling)
        self._count("engine.decode_tokens", len(self._active))

        self.lengths = self.lengths.at[jnp.asarray(active_slots)].add(1)
        # ONE coalesced fetch for tokens + lengths (two blocking syncs
        # before the hot-loop rework)
        host_next, lengths_host = self._fetch(next_tokens, self.lengths)
        if forced:
            # np.asarray of a device array is a read-only view; copy to edit
            host_next = host_next.copy()
            for slot, token in forced.items():
                host_next[slot] = token
            self._count("engine.h2d_uploads")
            self.cur_tokens = jnp.asarray(host_next)
        else:
            self.cur_tokens = next_tokens

        for slot in active_slots:
            st = self._active[slot]
            token = int(host_next[slot])
            st.generated.append(token)
            if st.grammar is not None:
                st.grammar.advance(token)
            reason = self._finish_reason(st, token, int(lengths_host[slot]))
            if reason is not None:
                finished.append(self._retire(slot, reason))
        return finished

    def _overlap_step_tick(self) -> List[SequenceResult]:
        """Fast-path tick body: ONE fused dispatch (decode + sample +
        length advance, RNG key carried in-jit), no blocking fetch — the
        token vector joins ``_inflight`` and commits when the lag flushes
        (every ``_overlap_lag`` ticks, one coalesced sync).  decode_tokens
        are counted at commit (in _commit_scanned), so totals match the
        plain path exactly."""
        admits = self._take_admit_pending()
        slots = [(s, self._active[s].seq_id) for s in sorted(self._active)]
        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.cache, nxt, self.lengths, self._key = self._overlap_decode(
                self.model_cfg, self.params, self.cache, self.cur_tokens,
                self.lengths, self._key, self.sampling, self._overlap_cap)
        self.cur_tokens = nxt
        self._inflight.append({"slots": slots, "toks": nxt,
                               "admits": admits})
        if len(self._inflight) >= self._overlap_lag:
            return self._overlap_flush()
        return []

    # ------------------------------------------------------------- internals

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.engine_cfg.max_seq_len

    def _admit(self, req: _Pending) -> Optional[SequenceResult]:
        slot = self._free_slots.pop(0)
        n = len(req.prompt_ids)
        bucket = self._bucket(n)
        assert n <= bucket, f"prompt {n} exceeds largest bucket {bucket}"
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt_ids
        with profiling.annotate("engine.prefill"):
            self._count("engine.dispatches")
            self.cache, logits = self._prefill(
                self.model_cfg, self.params, self.cache,
                jnp.asarray(padded), jnp.int32(n), jnp.int32(slot))
            self._key, sub = jax.random.split(self._key)
            first = self._sample(logits, sub, self.sampling)
        self._count("engine.prefill_tokens", n)
        if req.grammar is not None:
            # grammar first tokens stay synchronous: the FSM needs the
            # sampled value (and possibly a masked resample off these
            # logits) before the next dispatch
            return self._activate(req, slot, logits,
                                  int(self._fetch(first)[0][0]))
        # deferred admission: the device already has the first token (the
        # decode input), the HOST value commits at the next coalesced
        # drain/flush — admission no longer blocks on a per-group sync
        st = self._preactivate(req, slot)
        self.cur_tokens = self.cur_tokens.at[slot].set(first[0])
        self._defer_first(st, first, 0)
        return None

    def _preactivate(self, req: _Pending, slot: int) -> _Active:
        """Token-independent half of activation: register the slot and
        set its device length (the first token is handled separately —
        synchronously for grammar slots, deferred otherwise)."""
        n = len(req.prompt_ids)
        st = _Active(
            seq_id=req.seq_id, slot=slot, prompt_tokens=n,
            max_new_tokens=req.max_new_tokens, stop_strings=req.stop_strings,
            grammar=req.grammar)
        self._active[slot] = st
        self.lengths = self.lengths.at[slot].set(n)
        return st

    def _note_first_token(self, slot: int, token: int,
                          update_dev: bool) -> None:
        # deferred admissions already wrote the on-device first token at
        # _defer_first time; only the grammar path (whose constrained
        # token can differ from the sampled one) and pre-dispatch drains
        # write it here.  update_dev=False at a lagged flush: the device
        # vector has advanced past the first token.
        if update_dev:
            self.cur_tokens = self.cur_tokens.at[slot].set(token)

    def _activate(self, req: _Pending, slot: int, logits_1v,
                  first_token: int) -> Optional[SequenceResult]:
        """Synchronous activation: grammar-constrain the first token,
        register the slot, early-retire if already terminal."""
        st = self._preactivate(req, slot)
        token = first_token
        if st.grammar is not None:
            remaining = min(st.max_new_tokens,
                            self.engine_cfg.max_seq_len
                            - st.prompt_tokens - 1)
            token = self._grammar_first_token(st.grammar, logits_1v, token,
                                              remaining)
            st.grammar.advance(token)
        # the first sampled token may already terminate the sequence
        return self._commit_first(st, token, update_dev=True)

    def _admission_group(self) -> List[_Pending]:
        """Pop a FIFO run of pending requests sharing one prefill bucket,
        bounded by free slots and a batch cap — they prefill in ONE
        dispatch (prefill_batch).  CP mode admits singly (prefill_cp is
        per-sequence)."""
        group = [self._pending.pop(0)]
        if self._batch_admission:
            b0 = self._bucket(len(group[0].prompt_ids))
            while (self._pending and len(group) < len(self._free_slots)
                   and len(group) < 8
                   and self._bucket(len(self._pending[0].prompt_ids)) == b0):
                group.append(self._pending.pop(0))
        return group

    def _admit_batch(self, reqs: List[_Pending]) -> List[SequenceResult]:
        """Admit N same-bucket sequences with one batched prefill.  The
        batch is padded to a power of two by repeating the last row
        (same slot id: the duplicate scatter writes are idempotent)."""
        n = len(reqs)
        bucket = self._bucket(max(len(r.prompt_ids) for r in reqs))
        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        if self._pp and n_pad % self._pp_m:
            # the pipelined prefill microbatches its rows: pad the batch
            # to a microbatch multiple (rows repeat the last real row, so
            # the extra scatter writes stay idempotent)
            n_pad = -(-n_pad // self._pp_m) * self._pp_m
        slots = [self._free_slots.pop(0) for _ in range(n)]
        tokens = np.zeros((n_pad, bucket), np.int32)
        lens = np.zeros((n_pad,), np.int32)
        slot_arr = np.zeros((n_pad,), np.int32)
        for i, r in enumerate(reqs):
            tokens[i, :len(r.prompt_ids)] = r.prompt_ids
            lens[i] = len(r.prompt_ids)
            slot_arr[i] = slots[i]
        tokens[n:] = tokens[n - 1]
        lens[n:] = lens[n - 1]
        slot_arr[n:] = slot_arr[n - 1]

        with profiling.annotate("engine.prefill"):
            self._count("engine.dispatches")
            self.cache, logits = self._prefill_batch(
                self.model_cfg, self.params, self.cache,
                jnp.asarray(tokens), jnp.asarray(lens),
                jnp.asarray(slot_arr))
            self._key, sub = jax.random.split(self._key)
            firsts = self._sample(logits, sub, self.sampling)
        self._count("engine.prefill_tokens", int(lens[:n].sum()))
        self._count("engine.batched_admissions", n)

        if any(r.grammar is not None for r in reqs):
            # a grammar member forces the whole group synchronous so its
            # masked-resample key split keeps its stream position
            finished: List[SequenceResult] = []
            (firsts_host,) = self._fetch(firsts)
            for i, req in enumerate(reqs):
                early = self._activate(req, slots[i], logits[i:i + 1],
                                       int(firsts_host[i]))
                if early is not None:
                    finished.append(early)
            return finished
        for i, req in enumerate(reqs):
            st = self._preactivate(req, slots[i])
            self.cur_tokens = self.cur_tokens.at[slots[i]].set(firsts[i])
            self._defer_first(st, firsts, i)
        return []

    def _retire(self, slot: int, reason: str) -> SequenceResult:
        st = self._active.pop(slot)
        if self._deadlines:
            self._deadlines.pop(st.seq_id, None)
        self._free_slots.append(slot)
        # a crash-restored sequence's st.generated holds only post-restore
        # tokens and its admitted prompt carried the pre-crash generation;
        # stitch the prefix back and report against the ORIGINAL prompt
        # (mirrors the paged engine's preemption accounting)
        orig_prompt = self._prompts.pop(st.seq_id, None)
        generated = self._resumed.pop(st.seq_id, []) + st.generated
        text = self._final_text(generated, reason, st.stop_strings)
        return SequenceResult(
            seq_id=st.seq_id,
            token_ids=list(generated),
            text=text,
            finish_reason=reason,
            prompt_tokens=(len(orig_prompt) if orig_prompt is not None
                           else st.prompt_tokens),
            completion_tokens=len(generated),
        )

    # ------------------------------------------------- chunked scan tick

    def _scan_tick(self, chunk: int) -> List[SequenceResult]:
        """Commit ``chunk`` decode steps from one on-device scan; token
        accounting and finish semantics identical to the stepwise tick.
        Grammar slots whose FSM compiled to DFA tables run constrained
        INSIDE the scan (decode_scan_dfa) — zero per-token host work."""
        active_slots = list(self._active)
        setup = self._scan_dfa_setup()
        self._key, sub = jax.random.split(self._key)
        self._count("engine.dispatches")
        if setup is None:
            with profiling.annotate("engine.decode_step"):
                self.cache, toks, self.lengths = self._decode_scan(
                    self.model_cfg, self.params, self.cache,
                    self.cur_tokens, self.lengths, sub, chunk,
                    self.sampling, self.tokenizer.eos_id)
        else:
            (allow_t, next_t, dist_t, close_t, complete_t), states, \
                remaining = setup
            with profiling.annotate("engine.decode_step"):
                self.cache, toks, self.lengths, _ = self._decode_scan_dfa(
                    self.model_cfg, self.params, self.cache,
                    self.cur_tokens, self.lengths, sub, chunk,
                    self.sampling, self.tokenizer.eos_id,
                    jnp.asarray(states), jnp.asarray(remaining),
                    allow_t, next_t, dist_t, close_t, complete_t)
        (toks_host,) = self._fetch(toks)                 # [chunk, B]
        self.cur_tokens = toks[-1]

        return self._commit_scanned(active_slots, toks_host, chunk,
                                    self._grammar_post_commit)

    # --------------------------------------------- speculative decoding

    def _speculative_tick(self) -> List[SequenceResult]:
        """One verification tick on the contiguous cache: score all draft
        positions in one decode_multi, commit via _verify_and_commit.
        When every grammar slot shares one compiled DFA, the constrained
        greedy is computed ON DEVICE (dfa_greedy_multi) — spec×grammar
        keeps multi-token verify with no [B, T, V] logits transfer."""
        active_slots = list(self._active)
        cur_host, lengths_host = self._fetch(self.cur_tokens, self.lengths)
        tokens_in, drafts = self._build_drafts(active_slots, cur_host)

        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.cache, greedy, logits = self._decode_multi(
                self.model_cfg, self.params, self.cache,
                jnp.asarray(tokens_in), self.lengths)
            greedy_host, logits_host, constrained = \
                self._spec_constrained_greedy(greedy, logits, active_slots)

        lengths_host = lengths_host.copy()
        next_cur = cur_host.copy()

        def post_commit(slot: int, token: int) -> None:
            lengths_host[slot] += 1
            next_cur[slot] = token

        finished = self._verify_and_commit(active_slots, drafts, greedy_host,
                                           logits_host, post_commit,
                                           constrained)
        self._count("engine.h2d_uploads", 2)
        self.lengths = jnp.asarray(lengths_host)
        self.cur_tokens = jnp.asarray(next_cur)
        return finished


# ---------------------------------------------------------------------------
# On-device multi-step decode (throughput path, used by bench.py)
# ---------------------------------------------------------------------------


def overlap_step(
    cfg: ModelConfig,
    params,
    cache: llama.KVCache,
    cur_tokens: jnp.ndarray,    # [B]
    lengths: jnp.ndarray,       # [B]
    key: jax.Array,
    sampling: SamplingParams,
    cap: int,
    ep_mesh=None,
    decode_fn=None,
) -> Tuple[llama.KVCache, jnp.ndarray, jnp.ndarray, jax.Array]:
    """One fused hot-loop step for the overlapped engine: decode + RNG
    split + sample + length advance in a single dispatch, so the host
    never touches the carried state between ticks.

    ``jax.random.split`` is deterministic, so splitting in-jit yields the
    identical subkey stream as the plain tick's host-side split — sampled
    tokens match token-for-token.  ALL slots advance (clamped at ``cap``,
    the last writable cache position): a slot whose sequence already
    finished on the host keeps decoding garbage until the lagged flush
    retires it, which is safe because its tokens are never committed and
    its KV row is fully rewritten by the next admission's prefill before
    any position is attended.  Returns (cache, next_tokens, lengths, key).
    """
    if decode_fn is None:
        cache, logits = llama.decode_step(cfg, params, cache, cur_tokens,
                                          lengths, ep_mesh)
    else:
        cache, logits = decode_fn(cfg, params, cache, cur_tokens, lengths)
    key, sub = jax.random.split(key)
    nxt = sample_tokens(logits, sub, sampling)
    lengths = jnp.minimum(lengths + 1, cap).astype(lengths.dtype)
    return cache, nxt, lengths, key


def decode_scan(
    cfg: ModelConfig,
    params,
    cache: llama.KVCache,
    cur_tokens: jnp.ndarray,    # [B]
    lengths: jnp.ndarray,       # [B]
    key: jax.Array,
    n_steps: int,
    sampling: SamplingParams = SamplingParams(),
    eos_id: int = -1,
    ep_mesh=None,
    decode_fn=None,
) -> Tuple[llama.KVCache, jnp.ndarray, jnp.ndarray]:
    """Decode ``n_steps`` for the whole batch with zero host sync.

    Returns (cache, tokens [n_steps, B], lengths).  Slots that hit ``eos_id``
    stop advancing (their token repeats; host trims after the fact).
    ``decode_fn``: optional (cfg, params, cache, tokens, lengths) ->
    (cache, logits) override — the PP engine scans its pipelined step.
    """

    def body(carry, _):
        cache, cur, lens, done, key = carry
        if decode_fn is None:
            cache, logits = llama.decode_step(cfg, params, cache, cur, lens,
                                              ep_mesh)
        else:
            cache, logits = decode_fn(cfg, params, cache, cur, lens)
        key, sub = jax.random.split(key)
        nxt = sample_tokens(logits, sub, sampling)
        newly_done = done | (nxt == eos_id)
        advance = jnp.logical_not(done)
        cur = jnp.where(advance, nxt, cur)
        lens = lens + advance.astype(jnp.int32)
        return (cache, cur, lens, newly_done, key), cur

    done0 = jnp.zeros_like(cur_tokens, dtype=bool)
    (cache, _, lengths, _, _), toks = jax.lax.scan(
        body, (cache, cur_tokens, lengths, done0, key), None, length=n_steps)
    return cache, toks, lengths


def dfa_scan_step(logits, cur, lens, done, states, remaining, key,
                  sampling: SamplingParams, eos_id: int,
                  allow_t, next_t, dist_t, close_t, complete_t):
    """One on-device DFA-constrained sampling step, shared by the
    contiguous and paged scan bodies (single source for the budget-fits
    mask, force-close, complete->EOS, and state-transition logic).

    Returns (cur', lens', done', states', remaining', sub_key_consumed).
    """
    key, sub = jax.random.split(key)
    nxt_states = next_t[states]                       # [B, V]
    fits = dist_t[nxt_states] <= (remaining - 2)[:, None]
    rows = allow_t[states] & fits
    sampled = sample_tokens_masked(logits, sub, sampling, rows)
    # empty row (sub-minimal budget, guarded at submit): force close
    nxt = jnp.where(rows.any(axis=-1), sampled, close_t[states])
    nxt = jnp.where(complete_t[states], eos_id, nxt)
    newly_done = done | (nxt == eos_id)
    advance = jnp.logical_not(done)
    cur = jnp.where(advance, nxt, cur)
    lens = lens + advance.astype(lens.dtype)
    step_dfa = advance & (nxt != eos_id)
    states = jnp.where(step_dfa, next_t[states, nxt], states)
    remaining = remaining - advance.astype(jnp.int32)
    return cur, lens, newly_done, states, remaining, key


def dfa_greedy_multi(logits, states, remaining, eos_id: int,
                     allow_t, next_t, dist_t, close_t, complete_t):
    """Grammar-constrained greedy over a verification step's positions,
    entirely on device (the speculative analog of ``dfa_scan_step``).

    logits [B, T, V]; states/remaining [B] (FREE row for ungrammared
    slots, whose result is then the plain argmax).  The DFA advances along
    the CONSTRAINED choices: on the accepted draft prefix they equal the
    draft (that is what acceptance means), and positions after the first
    disagreement are never committed by the host.  Returns tokens [B, T],
    so speculative decoding keeps multi-token verify under a grammar
    without shipping [B, T, V] logits to the host."""

    def step(carry, lt):
        states, remaining = carry
        nxt_states = next_t[states]                       # [B, V]
        fits = dist_t[nxt_states] <= (remaining - 2)[:, None]
        rows = allow_t[states] & fits
        masked = jnp.where(rows, lt, -jnp.inf)
        tok = jnp.argmax(masked, axis=-1).astype(jnp.int32)
        tok = jnp.where(rows.any(axis=-1), tok, close_t[states])
        tok = jnp.where(complete_t[states], eos_id, tok)
        states = jnp.where(tok != eos_id, next_t[states, tok], states)
        remaining = remaining - 1
        return (states, remaining), tok

    _, toks = jax.lax.scan(step, (states, remaining),
                           jnp.swapaxes(logits, 0, 1))
    return jnp.swapaxes(toks, 0, 1)                       # [B, T]


def decode_scan_dfa(
    cfg: ModelConfig,
    params,
    cache: llama.KVCache,
    cur_tokens: jnp.ndarray,    # [B]
    lengths: jnp.ndarray,       # [B]
    key: jax.Array,
    n_steps: int,
    sampling: SamplingParams,
    eos_id: int,
    states: jnp.ndarray,        # [B] int32 DFA state per slot (FREE = none)
    remaining: jnp.ndarray,     # [B] int32 token budget per slot
    allow_t: jnp.ndarray,       # [S, V] bool
    next_t: jnp.ndarray,        # [S, V] int32
    dist_t: jnp.ndarray,        # [S] int32
    close_t: jnp.ndarray,       # [S] int32
    complete_t: jnp.ndarray,    # [S] bool
    ep_mesh=None,
    decode_fn=None,
) -> Tuple[llama.KVCache, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``decode_scan`` with the grammar DFA riding INSIDE the scan.

    Per step, entirely on device: gather the state's token mask, sample
    under it, force the budget-close / EOS transitions, and step the DFA
    (constrain.compile_schema_dfa tables).  Grammar-constrained sequences
    thus decode in chunked dispatches with ZERO per-token host work —
    SURVEY §7's "constrained decode that stays on the fast decode path".
    Returns (cache, tokens [n_steps, B], lengths, states).
    """

    def body(carry, _):
        cache, cur, lens, done, states, remaining, key = carry
        if decode_fn is None:
            cache, logits = llama.decode_step(cfg, params, cache, cur, lens,
                                              ep_mesh)
        else:
            cache, logits = decode_fn(cfg, params, cache, cur, lens)
        cur, lens, done, states, remaining, key = dfa_scan_step(
            logits, cur, lens, done, states, remaining, key, sampling,
            eos_id, allow_t, next_t, dist_t, close_t, complete_t)
        return (cache, cur, lens, done, states, remaining, key), cur

    done0 = jnp.zeros_like(cur_tokens, dtype=bool)
    (cache, _, lengths, _, states, _, _), toks = jax.lax.scan(
        body, (cache, cur_tokens, lengths, done0, states, remaining, key),
        None, length=n_steps)
    return cache, toks, lengths, states
