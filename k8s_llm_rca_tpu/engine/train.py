"""Training step: next-token cross-entropy + optax update, mesh-sharded.

The reference has no training at all (weights live behind the OpenAI API);
this exists so the framework can fine-tune its RCA models in-tree and so the
multi-chip sharding path has a full fwd+bwd+update graph to validate
(__graft_entry__.dryrun_multichip jits this over a real dp x tp mesh).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_llm_rca_tpu.config import ModelConfig
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.runtime.sharding import llama_param_specs, shard_pytree


def next_token_loss(cfg: ModelConfig, params, tokens: jnp.ndarray,
                    loss_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean CE of predicting tokens[:, 1:] from tokens[:, :-1]."""
    logits = llama.forward(cfg, params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        mask = loss_mask[:, 1:].astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def make_train_step(cfg: ModelConfig,
                    optimizer: optax.GradientTransformation
                    ) -> Callable:
    """Jittable (params, opt_state, tokens[, loss_mask]) ->
    (params, opt_state, loss).  Sharding comes from the argument
    placements (GSPMD propagation).  ``loss_mask`` [B, S] (optional)
    restricts the CE to masked-in positions — supervised-completion
    distillation trains only on the target tokens (rca/distill.py)."""

    def train_step(params, opt_state, tokens, loss_mask=None):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(cfg, p, tokens, loss_mask))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded_train_state(cfg: ModelConfig, mesh: Mesh,
                             optimizer: optax.GradientTransformation,
                             seed: int = 0) -> Tuple[Any, Any]:
    """Params sharded per llama_param_specs (TP over 'model', EP over
    'expert'); optimizer state inherits the param shardings leaf-wise."""
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    specs = llama_param_specs(cfg)
    params = shard_pytree(params, specs, mesh)
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=None)(params)   # placements propagate from params
    return params, opt_state


def shard_batch(tokens, mesh: Mesh):
    """Batch dim over 'data' (DP); sequence stays whole here — sequence
    sharding (SP/CP) is applied inside the attention modules in parallel/."""
    return jax.device_put(
        tokens, NamedSharding(mesh, P("data", None)))
