"""Grammar-constrained decoding: token-level FSMs applied as logit masks.

The reference extracts fenced ```json / ```cypher blocks with naive
``str.split`` and, when the model misformats, pushes the exception text back
into the thread and retries up to 3 times (reference
find_metapath/find_srckind_metapath_neo4j.py:193-196, test_all.py:70-83).
The serve layer already forces the fences themselves (forced_prefix / stop
strings, serve/backend.py); this module closes the remaining hole — the body
between the fences — with a character-level **JSON pushdown automaton**
lifted to token masks, so a run requested with ``grammar="json"`` cannot
emit unparseable JSON at all.  That converts the reference's retry loop
from a runtime recovery path into dead code.

Division of labor with the jitted decode path (SURVEY §7 hard part 4 —
"constrained decode that stays on the fast decode path"):

- the model forward + sampling stay compiled on device; the FSM runs on the
  host between ticks (the engines already sync one [B] token vector per
  tick, so the FSM adds no extra device round-trips);
- a *forced* token (e.g. EOS once the JSON value closes) costs nothing on
  device: the host overrides the sampled token before it feeds the next
  decode step — the overridden token is what gets written to the KV cache,
  because caches are written by the *next* tick's decode step;
- a *masked* step ships one [B, V] bool array to the device where
  ``sample_tokens_masked`` adds it to the logits — one small transfer, no
  recompilation (the mask is a traced argument).

Token→mask computation simulates each candidate token's characters through
a clone of the automaton.  For the 512-entry byte tokenizer this is
microseconds; for 32k+ BPE vocabs the per-token strings are precomputed
once and cached per tokenizer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from k8s_llm_rca_tpu.utils.logging import get_logger
from k8s_llm_rca_tpu.utils.tokenizer import Tokenizer

WS = " \t\n\r"
DIGITS = "0123456789"
HEX = DIGITS + "abcdefABCDEF"
# characters legal inside a JSON string (unescaped): anything above 0x1f
# except '"' and '\\'; we additionally exclude non-ASCII bytes so byte-level
# tokenizers can't split a multi-byte codepoint across a mask boundary
_STRING_CHARS = "".join(
    chr(c) for c in range(0x20, 0x7F) if chr(c) not in '"\\')
_ESCAPABLE = '"\\/bfnrtu'


@dataclass(frozen=True)
class Constraint:
    """What the FSM demands of the next token.

    ``force``: exact token id the engine must emit (no sampling).
    ``allow``: bool [V] mask of permitted token ids (sample under mask).
    Both ``None`` means the step is unconstrained.
    """

    force: Optional[int] = None
    allow: Optional[np.ndarray] = None

    @property
    def free(self) -> bool:
        return self.force is None and self.allow is None


class JsonCharAutomaton:
    """Incremental character-level validator for a single JSON value.

    ``accept(ch)`` consumes one character, returning False (and leaving the
    state unchanged) if it is not a legal continuation.  ``complete`` flips
    once a full top-level value has been consumed.  ``can_terminate`` also
    covers top-level numbers, which only end at end-of-input.
    """

    __slots__ = ("stack", "state", "lit", "lit_pos", "hex_left", "complete")

    def __init__(self):
        self.stack: List[str] = []       # 'obj' | 'arr'
        self.state = "value"
        self.lit = ""                    # target literal (true/false/null)
        self.lit_pos = 0
        self.hex_left = 0                # remaining \uXXXX hex digits
        self.complete = False

    def clone(self) -> "JsonCharAutomaton":
        c = JsonCharAutomaton.__new__(JsonCharAutomaton)
        c.stack = list(self.stack)
        c.state = self.state
        c.lit = self.lit
        c.lit_pos = self.lit_pos
        c.hex_left = self.hex_left
        c.complete = self.complete
        return c

    # ------------------------------------------------------------ helpers

    def _end_value(self) -> None:
        """A value just finished; decide what comes next."""
        if not self.stack:
            self.complete = True
            self.state = "trailing"
        else:
            self.state = "after_value"

    def _delimiters(self) -> str:
        """Characters that may legally follow a just-finished value."""
        if not self.stack:
            return WS
        return WS + (",}" if self.stack[-1] == "obj" else ",]")

    @property
    def can_terminate(self) -> bool:
        """True if end-of-input here yields a complete valid JSON value."""
        return self.complete or (
            not self.stack
            and self.state in ("num_zero", "num_int", "num_frac", "num_exp"))

    # ------------------------------------------------------------ accept

    def accept(self, ch: str) -> bool:  # noqa: C901 (it's a flat automaton)
        s = self.state
        if s in ("value", "arr_value"):
            if ch in WS:
                return True
            if ch == "{":
                self.stack.append("obj")
                self.state = "obj_key_or_end"
            elif ch == "[":
                self.stack.append("arr")
                self.state = "arr_value_or_end"
            elif ch == '"':
                self.state = "str"
            elif ch == "-":
                self.state = "num_minus"
            elif ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            elif ch in "tfn":
                self.lit = {"t": "true", "f": "false", "n": "null"}[ch]
                self.lit_pos = 1
                self.state = "lit"
            else:
                return False
            return True

        if s == "arr_value_or_end":
            if ch in WS:
                return True              # stay: '[  ]' is still closable
            if ch == "]":
                self.stack.pop()
                self._end_value()
                return True
            self.state = "value"
            ok = self.accept(ch)
            if not ok:
                self.state = "arr_value_or_end"
            return ok

        if s == "obj_key_or_end":
            if ch in WS:
                return True
            if ch == "}":
                self.stack.pop()
                self._end_value()
                return True
            if ch == '"':
                self.state = "key"
                return True
            return False

        if s == "obj_key":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key"
                return True
            return False

        if s in ("str", "key"):
            if ch == '"':
                self.state = "colon" if s == "key" else None
                if s == "str":
                    self._end_value()
                return True
            if ch == "\\":
                self.state = "str_esc" if s == "str" else "key_esc"
                return True
            return ch in _STRING_CHARS

        if s in ("str_esc", "key_esc"):
            base = "str" if s == "str_esc" else "key"
            if ch == "u":
                self.hex_left = 4
                self.state = base + "_hex"
                return True
            if ch in _ESCAPABLE:
                self.state = base
                return True
            return False

        if s in ("str_hex", "key_hex"):
            if ch in HEX:
                self.hex_left -= 1
                if self.hex_left == 0:
                    self.state = s[:3]
                return True
            return False

        if s == "colon":
            if ch in WS:
                return True
            if ch == ":":
                self.state = "value"
                return True
            return False

        if s == "after_value":
            if ch in WS:
                return True
            top = self.stack[-1]
            if ch == ",":
                self.state = "obj_key" if top == "obj" else "value"
                return True
            if ch == "}" and top == "obj":
                self.stack.pop()
                self._end_value()
                return True
            if ch == "]" and top == "arr":
                self.stack.pop()
                self._end_value()
                return True
            return False

        if s == "lit":
            if self.lit_pos < len(self.lit) and ch == self.lit[self.lit_pos]:
                self.lit_pos += 1
                if self.lit_pos == len(self.lit):
                    self._end_value()
                return True
            return False

        # ---- numbers: strict JSON grammar; they end on a delimiter, which
        # must then be re-dispatched through the post-value state
        if s in ("num_minus", "num_zero", "num_int",
                 "num_frac_start", "num_frac",
                 "num_exp_start", "num_exp_sign", "num_exp"):
            return self._accept_number(s, ch)

        if s == "trailing":
            return ch in WS

        raise AssertionError(f"unknown state {s}")

    def _closing_char(self) -> str:
        """One character moving toward the shortest valid completion."""
        s = self.state
        if s in ("value", "arr_value", "num_minus", "num_frac_start",
                 "num_exp_start", "num_exp_sign", "str_hex", "key_hex"):
            return "0"
        if s == "arr_value_or_end":
            return "]"
        if s == "obj_key_or_end":
            return "}"
        if s in ("obj_key", "str", "key"):
            return '"'
        if s in ("str_esc", "key_esc"):
            return "n"
        if s == "colon":
            return ":"
        if s == "after_value":
            return "}" if self.stack[-1] == "obj" else "]"
        if s == "lit":
            return self.lit[self.lit_pos]
        if s in ("num_zero", "num_int", "num_frac", "num_exp"):
            # number ends at the enclosing delimiter (top-level: end-of-input)
            return "}" if self.stack[-1] == "obj" else "]"
        raise AssertionError(f"no closing char for state {s}")

    def minimal_completion(self) -> str:
        """Shortest character string that completes a valid JSON value from
        the current state ('' if already complete / terminable)."""
        clone = self.clone()
        out = []
        while not clone.complete and not clone.can_terminate:
            ch = clone._closing_char()
            assert clone.accept(ch), (clone.state, ch)
            out.append(ch)
        return "".join(out)

    def _accept_number(self, s: str, ch: str) -> bool:
        cont = {
            "num_minus": {"0": "num_zero", **{d: "num_int" for d in "123456789"}},
            "num_zero": {".": "num_frac_start", "e": "num_exp_start",
                         "E": "num_exp_start"},
            "num_int": {**{d: "num_int" for d in DIGITS},
                        ".": "num_frac_start", "e": "num_exp_start",
                        "E": "num_exp_start"},
            "num_frac_start": {d: "num_frac" for d in DIGITS},
            "num_frac": {**{d: "num_frac" for d in DIGITS},
                         "e": "num_exp_start", "E": "num_exp_start"},
            "num_exp_start": {"+": "num_exp_sign", "-": "num_exp_sign",
                              **{d: "num_exp" for d in DIGITS}},
            "num_exp_sign": {d: "num_exp" for d in DIGITS},
            "num_exp": {d: "num_exp" for d in DIGITS},
        }[s]
        nxt = cont.get(ch)
        if nxt is not None:
            self.state = nxt
            return True
        # a complete number form may end at a delimiter of the enclosing
        # container; incomplete forms (num_minus, num_frac_start, ...) may not
        if s in ("num_zero", "num_int", "num_frac", "num_exp") and \
                ch in self._delimiters():
            self._end_value()
            if ch in WS:
                return True
            return self.accept(ch)   # re-dispatch ',' '}' ']'
        return False


def _token_strings(tokenizer: Tokenizer) -> List[str]:
    """Per-token decoded strings, cached ON the tokenizer instance (an
    id()-keyed module cache would leak tables and could serve a stale
    table after CPython address reuse)."""
    cached = getattr(tokenizer, "_token_strings_cache", None)
    if cached is None:
        cached = [tokenizer.decode([t]) for t in range(tokenizer.vocab_size)]
        tokenizer._token_strings_cache = cached
    return cached


class JsonGrammar:
    """Token-level FSM guaranteeing the generated body parses as JSON.

    Constraint per step: mask to tokens whose every character the automaton
    accepts; once the top-level value is complete (or a top-level number can
    terminate and the sampled token would be trailing junk), force EOS.
    """

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer
        self.auto = JsonCharAutomaton()
        self.eos_id = tokenizer.eos_id
        self._strings = _token_strings(tokenizer)
        self._mask_cache: Dict[Tuple, np.ndarray] = {}
        # exact single-character token ids for the force-close path (encode()
        # round trips are not identity for SentencePiece-style tokenizers)
        self._char_token: Dict[str, int] = {}
        max_chars = 1
        for t, s in enumerate(self._strings):
            if len(s) == 1 and s not in self._char_token:
                self._char_token[s] = t
            max_chars = max(max_chars, len(s))
        # one sampled token can extend the minimal completion by a few chars
        # per character it contains (an opening brace adds a closer, a key
        # quote adds '":0', ...), while force-close emits one char per tick —
        # so multi-char vocabs must start closing earlier
        self._close_margin = 2 + 4 * (max_chars - 1)

    @property
    def done(self) -> bool:
        return self.auto.complete

    def _state_key(self) -> Tuple:
        a = self.auto
        return (tuple(a.stack), a.state, a.lit, a.lit_pos, a.hex_left)

    def constraint(self, remaining: Optional[int] = None) -> Constraint:
        """``remaining``: token budget left for this sequence.  When it
        shrinks to the minimal-completion length (+2 safety margin, 1 token
        per char worst case), the FSM stops sampling and force-closes the
        value so a "length"-terminated sequence still parses."""
        if self.auto.complete:
            return Constraint(force=self.eos_id)
        if remaining is not None:
            completion = self.auto.minimal_completion()
            if remaining <= len(completion) + self._close_margin:
                if not completion:
                    return Constraint(force=self.eos_id)
                forced = self._char_token.get(completion[0])
                if forced is None:
                    # vocab has no exact single-char token for the closer
                    # (never the case for byte vocabs): end cleanly if the
                    # value can terminate, else emit what encode() gives
                    if self.auto.can_terminate:
                        return Constraint(force=self.eos_id)
                    forced = self.tokenizer.encode(completion[0])[0]
                return Constraint(force=forced)
        key = self._state_key()
        allow = self._mask_cache.get(key)
        if allow is None:
            allow = np.zeros((self.tokenizer.vocab_size,), bool)
            for t, s in enumerate(self._strings):
                if not s:
                    continue            # specials / empty decodes: never legal
                if all(c in WS for c in s):
                    # JSON never REQUIRES whitespace; banning pure-ws tokens
                    # keeps output compact instead of letting a weak model
                    # burn its budget emitting newlines
                    continue
                sim = self.auto.clone()
                if all(sim.accept(c) for c in s):
                    allow[t] = True
            if self.auto.can_terminate:
                allow[self.eos_id] = True
            self._mask_cache[key] = allow
        if not allow.any():
            # un-continuable (shouldn't happen with a byte vocab): end the
            # sequence rather than decode garbage forever
            return Constraint(force=self.eos_id)
        return Constraint(allow=allow)

    def advance(self, token: int) -> None:
        if token == self.eos_id:
            return
        for ch in self._strings[token]:
            if not self.auto.accept(ch):
                raise ValueError(
                    f"token {token} ({self._strings[token]!r}) violates the "
                    f"JSON grammar in state {self.auto.state}")


def make_grammar(name: Optional[str], tokenizer: Tokenizer,
                 prefer_native: bool = True):
    """GenOptions.grammar -> FSM instance (None = unconstrained).

    Prefers the C++ engine (native/, mask computation is O(V·len) per tick)
    and falls back to the Python FSM when no toolchain is available; the
    two are mask-for-mask identical (tests/test_native.py)."""
    if name is None:
        return None
    if name == "json":
        if prefer_native:
            try:
                from k8s_llm_rca_tpu import native
                if native.available():
                    return native.NativeJsonGrammar(tokenizer)
            except Exception as e:           # toolchain/ABI trouble: fall back
                get_logger(__name__).debug("native grammar unavailable: %s", e)
        return JsonGrammar(tokenizer)
    raise ValueError(f"unknown grammar {name!r} (supported: 'json')")
