"""Grammar-constrained decoding: token-level FSMs applied as logit masks.

The reference extracts fenced ```json / ```cypher blocks with naive
``str.split`` and, when the model misformats, pushes the exception text back
into the thread and retries up to 3 times (reference
find_metapath/find_srckind_metapath_neo4j.py:193-196, test_all.py:70-83).
The serve layer already forces the fences themselves (forced_prefix / stop
strings, serve/backend.py); this module closes the remaining hole — the body
between the fences — with a character-level **JSON pushdown automaton**
lifted to token masks, so a run requested with ``grammar="json"`` cannot
emit unparseable JSON at all.  That converts the reference's retry loop
from a runtime recovery path into dead code.

Division of labor with the jitted decode path (SURVEY §7 hard part 4 —
"constrained decode that stays on the fast decode path"):

- the model forward + sampling stay compiled on device; the FSM runs on the
  host between ticks (the engines already sync one [B] token vector per
  tick, so the FSM adds no extra device round-trips);
- a *forced* token (e.g. EOS once the JSON value closes) costs nothing on
  device: the host overrides the sampled token before it feeds the next
  decode step — the overridden token is what gets written to the KV cache,
  because caches are written by the *next* tick's decode step;
- a *masked* step ships one [B, V] bool array to the device where
  ``sample_tokens_masked`` adds it to the logits — one small transfer, no
  recompilation (the mask is a traced argument).

Token→mask computation simulates each candidate token's characters through
a clone of the automaton.  For the 512-entry byte tokenizer this is
microseconds; for 32k+ BPE vocabs the per-token strings are precomputed
once and cached per tokenizer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from k8s_llm_rca_tpu.utils.logging import get_logger
from k8s_llm_rca_tpu.utils.tokenizer import Tokenizer

WS = " \t\n\r"
DIGITS = "0123456789"
HEX = DIGITS + "abcdefABCDEF"
# characters legal inside a JSON string (unescaped): anything above 0x1f
# except '"' and '\\'; we additionally exclude non-ASCII bytes so byte-level
# tokenizers can't split a multi-byte codepoint across a mask boundary
_STRING_CHARS = "".join(
    chr(c) for c in range(0x20, 0x7F) if chr(c) not in '"\\')
_ESCAPABLE = '"\\/bfnrtu'
# schema strings: the \u hex form is excluded (it would add 4 hex states
# per position); the named escapes cover quoted commands and JSON payloads
_SCHEMA_ESCAPABLE = '"\\/bfnrt'


@dataclass(frozen=True)
class Constraint:
    """What the FSM demands of the next token.

    ``force``: exact token id the engine must emit (no sampling).
    ``allow``: bool [V] mask of permitted token ids (sample under mask).
    Both ``None`` means the step is unconstrained.
    """

    force: Optional[int] = None
    allow: Optional[np.ndarray] = None

    @property
    def free(self) -> bool:
        return self.force is None and self.allow is None


class JsonCharAutomaton:
    """Incremental character-level validator for a single JSON value.

    ``accept(ch)`` consumes one character, returning False (and leaving the
    state unchanged) if it is not a legal continuation.  ``complete`` flips
    once a full top-level value has been consumed.  ``can_terminate`` also
    covers top-level numbers, which only end at end-of-input.
    """

    __slots__ = ("stack", "state", "lit", "lit_pos", "hex_left", "complete")

    def __init__(self):
        self.stack: List[str] = []       # 'obj' | 'arr'
        self.state = "value"
        self.lit = ""                    # target literal (true/false/null)
        self.lit_pos = 0
        self.hex_left = 0                # remaining \uXXXX hex digits
        self.complete = False

    def clone(self) -> "JsonCharAutomaton":
        c = JsonCharAutomaton.__new__(JsonCharAutomaton)
        c.stack = list(self.stack)
        c.state = self.state
        c.lit = self.lit
        c.lit_pos = self.lit_pos
        c.hex_left = self.hex_left
        c.complete = self.complete
        return c

    # ------------------------------------------------------------ helpers

    def _end_value(self) -> None:
        """A value just finished; decide what comes next."""
        if not self.stack:
            self.complete = True
            self.state = "trailing"
        else:
            self.state = "after_value"

    def _delimiters(self) -> str:
        """Characters that may legally follow a just-finished value."""
        if not self.stack:
            return WS
        return WS + (",}" if self.stack[-1] == "obj" else ",]")

    @property
    def can_terminate(self) -> bool:
        """True if end-of-input here yields a complete valid JSON value."""
        return self.complete or (
            not self.stack
            and self.state in ("num_zero", "num_int", "num_frac", "num_exp"))

    # ------------------------------------------------------------ accept

    def accept(self, ch: str) -> bool:  # noqa: C901 (it's a flat automaton)
        s = self.state
        if s in ("value", "arr_value"):
            if ch in WS:
                return True
            if ch == "{":
                self.stack.append("obj")
                self.state = "obj_key_or_end"
            elif ch == "[":
                self.stack.append("arr")
                self.state = "arr_value_or_end"
            elif ch == '"':
                self.state = "str"
            elif ch == "-":
                self.state = "num_minus"
            elif ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            elif ch in "tfn":
                self.lit = {"t": "true", "f": "false", "n": "null"}[ch]
                self.lit_pos = 1
                self.state = "lit"
            else:
                return False
            return True

        if s == "arr_value_or_end":
            if ch in WS:
                return True              # stay: '[  ]' is still closable
            if ch == "]":
                self.stack.pop()
                self._end_value()
                return True
            self.state = "value"
            ok = self.accept(ch)
            if not ok:
                self.state = "arr_value_or_end"
            return ok

        if s == "obj_key_or_end":
            if ch in WS:
                return True
            if ch == "}":
                self.stack.pop()
                self._end_value()
                return True
            if ch == '"':
                self.state = "key"
                return True
            return False

        if s == "obj_key":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key"
                return True
            return False

        if s in ("str", "key"):
            if ch == '"':
                self.state = "colon" if s == "key" else None
                if s == "str":
                    self._end_value()
                return True
            if ch == "\\":
                self.state = "str_esc" if s == "str" else "key_esc"
                return True
            return ch in _STRING_CHARS

        if s in ("str_esc", "key_esc"):
            base = "str" if s == "str_esc" else "key"
            if ch == "u":
                self.hex_left = 4
                self.state = base + "_hex"
                return True
            if ch in _ESCAPABLE:
                self.state = base
                return True
            return False

        if s in ("str_hex", "key_hex"):
            if ch in HEX:
                self.hex_left -= 1
                if self.hex_left == 0:
                    self.state = s[:3]
                return True
            return False

        if s == "colon":
            if ch in WS:
                return True
            if ch == ":":
                self.state = "value"
                return True
            return False

        if s == "after_value":
            if ch in WS:
                return True
            top = self.stack[-1]
            if ch == ",":
                self.state = "obj_key" if top == "obj" else "value"
                return True
            if ch == "}" and top == "obj":
                self.stack.pop()
                self._end_value()
                return True
            if ch == "]" and top == "arr":
                self.stack.pop()
                self._end_value()
                return True
            return False

        if s == "lit":
            if self.lit_pos < len(self.lit) and ch == self.lit[self.lit_pos]:
                self.lit_pos += 1
                if self.lit_pos == len(self.lit):
                    self._end_value()
                return True
            return False

        # ---- numbers: strict JSON grammar; they end on a delimiter, which
        # must then be re-dispatched through the post-value state
        if s in ("num_minus", "num_zero", "num_int",
                 "num_frac_start", "num_frac",
                 "num_exp_start", "num_exp_sign", "num_exp"):
            return self._accept_number(s, ch)

        if s == "trailing":
            return ch in WS

        raise AssertionError(f"unknown state {s}")

    def _closing_char(self) -> str:
        """One character moving toward the shortest valid completion."""
        s = self.state
        if s in ("value", "arr_value", "num_minus", "num_frac_start",
                 "num_exp_start", "num_exp_sign", "str_hex", "key_hex"):
            return "0"
        if s == "arr_value_or_end":
            return "]"
        if s == "obj_key_or_end":
            return "}"
        if s in ("obj_key", "str", "key"):
            return '"'
        if s in ("str_esc", "key_esc"):
            return "n"
        if s == "colon":
            return ":"
        if s == "after_value":
            return "}" if self.stack[-1] == "obj" else "]"
        if s == "lit":
            return self.lit[self.lit_pos]
        if s in ("num_zero", "num_int", "num_frac", "num_exp"):
            # number ends at the enclosing delimiter (top-level: end-of-input)
            return "}" if self.stack[-1] == "obj" else "]"
        raise AssertionError(f"no closing char for state {s}")

    def minimal_completion(self) -> str:
        """Shortest character string that completes a valid JSON value from
        the current state ('' if already complete / terminable)."""
        clone = self.clone()
        out = []
        while not clone.complete and not clone.can_terminate:
            ch = clone._closing_char()
            assert clone.accept(ch), (clone.state, ch)
            out.append(ch)
        return "".join(out)

    def _accept_number(self, s: str, ch: str) -> bool:
        cont = {
            "num_minus": {"0": "num_zero", **{d: "num_int" for d in "123456789"}},
            "num_zero": {".": "num_frac_start", "e": "num_exp_start",
                         "E": "num_exp_start"},
            "num_int": {**{d: "num_int" for d in DIGITS},
                        ".": "num_frac_start", "e": "num_exp_start",
                        "E": "num_exp_start"},
            "num_frac_start": {d: "num_frac" for d in DIGITS},
            "num_frac": {**{d: "num_frac" for d in DIGITS},
                         "e": "num_exp_start", "E": "num_exp_start"},
            "num_exp_start": {"+": "num_exp_sign", "-": "num_exp_sign",
                              **{d: "num_exp" for d in DIGITS}},
            "num_exp_sign": {d: "num_exp" for d in DIGITS},
            "num_exp": {d: "num_exp" for d in DIGITS},
        }[s]
        nxt = cont.get(ch)
        if nxt is not None:
            self.state = nxt
            return True
        # a complete number form may end at a delimiter of the enclosing
        # container; incomplete forms (num_minus, num_frac_start, ...) may not
        if s in ("num_zero", "num_int", "num_frac", "num_exp") and \
                ch in self._delimiters():
            self._end_value()
            if ch in WS:
                return True
            return self.accept(ch)   # re-dispatch ',' '}' ']'
        return False


def _token_strings(tokenizer: Tokenizer) -> List[str]:
    """Per-token decoded strings, cached ON the tokenizer instance (an
    id()-keyed module cache would leak tables and could serve a stale
    table after CPython address reuse)."""
    cached = getattr(tokenizer, "_token_strings_cache", None)
    if cached is None:
        cached = [tokenizer.decode([t]) for t in range(tokenizer.vocab_size)]
        tokenizer._token_strings_cache = cached
    return cached


def _vocab_force_tables(strings) -> Tuple[Dict[str, int], int]:
    """(single-char token map, force-close margin) for a vocab.

    The margin encodes the force-close invariant shared by every grammar:
    one sampled token can extend the minimal completion by a few chars per
    character it contains (an opening brace adds a closer, a key quote
    adds '":0', ...), while force-close emits one char per tick — so
    multi-char vocabs must start closing earlier."""
    char_token: Dict[str, int] = {}
    max_chars = 1
    for t, s in enumerate(strings):
        if len(s) == 1 and s not in char_token:
            char_token[s] = t
        max_chars = max(max_chars, len(s))
    return char_token, 2 + 4 * (max_chars - 1)


class JsonGrammar:
    """Token-level FSM guaranteeing the generated body parses as JSON.

    Constraint per step: mask to tokens whose every character the automaton
    accepts; once the top-level value is complete (or a top-level number can
    terminate and the sampled token would be trailing junk), force EOS.
    """

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer
        self.auto = JsonCharAutomaton()
        self.eos_id = tokenizer.eos_id
        self._strings = _token_strings(tokenizer)
        self._mask_cache: Dict[Tuple, np.ndarray] = {}
        # exact single-character token ids for the force-close path (encode()
        # round trips are not identity for SentencePiece-style tokenizers)
        self._char_token, self._close_margin = _vocab_force_tables(
            self._strings)

    @property
    def done(self) -> bool:
        return self.auto.complete

    def _state_key(self) -> Tuple:
        a = self.auto
        return (tuple(a.stack), a.state, a.lit, a.lit_pos, a.hex_left)

    def constraint(self, remaining: Optional[int] = None) -> Constraint:
        """``remaining``: token budget left for this sequence.  When it
        shrinks to the minimal-completion length (+2 safety margin, 1 token
        per char worst case), the FSM stops sampling and force-closes the
        value so a "length"-terminated sequence still parses."""
        if self.auto.complete:
            return Constraint(force=self.eos_id)
        if remaining is not None:
            completion = self.auto.minimal_completion()
            if remaining <= len(completion) + self._close_margin:
                if not completion:
                    return Constraint(force=self.eos_id)
                forced = self._char_token.get(completion[0])
                if forced is None:
                    # vocab has no exact single-char token for the closer
                    # (never the case for byte vocabs): end cleanly if the
                    # value can terminate, else emit what encode() gives
                    if self.auto.can_terminate:
                        return Constraint(force=self.eos_id)
                    forced = self.tokenizer.encode(completion[0])[0]
                return Constraint(force=forced)
        key = self._state_key()
        allow = self._mask_cache.get(key)
        if allow is None:
            allow = np.zeros((self.tokenizer.vocab_size,), bool)
            for t, s in enumerate(self._strings):
                if not s:
                    continue            # specials / empty decodes: never legal
                if all(c in WS for c in s):
                    # JSON never REQUIRES whitespace; banning pure-ws tokens
                    # keeps output compact instead of letting a weak model
                    # burn its budget emitting newlines
                    continue
                sim = self.auto.clone()
                if all(sim.accept(c) for c in s):
                    allow[t] = True
            if self.auto.can_terminate:
                allow[self.eos_id] = True
            self._mask_cache[key] = allow
        if not allow.any():
            # un-continuable (shouldn't happen with a byte vocab): end the
            # sequence rather than decode garbage forever
            return Constraint(force=self.eos_id)
        return Constraint(allow=allow)

    def advance(self, token: int) -> None:
        if token == self.eos_id:
            return
        for ch in self._strings[token]:
            if not self.auto.accept(ch):
                raise ValueError(
                    f"token {token} ({self._strings[token]!r}) violates the "
                    f"JSON grammar in state {self.auto.state}")


# ---------------------------------------------------------------------------
# schema-constrained decoding (structured outputs)
# ---------------------------------------------------------------------------
#
# Where JsonGrammar guarantees "some valid JSON", SchemaGrammar guarantees a
# SPECIFIC shape: fixed object keys in order, enum-constrained strings,
# bounded arrays/integers.  Punctuation and keys are *forced* (the model
# never samples them); the model only chooses at genuine decision points
# (enum continuations, free-string characters, array continue-vs-close).
# This is what makes the RCA locator stage (rca/locator.py) robust for ANY
# model: even random weights yield a plan whose DestinationKind is a real
# kind from the metagraph vocabulary — the reference can only hope GPT-4
# follows its page-long prompt (reference
# find_metapath/find_srckind_metapath_neo4j.py:212-238).
#
# Supported schema nodes (plain dicts):
#   {"const": "text"}                      literal span (internal use)
#   {"enum": ["A", "B", ...]}              one of the quoted literals
#   {"type": "string", "max_len": N,
#    "escapes": bool}                       free string; escapes=True also
#                                           admits JSON escape pairs \" \\
#                                           \/ \b \f \n \r \t (~2x the DFA
#                                           states for that field)
#   {"type": "integer", "max_digits": N}   non-negative JSON integer
#   {"type": "boolean"}                    true | false
#   {"type": "array", "items": S,
#    "min_items": a, "max_items": b}       '[' items ', '-separated ']'
#   {"type": "object", "properties":
#    [(key, S), ...]}                      fixed keys, fixed order
#   {"type": "choice", "options":
#    ["txt1", "txt2", ...]}                 RAW-text alternative (no JSON
#                                           quoting; options prefix-free) —
#                                           compiled templates (e.g. the
#                                           Cypher skeleton grammar) offer
#                                           the model a bounded choice of
#                                           complete well-formed variants
#   {"type": "seq", "items": [S, ...]}     raw concatenation of nodes (no
#                                           JSON decorations; template glue)
#   {"type": "json", "max_depth": D,
#    "max_str": L, "max_digits": N,
#    "max_items": M, "key_len": K}         BOUNDED any-JSON value: nesting
#                                           capped at D, strings/ints/
#                                           containers bounded — FINITE by
#                                           construction, so generic JSON
#                                           decode compiles to DFA tables
#                                           and rides the on-device scan
#                                           (the unbounded JsonGrammar
#                                           cannot).  Alternation handled
#                                           by first-char dispatch ('{',
#                                           '[', '"', digit, t/f/n are
#                                           disjoint).


def _compile_schema(schema: Dict, _root: bool = True) -> Tuple:
    """Schema dict -> immutable node tree.  ``_root`` tracks whether this
    node is the DOCUMENT root (nested nodes always have a following
    delimiter, which changes what can terminate — see the json node)."""
    import json as _json

    if "const" in schema:
        return ("lit", schema["const"])
    if "enum" in schema:
        cands = tuple(str(c) for c in schema["enum"])
        if not cands:
            raise ValueError("enum must be non-empty")
        for c in cands:
            if any(ch not in _STRING_CHARS for ch in c):
                raise ValueError(f"enum literal {c!r} has non-plain chars")
        return ("enum", cands)
    t = schema.get("type")
    if t == "string":
        # escapes=True additionally admits \" \\ \/ \b \f \n \r \t inside
        # the string (JSON escape pairs; ~2x the DFA states per field, so
        # it is opt-in per field — fields carrying quoted commands/JSON
        # need it, short labels don't)
        return ("str", int(schema.get("max_len", 64)),
                bool(schema.get("escapes", False)))
    if t == "integer":
        return ("int", int(schema.get("max_digits", 6)))
    if t == "boolean":
        return ("bool", ("true", "false"))
    if t == "array":
        lo = int(schema.get("min_items", 0))
        hi = int(schema.get("max_items", 8))
        if not (0 <= lo <= hi and hi >= 1):
            raise ValueError(f"bad array bounds [{lo}, {hi}]")
        return ("arr", _compile_schema(schema["items"], False), lo, hi, "[", "]")
    if t == "object":
        props = schema["properties"]
        if isinstance(props, dict):
            props = list(props.items())
        nodes: List[Tuple] = []
        for i, (key, sub) in enumerate(props):
            opener = "{" if i == 0 else ", "
            nodes.append(("lit", f"{opener}{_json.dumps(key)}: "))
            nodes.append(_compile_schema(sub, False))
        nodes.append(("lit", "}" if props else "{}"))
        return ("seq", tuple(nodes))
    if t == "choice":
        # dedup by VALUE (duplicates would leave the candidate set unable
        # to narrow to one, so the frame could never pop)
        opts = tuple(dict.fromkeys(str(o) for o in schema["options"]))
        if not opts or any(not o for o in opts):
            raise ValueError("choice options must be non-empty strings")
        for a in opts:
            for b in opts:
                if a != b and b.startswith(a):
                    # the candidate-narrowing frame pops only on a UNIQUE
                    # fully-consumed candidate; prefix pairs would make the
                    # shorter option unreachable
                    raise ValueError(
                        f"choice options must be prefix-free: {a!r} "
                        f"prefixes {b!r}")
        if len(opts) == 1:
            return ("lit", opts[0])
        # raw-text alternatives reuse the boolean machinery: "bool" is
        # exactly candidate narrowing over ("true", "false")
        return ("bool", opts)
    if t == "seq":
        items = tuple(_compile_schema(s, False) for s in schema["items"])
        if not items:
            raise ValueError("seq items must be non-empty")
        return ("seq", items)
    if t == "json":
        depth = int(schema.get("max_depth", 2))
        if not 0 <= depth <= 6:
            raise ValueError(f"json max_depth {depth} out of range [0, 6]")
        return _json_value_node(
            depth,
            max_str=int(schema.get("max_str", 32)),
            max_digits=int(schema.get("max_digits", 9)),
            max_items=int(schema.get("max_items", 6)),
            key_len=int(schema.get("key_len", 16)),
            top=_root)
    raise ValueError(f"unsupported schema node: {schema!r}")


def _json_value_node(depth: int, max_str: int, max_digits: int,
                     max_items: int, key_len: int,
                     top: bool = False) -> Tuple:
    """Bounded any-JSON value as an alternation tree.

    The int child comes first by convention when present: "alt"
    forced-closing descends into child 0, and "0" is the shortest
    closable value.  At the TOP level the bare-int child is dropped: an
    int frame pops only at a delimiter, and a document's end has none, so
    a bare top-level number could never reach the complete state (every
    container/string/keyword closes on its own last char instead)."""
    scalars = (
        ("int", max_digits),
        ("bool", ("true", "false", "null")),
        ("str", max_str, True),
    )
    if top:
        scalars = scalars[1:]
    if depth <= 0:
        return ("alt", scalars)
    sub = _json_value_node(depth - 1, max_str, max_digits, max_items,
                           key_len)
    obj_entry = ("seq", (("str", key_len, False), ("lit", ": "), sub))
    return ("alt", scalars + (
        ("arr", sub, 0, max_items, "[", "]"),
        ("arr", obj_entry, 0, max_items, "{", "}"),
    ))


def _node_first_char(node: Tuple) -> str:
    kind = node[0]
    if kind == "lit":
        return node[1][0]
    if kind in ("str", "enum"):
        return '"'
    if kind == "int":
        return "0"
    if kind == "bool":                     # also generic raw-text choices
        return min(node[1], key=len)[0]
    if kind == "arr":
        return node[4]
    if kind == "seq":
        return _node_first_char(node[1][0])
    if kind == "alt":
        return _node_first_char(node[1][0])
    raise AssertionError(node)


def _node_first_chars(node: Tuple) -> str:
    """EVERY char the node can legally start with (alt dispatch)."""
    kind = node[0]
    if kind == "lit":
        return node[1][0]
    if kind in ("str", "enum"):
        return '"'
    if kind == "int":
        return DIGITS
    if kind == "bool":
        return "".join({c[0] for c in node[1]})
    if kind == "arr":
        return node[4]
    if kind == "seq":
        return _node_first_chars(node[1][0])
    if kind == "alt":
        return "".join(_node_first_chars(c) for c in node[1])
    raise AssertionError(node)


class SchemaAutomaton:
    """Character acceptor for one schema-shaped JSON value.

    Mutable frame stack; each frame is a list whose head names the kind.
    ``accept`` consumes one character (False = illegal, state unchanged for
    the dispatching frame); ``complete`` flips when the root value closes.
    """

    __slots__ = ("stack", "complete")

    def __init__(self, root: Tuple):
        self.stack: List[List] = []
        self.complete = False
        self._push(root)

    def clone(self) -> "SchemaAutomaton":
        c = SchemaAutomaton.__new__(SchemaAutomaton)
        c.stack = [list(f) for f in self.stack]
        c.complete = self.complete
        return c

    # ------------------------------------------------------------ frames

    def _push(self, node: Tuple) -> None:
        kind = node[0]
        if kind == "lit":
            self.stack.append(["lit", node[1], 0])
        elif kind == "str":
            # [_, max_len, n, opened, esc_pending, escapes_allowed]
            self.stack.append(["str", node[1], 0, False, False, node[2]])
        elif kind == "enum":
            self.stack.append(["enum", node[1], 0, False])
        elif kind == "int":
            self.stack.append(["int", node[1], 0, False])
        elif kind == "bool":
            self.stack.append(["bool", node[1], 0])
        elif kind == "arr":
            # [_, item, lo, hi, count, state, open_ch, close_ch]
            self.stack.append(["arr", node[1], node[2], node[3], 0, "open",
                               node[4], node[5]])
        elif kind == "seq":
            self.stack.append(["seq", node[1], 0])
            self._push(node[1][0])
        elif kind == "alt":
            self.stack.append(["alt", node[1]])
        else:
            raise AssertionError(node)

    def _pop_done(self) -> None:
        """Top frame finished; unwind seq/arr parents."""
        self.stack.pop()
        while self.stack:
            top = self.stack[-1]
            if top[0] == "seq":
                top[2] += 1
                if top[2] < len(top[1]):
                    self._push(top[1][top[2]])
                    return
                self.stack.pop()
            elif top[0] == "arr":
                top[4] += 1
                top[5] = "after_item"
                return
            else:
                raise AssertionError(top)
        self.complete = True

    # ------------------------------------------------------------ accept

    def accept(self, ch: str) -> bool:
        if self.complete:
            return ch in WS
        f = self.stack[-1]
        kind = f[0]

        if kind == "lit":
            if f[1][f[2]] != ch:
                return False
            f[2] += 1
            if f[2] == len(f[1]):
                self._pop_done()
            return True

        if kind == "str":           # [_, max_len, n, opened, esc, escapes]
            if not f[3]:
                if ch == '"':
                    f[3] = True
                    return True
                return False
            if f[4]:                        # escape pending: \X pair
                if ch in _SCHEMA_ESCAPABLE:
                    f[4] = False
                    f[2] += 1
                    return True
                return False
            if ch == '"':
                self._pop_done()
                return True
            if ch == "\\" and f[5] and f[2] < f[1]:
                f[4] = True
                return True
            if ch in _STRING_CHARS and f[2] < f[1]:
                f[2] += 1
                return True
            return False

        if kind == "enum":                  # [_, cands, pos, opened]
            if not f[3]:
                if ch == '"':
                    f[3] = True
                    return True
                return False
            if ch == '"':
                if any(len(c) == f[2] for c in f[1]):
                    self._pop_done()
                    return True
                return False
            nxt = tuple(c for c in f[1] if len(c) > f[2] and c[f[2]] == ch)
            if not nxt:
                return False
            f[1] = nxt
            f[2] += 1
            return True

        if kind == "int":                   # [_, max_digits, n, leading_zero]
            if ch in DIGITS:
                if f[2] == 0:
                    f[2], f[3] = 1, ch == "0"
                    return True
                if f[3] or f[2] >= f[1]:
                    return False
                f[2] += 1
                return True
            if f[2] > 0:                    # number ends at the delimiter:
                self._pop_done()            # pop, then re-dispatch the char
                return self.accept(ch)
            return False

        if kind == "bool":                  # [_, cands, pos]
            nxt = tuple(c for c in f[1] if len(c) > f[2] and c[f[2]] == ch)
            if not nxt:
                return False
            f[1] = nxt
            f[2] += 1
            if len(f[1]) == 1 and f[2] == len(f[1][0]):
                self._pop_done()
            return True

        if kind == "arr":     # [_, item, lo, hi, count, state, open, close]
            state = f[5]
            if state == "open":
                if ch != f[6]:
                    return False
                f[5] = "first"
                return True
            if state == "first":
                if ch == f[7] and f[2] == 0:
                    self._pop_done()
                    return True
                depth = len(self.stack)      # a seq item pushes >1 frame
                f[5] = "in"
                self._push(f[1])
                if self.accept(ch):
                    return True
                del self.stack[depth:]       # illegal first char: undo
                f[5] = "first"
                return False
            if state == "after_item":
                if ch == "," and f[4] < f[3]:
                    f[5] = "sep"
                    return True
                if ch == f[7] and f[4] >= f[2]:
                    self._pop_done()
                    return True
                return False
            if state == "sep":
                if ch != " ":
                    return False
                f[5] = "in"
                self._push(f[1])
                return True
            raise AssertionError(state)

        if kind == "alt":                   # [_, children]
            for child in f[1]:
                if ch in _node_first_chars(child):
                    # commit to the unique child claiming this first char
                    self.stack.pop()
                    self._push(child)
                    return self.accept(ch)
            return False

        raise AssertionError(kind)

    # ---------------------------------------------------- forced closing

    def _min_step(self) -> Optional[str]:
        """One character of the shortest completion, or None if the step is
        a charless transition (e.g. a finished integer popping)."""
        f = self.stack[-1]
        kind = f[0]
        if kind == "lit":
            return f[1][f[2]]
        if kind == "str":
            return "n" if f[4] else '"'     # finish a pending escape first
        if kind == "enum":
            if not f[3]:
                return '"'
            best = min(f[1], key=len)
            return '"' if len(best) == f[2] else best[f[2]]
        if kind == "int":
            if f[2] == 0:
                return "0"
            self._pop_done()                # ends at delimiter: charless pop
            return None
        if kind == "bool":
            return min(f[1], key=len)[f[2]]
        if kind == "arr":
            state = f[5]
            if state == "open":
                return f[6]
            if state == "first":
                return f[7] if f[2] == 0 else _node_first_char(f[1])
            if state == "after_item":
                return f[7] if f[4] >= f[2] else ","
            if state == "sep":
                return " "
        if kind == "alt":
            # descend into child 0 (the minimal-completion child by
            # construction); charless transition
            self.stack.pop()
            self._push(f[1][0])
            return None
        raise AssertionError(f)

    def minimal_completion(self) -> str:
        clone = self.clone()
        out: List[str] = []
        for _ in range(100_000):
            if clone.complete:
                return "".join(out)
            ch = clone._min_step()
            if ch is None:
                continue
            assert clone.accept(ch), (clone.stack, ch)
            out.append(ch)
        raise AssertionError("schema completion did not converge")

    def state_key(self) -> Tuple:
        return (self.complete, tuple(tuple(f) for f in self.stack))


class SchemaGrammar:
    """Token-level FSM enforcing a schema template (structured outputs).

    Same engine protocol as JsonGrammar: ``constraint(remaining)`` /
    ``advance(token)``.  Literal spans are *forced* as the longest matching
    vocab token, so skeleton text costs one forced token per tick (one per
    char on byte vocabs) and zero sampling."""

    def __init__(self, schema: Dict, tokenizer: Tokenizer):
        self.tokenizer = tokenizer
        self.root = _compile_schema(schema)
        self.auto = SchemaAutomaton(self.root)
        self.eos_id = tokenizer.eos_id
        self._strings = _token_strings(tokenizer)
        self._mask_cache: Dict[Tuple, np.ndarray] = {}
        self._char_token, self._close_margin = _vocab_force_tables(
            self._strings)

    @property
    def done(self) -> bool:
        return self.auto.complete

    def min_budget(self) -> int:
        """Smallest max_new_tokens that can hold a valid document (worst
        case one char per token).  Budgets below this cannot terminate in a
        parseable state; EngineBackend.start rejects them."""
        return len(SchemaAutomaton(self.root).minimal_completion()) \
            + self._close_margin

    def _force_char(self, ch: str) -> Constraint:
        forced = self._char_token.get(ch)
        if forced is None:
            forced = self.tokenizer.encode(ch)[0]
        return Constraint(force=forced)

    def _forced_literal(self) -> Optional[Constraint]:
        """When the automaton sits in a literal span — or a candidate
        ("bool"/choice) frame whose remaining candidates all agree on the
        next characters — force the longest token lying entirely inside
        the agreed span.  This keeps per-request template grammars (e.g.
        the stage-2 Cypher skeleton, long literals + one branch point)
        O(1) per token: the O(V·len) mask build runs only at genuine
        divergence points."""
        f = self.auto.stack[-1] if self.auto.stack else None
        if f is None:
            return None
        if f[0] == "lit":
            upcoming = f[1][f[2]:]
        elif f[0] == "bool":
            # common prefix of all remaining candidates' suffixes
            suffixes = [c[f[2]:] for c in f[1]]
            upcoming = suffixes[0]
            for s in suffixes[1:]:
                n = min(len(upcoming), len(s))
                i = 0
                while i < n and upcoming[i] == s[i]:
                    i += 1
                upcoming = upcoming[:i]
            if not upcoming:
                return None                  # divergence point: mask
        else:
            return None
        best = self._char_token.get(upcoming[0])
        best_len = 1 if best is not None else 0
        if len(upcoming) > 1:
            for t, s in enumerate(self._strings):
                if len(s) > best_len and len(s) <= len(upcoming) \
                        and upcoming.startswith(s):
                    best, best_len = t, len(s)
        if best is None:
            return None                     # no in-span token: mask instead
        return Constraint(force=best)

    def constraint(self, remaining: Optional[int] = None) -> Constraint:
        """Budget soundness: a fixed close-margin is NOT enough for schema
        templates — one sampled ',' can commit the document to a whole
        mandatory item, jumping the minimal completion by dozens of chars.
        The mask is therefore BUDGET-AWARE: a token is legal only if the
        document can still complete within ``remaining`` after it (the
        per-token completion lengths are cached per state)."""
        if self.auto.complete:
            return Constraint(force=self.eos_id)
        forced = self._forced_literal()
        if forced is not None:
            # literal span: skip the O(V) mask build — the forced token is
            # ON the template path, so it can only shrink the completion;
            # verify the budget on it directly
            if remaining is None:
                return forced
            sim = self.auto.clone()
            ok = all(sim.accept(ch) for ch in self._strings[forced.force])
            if ok and len(sim.minimal_completion()) <= remaining - 2:
                return forced
        key = self.auto.state_key()
        entry = self._mask_cache.get(key)
        if entry is None:
            allow = np.zeros((self.tokenizer.vocab_size,), bool)
            next_len = np.full((self.tokenizer.vocab_size,),
                               np.iinfo(np.int32).max, np.int32)
            for t, s in enumerate(self._strings):
                if not s:
                    continue   # empty decodes would self-loop forever;
                # (pure-WS tokens stay legal: schema templates REQUIRE
                # their separators' whitespace, unlike free-form JSON)
                sim = self.auto.clone()
                if all(sim.accept(c) for c in s):
                    allow[t] = True
                    next_len[t] = len(sim.minimal_completion())
            self._mask_cache[key] = entry = (allow, next_len)
        allow, next_len = entry
        if remaining is not None:
            # the token itself + the completion chars (1 token/char worst
            # case) + the EOS token must all fit the budget
            allow = allow & (next_len <= remaining - 2)
        if not allow.any():
            completion = self.auto.minimal_completion()
            if not completion:          # already terminable: end cleanly
                return Constraint(force=self.eos_id)
            return self._force_char(completion[0])
        hits = np.flatnonzero(allow)
        if len(hits) == 1:
            return Constraint(force=int(hits[0]))
        return Constraint(allow=allow)

    def advance(self, token: int) -> None:
        if token == self.eos_id:
            return
        for ch in self._strings[token]:
            if not self.auto.accept(ch):
                raise ValueError(
                    f"token {token} ({self._strings[token]!r}) violates the "
                    f"schema grammar at {self.auto.stack[-1:]!r}")


def _template_text_len(node) -> int:
    """Estimated DFA state count for a choice/seq template grammar: the
    automaton has ~one state per emittable literal character, so sum the
    literal text lengths (choice options, seq items).  Non-literal
    sub-nodes fall back to their serialized length (conservative)."""
    if isinstance(node, str):
        return len(node)
    if isinstance(node, dict):
        t = node.get("type")
        if t == "choice":
            return sum(_template_text_len(o) for o in node.get("options", ()))
        if t == "seq":
            return sum(_template_text_len(i) for i in node.get("items", ()))
    import json as _json

    return len(_json.dumps(node, default=str))


def make_grammar(name, tokenizer: Tokenizer, prefer_native: bool = True):
    """GenOptions.grammar -> FSM instance (None = unconstrained).

    ``name`` may be the string "json" (any-JSON grammar; prefers the C++
    engine in native/, mask computation is O(V·len) per tick, and falls
    back to the Python FSM — the two are mask-for-mask identical,
    tests/test_native.py) or a schema dict (SchemaGrammar structured
    output)."""
    if name is None:
        return None
    if isinstance(name, dict):
        if name.get("type") in ("choice", "seq"):
            # raw-text template grammars (e.g. the per-incident Cypher
            # skeleton) are typically ONE-SHOT, so the DFA compile is pure
            # overhead for THAT run — but an interpreted slot degrades the
            # engine's WHOLE batch to per-token stepwise ticks
            # (_scan_chunk), which on dispatch-latency-dominated hosts
            # costs far more than the compile (observed: the shared-engine
            # sweep serialized onto host ticks whenever any stage-2
            # skeleton was in flight).  Compile when the estimated table
            # (one state per template char x vocab) stays small; fall back
            # to the interpreted FSM above that or on compile refusal.
            # The estimate sums the template's LITERAL text lengths — the
            # DFA has roughly one state per emittable char; counting the
            # serialized dict's keys/syntax (len(json.dumps)) overshot ~2x
            # and flipped mid-size templates to the interpreted FSM, which
            # degrades the whole shared batch to per-token host ticks.
            est_states = _template_text_len(name)
            if est_states * tokenizer.vocab_size * 5 <= \
                    _DFA_TEMPLATE_TABLE_BYTES:
                try:
                    return DFAGrammar(name, tokenizer)
                except (ValueError, MemoryError) as e:
                    get_logger(__name__).info(
                        "template DFA unavailable (%s); interpreted", e)
            return SchemaGrammar(name, tokenizer)
        # prefer the compiled DFA (tables cached per tokenizer; enables the
        # engines' on-device constrained scan); fall back to the
        # interpreted FSM when the schema's state space is too large
        try:
            return DFAGrammar(name, tokenizer)
        except (ValueError, MemoryError) as e:
            get_logger(__name__).info("schema DFA unavailable (%s); using "
                                      "the interpreted FSM", e)
            return SchemaGrammar(name, tokenizer)
    if name == "json":
        # bounded-depth DFA first: generic JSON then rides the engines'
        # on-device constrained scan like schema grammars (the unbounded
        # automaton cannot compile — VERDICT r2 item 6).  The bounds
        # restrict output to canonical JSON of modest depth/size, which is
        # strictly parseable; oversized vocabularies blow the table budget
        # and fall through to the unbounded host-side grammars.
        try:
            import time as _time

            t0 = _time.perf_counter()
            g = DFAGrammar({"type": "json"}, tokenizer)
            dt = _time.perf_counter() - t0
            if dt > 0.2:
                # the one-off BFS costs seconds; mark it so the first
                # request's latency cliff is attributable (later requests
                # hit the per-tokenizer table cache)
                get_logger(__name__).info(
                    "compiled bounded-json DFA (%d states) in %.1fs "
                    "(cached per tokenizer)", g.tables.n_states, dt)
            return g
        except (ValueError, MemoryError) as e:
            get_logger(__name__).info(
                "bounded-json DFA unavailable (%s); using the unbounded "
                "host grammar", e)
        if prefer_native:
            try:
                from k8s_llm_rca_tpu import native
                if native.available():
                    return native.NativeJsonGrammar(tokenizer)
            except Exception as e:           # toolchain/ABI trouble: fall back
                get_logger(__name__).debug("native grammar unavailable: %s", e)
        return JsonGrammar(tokenizer)
    raise ValueError(f"unknown grammar {name!r} (supported: 'json' or a "
                     f"schema dict)")


# ---------------------------------------------------------------------------
# compiled DFA: schema-constrained decode ON the device (zero host sync)
# ---------------------------------------------------------------------------
#
# SchemaAutomaton is FINITE by construction (fixed keys, bounded strings /
# arrays / integers), so the whole grammar compiles to lookup tables:
#
#   char_next  [S, C]   char-level DFA (BFS over automaton states)
#   token_next [S, V]   char DFA lifted through each token's characters
#   allow      [S, V]   token legal in state s (host mask, bit-identical)
#   dist       [S]      chars to the nearest completion (budget force-close)
#   close_tok  [S]      next token on that shortest completion path
#   complete   [S]      full document consumed -> force EOS
#
# With the FSM reduced to gathers, the jitted decode scan applies the
# grammar itself (engine.decode_scan_dfa): mask -> sample -> state
# transition, all on device — the "constrained decode that stays on the
# fast decode path" hard part of SURVEY §7, solved the TPU way.  Host-side
# DFAGrammar speaks the same constraint/advance protocol (table lookups),
# so stepwise ticks, preemption and retries keep working unchanged.

_DFA_REJECT = -1
# cap on the compiled tables' footprint: token_next int32 + allow bool per
# (state, vocab) cell.  BFS enforces it incrementally, so oversized schemas
# fail fast with ValueError and make_grammar falls back to the interpreted
# FSM instead of allocating unbounded [S, V] arrays
_DFA_MAX_TABLE_BYTES = 256 * 1024 * 1024
_DFA_FAR = np.int32(1 << 30)

# table budget for ONE-SHOT template grammars (choice/seq): smaller than
# _DFA_MAX_TABLE_BYTES because the compile amortizes over a single run —
# at 32 MB a 512-token test vocab admits ~13k template chars while a 32k
# production vocab flips long templates to the interpreted FSM (where the
# compile would cost minutes)
_DFA_TEMPLATE_TABLE_BYTES = 32 << 20


class DFATables:
    """Host (numpy) tables for one compiled schema x tokenizer."""

    __slots__ = ("token_next", "allow", "dist", "close_tok", "complete",
                 "start", "free_state", "close_margin", "eos_id",
                 "n_states", "single")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def _enumerate_char_dfa(root, alphabet: str, max_states: int):
    """BFS the automaton over ``alphabet``; returns (char_next [S, C],
    complete [S], automatons-per-state for distance bootstrapping)."""
    start = SchemaAutomaton(root)
    ids: Dict[Tuple, int] = {start.state_key(): 0}
    autos = [start]
    rows: List[List[int]] = []
    frontier = [0]
    while frontier:
        nxt_frontier: List[int] = []
        for sid in frontier:
            a = autos[sid]
            row = []
            for ch in alphabet:
                sim = a.clone()
                if not sim.accept(ch):
                    row.append(_DFA_REJECT)
                    continue
                key = sim.state_key()
                tid = ids.get(key)
                if tid is None:
                    tid = len(autos)
                    if tid >= max_states:
                        raise ValueError(
                            f"schema DFA exceeds {max_states} states "
                            f"(table budget {_DFA_MAX_TABLE_BYTES >> 20} MB)")
                    ids[key] = tid
                    autos.append(sim)
                    nxt_frontier.append(tid)
                row.append(tid)
            rows.append(row)
        frontier = nxt_frontier
    char_next = np.asarray(rows, np.int32)
    complete = np.asarray([a.complete for a in autos], bool)
    return char_next, complete


def compile_schema_dfa(schema: Dict, tokenizer: Tokenizer) -> DFATables:
    """Compile a schema to device-ready DFA tables (see module section)."""
    root = _compile_schema(schema)
    strings = _token_strings(tokenizer)
    char_token, close_margin = _vocab_force_tables(strings)

    # alphabet: every char any vocab token can emit (others always reject)
    alphabet = sorted(set("".join(strings)))
    col = {ch: i for i, ch in enumerate(alphabet)}
    max_states = max(256, _DFA_MAX_TABLE_BYTES // (5 * len(strings)))
    char_next, complete = _enumerate_char_dfa(root, alphabet, max_states)
    n = char_next.shape[0]

    # dist (chars to completion) + the closing char, by fixpoint relaxation
    dist = np.where(complete, 0, _DFA_FAR).astype(np.int64)
    close_col = np.zeros((n,), np.int32)
    # neighbor distances: dist over char_next with REJECT -> FAR
    for _ in range(n + 1):
        nb = np.where(char_next >= 0, dist[np.maximum(char_next, 0)],
                      _DFA_FAR)                        # [S, C]
        best = nb.min(axis=1)
        cand = np.minimum(dist, 1 + best)
        if (cand == dist).all():
            break
        improved = cand < dist
        close_col = np.where(improved, nb.argmin(axis=1), close_col)
        dist = cand
    if (dist >= _DFA_FAR).any():
        raise ValueError("schema DFA has states with no completion path")

    # lift the char DFA through every token's characters: [S, V]
    V = len(strings)
    max_len = max((len(s) for s in strings), default=1)
    # the alphabet is built FROM the vocab strings, so every token char
    # has a column by construction
    tok_chars = np.full((V, max_len), -1, np.int32)
    tok_len = np.zeros((V,), np.int32)
    for t, s in enumerate(strings):
        tok_len[t] = len(s)
        for i, ch in enumerate(s):
            tok_chars[t, i] = col[ch]

    cur = np.broadcast_to(np.arange(n, dtype=np.int32)[:, None],
                          (n, V)).copy()
    for pos in range(max_len):
        active = pos < tok_len                        # [V]
        chars = np.maximum(tok_chars[:, pos], 0)      # [V]
        safe = np.maximum(cur, 0)
        stepped = char_next[safe, chars[None, :]]     # [S, V]
        stepped = np.where(cur < 0, _DFA_REJECT, stepped)
        cur = np.where(active[None, :], stepped, cur)

    allow = cur >= 0
    # ban empty decodes (they would self-loop forever); pure-WS tokens stay
    # LEGAL — schema templates REQUIRE their separators' spaces, unlike
    # free-form JSON where whitespace is optional padding
    for t, s in enumerate(strings):
        if not s:
            allow[:, t] = False
    allow[:, tokenizer.eos_id] = False     # EOS is forced via `complete`
    allow[complete] = False                # complete -> force EOS

    # closing token per state: exact single-char token for the closing char
    close_tok = np.zeros((n,), np.int32)
    for s in range(n):
        if complete[s]:
            close_tok[s] = tokenizer.eos_id
            continue
        ch = alphabet[close_col[s]]
        tid = char_token.get(ch)
        if tid is None:
            # No exact single-char vocab token for this closing char: a
            # multi-char encode() fallback could land the scan's force-close
            # on a token whose extra chars derail the DFA (worst case the
            # state maps to FREE and the slot decodes unconstrained while the
            # host-side advance raises mid-serve).  Refuse to compile;
            # make_grammar falls back to the interpreted SchemaGrammar,
            # which force-closes char-by-char on the host.
            raise ValueError(
                f"closing char {ch!r} has no single-char vocab token; "
                f"schema DFA cannot force-close safely")
        close_tok[s] = tid

    # singleton states (literal spans): exactly one legal token -> the
    # host constraint can FORCE it instead of shipping a mask
    single = np.where(allow.sum(axis=1) == 1,
                      allow.argmax(axis=1), -1).astype(np.int32)

    # append the FREE row (unconstrained slots in a mixed scan batch)
    free = n
    token_next = np.concatenate(
        [np.where(cur >= 0, cur, free).astype(np.int32),
         np.full((1, V), free, np.int32)], axis=0)
    allow = np.concatenate([allow, np.ones((1, V), bool)], axis=0)
    # FREE row distance is 0: unconstrained slots must always pass the
    # budget-fits mask (their budgets are enforced by the engine, not the
    # grammar)
    dist = np.concatenate([dist.astype(np.int32), [0]])
    close_tok = np.concatenate([close_tok, [tokenizer.eos_id]])
    complete = np.concatenate([complete, [False]])
    single = np.concatenate([single, [-1]])

    return DFATables(token_next=token_next, allow=allow, dist=dist,
                     close_tok=close_tok, complete=complete, start=0,
                     free_state=free, close_margin=close_margin,
                     eos_id=tokenizer.eos_id, n_states=n + 1,
                     single=single)


def _dfa_cache_get(schema: Dict, tokenizer: Tokenizer) -> DFATables:
    """Per-tokenizer cache keyed by the canonical schema JSON (compilation
    costs seconds; serving reuses one schema for thousands of runs)."""
    import json as _json

    # no default=str: two distinct non-serializable values whose str() forms
    # collide would alias to one compiled table set.  A non-serializable
    # schema refuses here (as ValueError so make_grammar's interpreted-FSM
    # fallback applies; SchemaGrammar coerces values itself)
    try:
        key = _json.dumps(schema, sort_keys=True)
    except TypeError as e:
        raise ValueError(f"schema is not canonically JSON-serializable: {e}")
    cache = getattr(tokenizer, "_dfa_tables_cache", None)
    if cache is None:
        cache = {}
        tokenizer._dfa_tables_cache = cache
    tables = cache.get(key)
    if tables is not None:
        cache[key] = cache.pop(key)       # LRU refresh: hot schemas (the
        # per-stage plan/report) must survive one-shot skeleton churn
        if isinstance(tables, str):
            raise ValueError(tables)      # negative-cached compile refusal
        return tables
    try:
        tables = compile_schema_dfa(schema, tokenizer)
    except ValueError as e:
        # negative-cache refusals too: an uncompilable schema (state
        # blowup, vocab missing a closer token) must not re-pay the full
        # BFS + token lift on every request before falling back.  Store
        # the message only — the live exception's traceback would pin the
        # partially-built [S, V] compile arrays in the cache
        tables = str(e)
    # bound the cache: a server fed ever-changing schemas must not
    # accumulate multi-MB table sets (or unbounded refusal entries)
    # forever (FIFO eviction; dict preserves insertion order)
    while len(cache) >= 8:
        cache.pop(next(iter(cache)))
    cache[key] = tables
    if isinstance(tables, str):
        raise ValueError(tables)
    return tables


class DFAGrammar:
    """SchemaGrammar drop-in backed by compiled tables.

    Same host protocol (constraint/advance) via O(1) lookups, PLUS
    ``tables`` for the engines' on-device constrained scan
    (engine.decode_scan_dfa) — grammar slots no longer force per-token
    host ticks."""

    def __init__(self, schema: Dict, tokenizer: Tokenizer):
        self.tokenizer = tokenizer
        self.tables = _dfa_cache_get(schema, tokenizer)
        self.eos_id = tokenizer.eos_id
        self.state = self.tables.start

    @property
    def done(self) -> bool:
        return bool(self.tables.complete[self.state])

    def min_budget(self) -> int:
        return int(self.tables.dist[self.tables.start]) \
            + self.tables.close_margin

    def constraint(self, remaining: Optional[int] = None) -> Constraint:
        """Budget-aware: only tokens from which the document still
        completes within ``remaining`` are legal (dist of the successor
        state; a fixed margin is unsound for templates — see
        SchemaGrammar.constraint)."""
        t = self.tables
        if t.complete[self.state]:
            return Constraint(force=self.eos_id)
        row = t.allow[self.state]
        if remaining is not None:
            nxt = t.token_next[self.state]
            row = row & (np.where(row, t.dist[np.minimum(
                nxt, t.n_states - 1)], _DFA_FAR) <= remaining - 2)
        if not row.any():
            return Constraint(force=int(t.close_tok[self.state]))
        hits = np.flatnonzero(row)
        if len(hits) == 1:
            return Constraint(force=int(hits[0]))
        return Constraint(allow=row)

    def advance(self, token: int) -> None:
        if token == self.eos_id:
            return
        t = self.tables
        nxt = int(t.token_next[self.state, token])
        if nxt == t.free_state and not t.allow[self.state, token]:
            raise ValueError(
                f"token {token} violates the schema DFA in state "
                f"{self.state}")
        self.state = nxt
