"""Paged KV cache: block allocator, paged model entry points, engine.

vLLM-style paging re-designed for XLA's static shapes:

- the KV pool is one [L, n_pages, page_size, n_kv*d] array per k/v —
  every shape static, so prefill/decode compile once; the kv-head and
  head-dim axes are merged on the lane axis so TPU tiling doesn't pad
  head_dim 64 -> 128 (see ops/paged_attention.py and models/llama.KVCache
  for the same layout rule);
- **page 0 is the reserved trash page**: block-table entries past a
  sequence's live pages point at it, so scatter/gather indices are
  always in-bounds (JAX clamps out-of-bounds anyway, but clamping would
  silently corrupt the *last* page — the trash page makes over-writes
  harmless by construction) and the paged-attention kernel masks it out
  by length;
- the allocator is host-side and is the single owner of page ids.  It
  enforces the invariants SURVEY §5 (race detection) demands of the
  build: no double-free, no page owned by two sequences, exact leak
  accounting.  (The reference has no cache and no concurrency at all —
  its serving state lives behind the OpenAI API, reference
  common/openai_generic_assistant.py:45-51.)

Attention during decode runs through the Pallas paged-attention kernel
on TPU (ops/paged_attention.py) and its XLA reference path elsewhere.
"""

from __future__ import annotations

import functools
import itertools
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_rca_tpu.config import EngineConfig, ModelConfig
from k8s_llm_rca_tpu.engine.engine import (
    EngineBase, SequenceResult, _Active, _Pending, flash_prefill_plan,
    validate_cp_divisibility,
)
from k8s_llm_rca_tpu.engine.sampling import (
    SamplingParams, sample_tokens, sample_tokens_masked,
)
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.models import llama
from k8s_llm_rca_tpu.models.quant import dq, gather_rows
from k8s_llm_rca_tpu.models.llama import _quantize_kv
from k8s_llm_rca_tpu.ops.attention import decode_attention
from k8s_llm_rca_tpu.ops.norms import rms_norm
from k8s_llm_rca_tpu.ops.paged_attention import (
    paged_attention, paged_attention_quant, paged_attention_quant_sharded,
    paged_attention_sharded, paged_attention_xla,
)
from k8s_llm_rca_tpu.engine.prefix import (
    CACHE_OWNER, PrefixCache, PrefixStore, _page_keys,
)
from k8s_llm_rca_tpu.ops.rope import rope_frequencies
from k8s_llm_rca_tpu.runtime import profiling
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger
from k8s_llm_rca_tpu.utils.pages import (
    convert_page_record, gather_pages, pool_compatible, record_fields,
    record_nbytes, records_compatible, restore_pages, split_pages,
    stack_pages, suffix_bucket,
)
from k8s_llm_rca_tpu.utils.tokenizer import Tokenizer

log = get_logger(__name__)

TRASH_PAGE = 0

# allocator owner tag for pages stolen by an injected "oom" tick fault
# (sequence ids are >= 0; the prefix cache owns -2)
FAULT_OWNER = -3


class AllocatorError(RuntimeError):
    """Invariant violation (double free, alias, foreign page)."""


class OutOfPages(RuntimeError):
    """Pool exhausted; caller should preempt a sequence and retry."""


class PageAllocator:
    """Host-side free-list allocator over page ids 1..n_pages-1.

    Page 0 is never handed out (trash page, see module docstring).
    Every page is owned by at most one owner tag; `free` verifies
    ownership so a double-free or cross-sequence free fails loudly
    instead of silently aliasing KV state.
    """

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.n_pages = n_pages
        self._free: List[int] = list(range(1, n_pages))
        self._owner: Dict[int, int] = {}          # page -> owner tag

    @property
    def n_free(self) -> int:
        return len(self._free)

    def pages_of(self, owner: int) -> List[int]:
        return [p for p, o in self._owner.items() if o == owner]

    def alloc(self, n: int, owner: int) -> List[int]:
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free of {self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    def _push_free(self, p: int) -> None:
        """Return one validated page to the free store (subclass hook —
        the partitioned allocator routes it to the page's partition)."""
        self._free.append(p)

    def _free_pages(self) -> List[int]:
        """All free page ids (subclass hook for check())."""
        return self._free

    def free(self, pages: Sequence[int], owner: int) -> None:
        for p in pages:
            if p == TRASH_PAGE:
                raise AllocatorError("attempt to free the trash page")
            got = self._owner.get(p)
            if got is None:
                raise AllocatorError(f"double free of page {p}")
            if got != owner:
                raise AllocatorError(
                    f"page {p} owned by {got}, freed by {owner}")
            del self._owner[p]
            self._push_free(p)

    def transfer(self, pages: Sequence[int], from_owner: int,
                 to_owner: int) -> None:
        """Re-tag ownership (e.g. sequence -> prefix cache).  Validates every
        page first so a failed transfer changes nothing."""
        for p in pages:
            if p == TRASH_PAGE:
                raise AllocatorError("attempt to transfer the trash page")
            got = self._owner.get(p)
            if got is None:
                raise AllocatorError(f"transfer of free page {p}")
            if got != from_owner:
                raise AllocatorError(
                    f"page {p} owned by {got}, transferred by {from_owner}")
        for p in pages:
            self._owner[p] = to_owner

    def check(self) -> None:
        """Global invariant: free ∪ owned == all pages, disjoint."""
        free_list = self._free_pages()
        free: Set[int] = set(free_list)
        owned: Set[int] = set(self._owner)
        if free & owned:
            raise AllocatorError(f"pages both free and owned: {free & owned}")
        if len(free) != len(free_list):
            raise AllocatorError("duplicate entries in free list")
        universe = set(range(1, self.n_pages))
        if free | owned != universe:
            raise AllocatorError(
                f"leaked pages: {sorted(universe - free - owned)}")


class PartitionedPageAllocator(PageAllocator):
    """Page allocator whose id space splits into ``n_parts`` CONTIGUOUS
    partitions — the host-side twin of a pool whose page axis is sharded
    over the CP seq mesh axis (partition p's pages physically live on CP
    device p).  ``alloc`` targets one partition (a page covering sequence
    positions [j*page, (j+1)*page) must come from the device owning that
    position range, engine._page_part); ``free``/``transfer`` return each
    page to the partition its id falls in.  Invariants (no double free,
    single owner, exact leak accounting) are PageAllocator's.
    """

    def __init__(self, n_pages: int, n_parts: int):
        if n_pages % n_parts:
            raise ValueError(
                f"num_pages={n_pages} not divisible into {n_parts} "
                f"partitions (pool page axis must shard evenly)")
        super().__init__(n_pages)
        self.n_parts = n_parts
        per = n_pages // n_parts
        # partition 0 loses page 0 (the reserved trash page)
        self._free_parts: List[List[int]] = [
            list(range(max(1, i * per), (i + 1) * per))
            for i in range(n_parts)
        ]
        self._free = []          # base free list unused; see properties

    def part_of(self, page: int) -> int:
        return page * self.n_parts // self.n_pages

    @property
    def n_free(self) -> int:
        return sum(len(p) for p in self._free_parts)

    def alloc(self, n: int, owner: int, *, part: int) -> List[int]:
        # ``part`` is REQUIRED (no default): a partition-blind caller
        # falling through to the base-class signature would silently drain
        # partition 0, a misalignment check() cannot detect
        free = self._free_parts[part]
        if n > len(free):
            raise OutOfPages(
                f"need {n} pages in partition {part}, {len(free)} free "
                f"(pool total free {self.n_free}/{self.n_pages})")
        pages = [free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    # free()/check() come from PageAllocator through these hooks, so the
    # safety invariants (double-free / alias / leak detection) stay ONE
    # implementation

    def _push_free(self, p: int) -> None:
        self._free_parts[self.part_of(p)].append(p)

    def _free_pages(self) -> List[int]:
        return [p for part in self._free_parts for p in part]

    def check(self) -> None:
        for i, part in enumerate(self._free_parts):
            for p in part:
                if self.part_of(p) != i:
                    raise AllocatorError(
                        f"page {p} in wrong partition {i} "
                        f"(belongs to {self.part_of(p)})")
        super().check()


def make_allocator(n_pages: int, prefer_native: bool = True):
    """Page allocator factory: the C++ allocator (native/) when buildable,
    else the Python one — identical interface and invariants."""
    if prefer_native:
        try:
            from k8s_llm_rca_tpu import native
            if native.available():
                return native.NativePageAllocator(n_pages)
        except Exception as e:
            log.debug("native allocator unavailable: %s", e)
    return PageAllocator(n_pages)


# ---------------------------------------------------------------------------
# paged model entry points
# ---------------------------------------------------------------------------


class PagePool(NamedTuple):
    """Paged KV pool: k/v [L, n_pages, page_size, kv_dim].

    Quantized modes mirror models.llama.KVCache: int8 stores k/v as int8
    with one dynamic scale per written token (``k_scale``/``v_scale``
    [L, n_pages, page_size]); "int4" additionally nibble-packs two signed
    4-bit values per byte along kv_dim (k/v [..., kv_dim/2], the halved
    last dim is the discriminator).  The scale pools' trailing page_size
    axis lane-pads to 128, but at 2 bytes/token/layer they are noise next
    to the page payload.  Page ids index k/v and the scale pools
    identically, so block-table sharing (prefix cache) and page transfer
    need no extra bookkeeping.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_paged_cache(cfg: ModelConfig, n_pages: int, page_size: int,
                     kv_dtype=None) -> PagePool:
    shape = (cfg.n_layers, n_pages, page_size, cfg.kv_dim)
    if isinstance(kv_dtype, str) and kv_dtype == "int4":
        assert cfg.kv_dim % 2 == 0
        pshape = (*shape[:3], cfg.kv_dim // 2)
        # scale pools live in f32: they are tiny next to the pages
        # (1/kv_dim of the bytes) and f32 storage saves the quantized
        # kernel a bf16->f32 re-cast of both pools on every layer call
        return PagePool(k=jnp.zeros(pshape, jnp.int8),
                        v=jnp.zeros(pshape, jnp.int8),
                        k_scale=jnp.zeros(shape[:3], jnp.float32),
                        v_scale=jnp.zeros(shape[:3], jnp.float32))
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        return PagePool(k=jnp.zeros(shape, jnp.int8),
                        v=jnp.zeros(shape, jnp.int8),
                        k_scale=jnp.zeros(shape[:3], jnp.float32),
                        v_scale=jnp.zeros(shape[:3], jnp.float32))
    dtype = jnp.dtype(cfg.dtype)
    return PagePool(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _pool_packed(cfg: ModelConfig, pool: PagePool) -> bool:
    """True when the pool stores nibble-packed int4 KV (kv_dim halved)."""
    return pool.k.shape[-1] != cfg.kv_dim


def _gather_dequant_pages(pages: jnp.ndarray, scales: Optional[jnp.ndarray],
                          block_tables: jnp.ndarray, n_kv: int, d: int,
                          dtype, packed: bool) -> jnp.ndarray:
    """Gather a dense per-sequence KV view [B, S_max, n_kv, d] from the
    pool, dequantizing (unpack + per-token scale) when quantized."""
    b = block_tables.shape[0]
    kv = jnp.take(pages, block_tables, axis=0)      # [B, pp, page, kv']
    s = (jnp.take(scales, block_tables, axis=0)     # [B, pp, page]
         if scales is not None else None)
    kv = llama._dequant_layer(kv, s, dtype, packed)
    return kv.reshape(b, -1, n_kv, d)


def _write_pool_pages(cfg: ModelConfig, pool: PagePool, new_k, new_v,
                      page_map: jnp.ndarray, n_seq_pages: int,
                      page_size: int) -> PagePool:
    """Scatter [L, S_pad, n_kv, d] prefill KV into ``page_map`` pool pages,
    quantizing per token first when the pool is quantized (shared by the
    full and chunked prefill paths)."""
    def to_pages(a, last):
        return a.reshape(a.shape[0], n_seq_pages, page_size, last)

    k_scale, v_scale = pool.k_scale, pool.v_scale
    new_k = to_pages(new_k, cfg.kv_dim)
    new_v = to_pages(new_v, cfg.kv_dim)
    if pool.quantized:
        packed = _pool_packed(cfg, pool)
        new_k, ks = _quantize_kv(new_k, packed)
        new_v, vs = _quantize_kv(new_v, packed)
        k_scale = k_scale.at[:, page_map].set(ks)
        v_scale = v_scale.at[:, page_map].set(vs)
    return PagePool(pool.k.at[:, page_map].set(new_k),
                    pool.v.at[:, page_map].set(new_v), k_scale, v_scale)


def paged_prefill(cfg: ModelConfig, params, pool: PagePool,
                  tokens: jnp.ndarray, length: jnp.ndarray,
                  page_map: jnp.ndarray, use_flash: bool = False,
                  ep_mesh=None, flash_mesh=None, sp_mesh=None):
    """Prefill ONE sequence, scattering its KV into ``page_map`` pages.

    tokens [1, S_pad] with S_pad a multiple of page_size; page_map
    [S_pad // page_size] int32 page ids (entries past the prompt's pages
    must be TRASH_PAGE).  ``use_flash``: see llama.prefill_kv.  Returns
    (pool', logits [1, V]).
    """
    _, s_pad = tokens.shape
    page_size = pool.page_size
    assert s_pad % page_size == 0, (s_pad, page_size)
    new_k, new_v, logits = llama.prefill_kv(cfg, params, tokens, length,
                                            use_flash, ep_mesh, flash_mesh,
                                            sp_mesh)
    pool = _write_pool_pages(cfg, pool, new_k, new_v, page_map,
                             s_pad // page_size, page_size)
    return pool, logits


def _chunk_attention(cfg: ModelConfig, q, k_all, v_all, mask):
    """Masked fp32 softmax attention for chunked prefill.

    q [1, C, n_heads, d]; k_all/v_all [1, S, n_kv, d]; mask [C, S] — the
    caller builds the causal+validity mask in ABSOLUTE positions because
    the gathered prefix buffer is padded to a static page count, so buffer
    index != absolute position (ops/attention.causal_attention assumes
    they're equal and can't be reused here).
    """
    from k8s_llm_rca_tpu.ops.attention import NEG_INF, repeat_kv

    n_rep = cfg.n_heads // cfg.n_kv_heads
    # enforce the GQA invariant where it is CONSUMED: the repeat factor is
    # the global cfg ratio while the kv-head count comes from the (possibly
    # sharded) page buffer — consistent only when whole GQA groups live per
    # shard.  A mesh sharding q-heads but not kv-heads must fail loudly
    # here, not attend with the wrong repeat factor.
    assert q.shape[2] == n_rep * k_all.shape[2], (
        f"GQA repeat mismatch in _chunk_attention: q heads {q.shape[2]} != "
        f"n_rep {n_rep} (= n_heads//n_kv_heads) * local kv heads "
        f"{k_all.shape[2]} — the mesh shards q-heads and kv-heads "
        f"differently; shard whole GQA groups per device")
    k = repeat_kv(k_all, n_rep).astype(jnp.float32)
    v = repeat_kv(v_all, n_rep).astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(cfg.head_dim))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k) * scale
    mask_b = mask[None, None] if mask.ndim == 2 else mask[:, None]
    logits = jnp.where(mask_b, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out.astype(q.dtype)


def paged_prefill_batch(cfg: ModelConfig, params, pool: PagePool,
                        tokens: jnp.ndarray, lengths: jnp.ndarray,
                        page_maps: jnp.ndarray, use_flash: bool = False,
                        ep_mesh=None, flash_mesh=None, sp_mesh=None):
    """Prefill N sequences into their pool pages in ONE dispatch.

    tokens [N, S_pad] right-padded (S_pad a page multiple); lengths [N];
    page_maps [N, S_pad // page_size] int32 page ids — DISTINCT across
    rows except padding rows repeating the last real row (idempotent
    duplicate writes, same contract as llama.prefill_batch slots).
    Returns (pool', logits [N, V] at each row's last valid token).
    """
    n, s_pad = tokens.shape
    page_size = pool.page_size
    assert s_pad % page_size == 0, (s_pad, page_size)
    n_seq_pages = s_pad // page_size
    new_k, new_v, logits = llama._prefill_batch_kv(cfg, params, tokens,
                                                   lengths, use_flash,
                                                   ep_mesh, flash_mesh,
                                                   sp_mesh)
    # fold the batch dim into the page dim: the single-sequence write
    # helper scatters [L, total_pages, page, kv] by a flat page map
    pool = _write_pool_pages(
        cfg, pool, new_k.reshape(cfg.n_layers, n * s_pad, cfg.kv_dim),
        new_v.reshape(cfg.n_layers, n * s_pad, cfg.kv_dim),
        page_maps.reshape(-1), n * n_seq_pages, page_size)
    return pool, logits


def paged_prefill_cp(cfg: ModelConfig, params, pool: PagePool,
                     tokens: jnp.ndarray, length: jnp.ndarray,
                     page_map: jnp.ndarray, mesh, seq_axis: str = "seq",
                     cp_mode: str = "ring", head_axis: Optional[str] = None,
                     ep_mesh=None):
    """Context-parallel paged prefill: ring/Ulysses attention compute
    (llama.prefill_kv_cp, sequence sharded over ``mesh[seq_axis]``) with
    the page-scatter write — long prompts prefill across the ICI ring
    straight into pool pages (SURVEY §7 hard-part 6: CP correctness
    against the paged cache).  Same contract as ``paged_prefill``."""
    _, s_pad = tokens.shape
    page_size = pool.page_size
    assert s_pad % page_size == 0, (s_pad, page_size)
    new_k, new_v, logits = llama.prefill_kv_cp(cfg, params, tokens, length,
                                               mesh, seq_axis, cp_mode,
                                               head_axis, ep_mesh)
    pool = _write_pool_pages(cfg, pool, new_k, new_v, page_map,
                             s_pad // page_size, page_size)
    return pool, logits


def _chunk_layer(cfg: ModelConfig, layer, x, angles, positions, mask,
                 k_pages, v_pages, k_scales, v_scales, prefix_table,
                 dtype, packed: bool, ep_mesh=None, tp_axis=None):
    """One transformer layer of chunked prefix prefill: gather + dequant
    the layer's cached prefix pages, attend chunk-over-(prefix + chunk)
    with the absolute-position mask, finish the block.  Returns
    (x', k, v) with k/v the chunk's NEW KV [1, C, n_kv, d] — the caller
    owns the page write (plain path batches it across layers;
    the pipelined path scatters per stage with GPipe valid-masking).
    ONE implementation for all paths, so the chunk attention/mask/
    dequant contract cannot drift between them.

    ``tp_axis``: manual-TP mode for use INSIDE a shard_map stage body
    (the PP×TP prefix-hit path): the layer weights and ``k_pages``/
    ``v_pages`` are this device's shards — the prefix gather reads the
    local kv lanes (per-shard consistent with how the pipelined TP
    prefill/decode wrote them, incl. the per-shard split-half int4
    layout), attention runs on local head shards, and the row-parallel
    wo / w_down partial sums psum-combine (mirroring
    pipeline._block_prefill_tp)."""
    b, c_pad = x.shape[0], x.shape[1]
    h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
    q, k, v = llama._qkv(cfg, layer, h, angles, positions)
    # gather + dequant the cached prefix: [B, S_pre, n_kv(_local), d] —
    # the kv-head count comes from the page buffer itself so the same
    # code serves the global pool and a TP lane shard of it
    kv_lanes = k_pages.shape[-1] * (2 if packed else 1)
    n_kv = kv_lanes // cfg.head_dim
    tables = (prefix_table if prefix_table.ndim == 2
              else prefix_table[None])           # [B, pb] or [pb] -> [1, pb]
    kp = _gather_dequant_pages(
        k_pages, k_scales, tables, n_kv,
        cfg.head_dim, dtype, packed)
    vp = _gather_dequant_pages(
        v_pages, v_scales, tables, n_kv,
        cfg.head_dim, dtype, packed)
    attn = _chunk_attention(cfg, q,
                            jnp.concatenate([kp, k], axis=1),
                            jnp.concatenate([vp, v], axis=1), mask)
    out = llama._w_mm(cfg, attn.reshape(b, c_pad, -1), layer["wo"])
    if tp_axis is not None:
        x = x + jax.lax.psum(out, tp_axis)
        hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        gate = jax.nn.silu(llama._w_mm(cfg, hm, layer["w_gate"]))
        up = llama._w_mm(cfg, hm, layer["w_up"])
        x = x + jax.lax.psum(llama._w_mm(cfg, gate * up, layer["w_down"]),
                             tp_axis)
    else:
        x = x + out
        hm = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        x = x + llama._mlp(cfg, layer, hm, ep_mesh)
    return x, k, v


def paged_prefill_chunk(cfg: ModelConfig, params, pool: PagePool,
                        tokens: jnp.ndarray, chunk_len: jnp.ndarray,
                        prefix_len: jnp.ndarray, prefix_table: jnp.ndarray,
                        page_map: jnp.ndarray, ep_mesh=None):
    """Prefill the non-cached SUFFIX of a prompt whose first ``prefix_len``
    tokens' KV already sit in pool pages (prefix-cache hit).

    tokens [1, C_pad] right-padded chunk (``chunk_len`` valid), absolute
    positions ``prefix_len + i``; prefix_table [pages_per_seq] page ids
    whose first ``prefix_len // page_size`` entries hold the cached prefix
    (later entries arbitrary — masked); page_map [C_pad // page_size] new
    pages receiving the chunk's KV.  Returns (pool',
    logits [1, V] at the last valid chunk token).

    The N=1 case of ``paged_prefill_chunk_batch`` — ONE implementation
    of the chunk mask/attention/write contract, so the single and
    batched admission paths cannot drift."""
    return paged_prefill_chunk_batch(
        cfg, params, pool, tokens,
        jnp.asarray(chunk_len, jnp.int32)[None],
        jnp.asarray(prefix_len, jnp.int32)[None],
        prefix_table[None], page_map[None], ep_mesh=ep_mesh)


def paged_prefill_chunk_batch(cfg: ModelConfig, params, pool: PagePool,
                              tokens: jnp.ndarray, chunk_lens: jnp.ndarray,
                              prefix_lens: jnp.ndarray,
                              prefix_tables: jnp.ndarray,
                              page_maps: jnp.ndarray, ep_mesh=None):
    """Chunked prefix prefill of N prefix-HIT suffixes in ONE dispatch.

    The per-sequence ``paged_prefill_chunk`` forced every cache hit to
    admit single-file, so a wave of same-prefix requests paid one
    dispatch EACH while misses batch-prefill 8 at a time — measured 5x
    slower than the miss path for a 256-request same-prefix wave on the
    dispatch-bound bench host.  This batched twin keeps BOTH wins: the
    prefix-KV reuse and the single dispatch.

    tokens [N, C_pad] right-padded suffixes (C_pad a page multiple);
    chunk_lens [N] valid suffix tokens; prefix_lens [N] cached tokens
    per row; prefix_tables [N, PB] page ids whose first
    prefix_lens[i]//page entries hold row i's cached prefix (rest
    arbitrary — masked); page_maps [N, C_pad // page] new pages
    receiving each row's chunk KV (padding rows repeat a real row —
    idempotent duplicate writes, the paged_prefill_batch contract).
    Returns (pool', logits [N, V] at each row's last valid token).
    """
    n, c_pad = tokens.shape
    page_size = pool.page_size
    assert c_pad % page_size == 0, (c_pad, page_size)
    s_prefix = prefix_tables.shape[1] * page_size
    dtype = jnp.dtype(cfg.dtype)
    packed = _pool_packed(cfg, pool)

    angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = prefix_lens[:, None] + jnp.arange(c_pad)[None, :]  # [N, C]
    x = gather_rows(params["embedding"], tokens).astype(dtype)

    # per-row causal + validity mask in absolute positions
    q_pos = positions                                              # [N, C]
    k_abs = jnp.concatenate([
        jnp.broadcast_to(jnp.arange(s_prefix)[None, :], (n, s_prefix)),
        q_pos], axis=1)                                            # [N, S]
    k_valid = jnp.concatenate([
        jnp.arange(s_prefix)[None, :] < prefix_lens[:, None],
        jnp.arange(c_pad)[None, :] < chunk_lens[:, None]], axis=1)
    mask = ((q_pos[:, :, None] >= k_abs[:, None, :])
            & k_valid[:, None, :])                                 # [N, C, S]

    ks, vs = [], []
    for li, layer in enumerate(params["layers"]):
        x, k, v = _chunk_layer(
            cfg, layer, x, angles, positions, mask,
            pool.k[li], pool.v[li],
            pool.k_scale[li] if pool.quantized else None,
            pool.v_scale[li] if pool.quantized else None,
            prefix_tables, dtype, packed, ep_mesh)
        ks.append(k.reshape(n * c_pad, cfg.kv_dim))
        vs.append(v.reshape(n * c_pad, cfg.kv_dim))

    n_chunk_pages = c_pad // page_size
    pool = _write_pool_pages(
        cfg, pool, jnp.stack(ks), jnp.stack(vs),
        page_maps.reshape(-1), n * n_chunk_pages, page_size)

    last = jnp.take_along_axis(
        x, jnp.maximum(chunk_lens - 1, 0)[:, None, None], axis=1)  # [N,1,H]
    logits = llama._logits(cfg, params, last)[:, 0]                # [N, V]
    return pool, logits


def paged_decode_step(cfg: ModelConfig, params, pool: PagePool,
                      tokens: jnp.ndarray, lengths: jnp.ndarray,
                      block_tables: jnp.ndarray, *,
                      use_kernel: Optional[bool] = None, ep_mesh=None,
                      tp_mesh=None):
    """One decode step for all sequences over the paged pool.

    tokens [B]; lengths [B] tokens already cached; block_tables
    [B, pages_per_seq].  The new token's KV is written at logical
    position lengths[b], i.e. page block_tables[b, lengths[b] // page]
    offset lengths[b] % page.  Returns (pool', logits).

    Quantized pools use the quantized Pallas kernel on TPU (int8 or
    nibble-packed int4 pages + per-token scale rows) and a gather+dequant
    XLA path elsewhere.  ``tp_mesh``: run the kernel PER HEAD SHARD over
    the mesh's "model" axis (ops.paged_attention_sharded) — the engine
    passes it only for configs the shard_map wrapper supports (whole GQA
    groups per shard, unpacked pool, no CP).
    """
    b = tokens.shape[0]
    page_size = pool.page_size
    dtype = jnp.dtype(cfg.dtype)
    packed = _pool_packed(cfg, pool)
    angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = lengths[:, None]
    x = gather_rows(params["embedding"], tokens[:, None]).astype(dtype)

    page_idx = lengths // page_size
    page_ids = jnp.take_along_axis(
        block_tables, page_idx[:, None], axis=1)[:, 0]        # [B]
    offsets = lengths % page_size                             # [B]

    kernel_on = use_kernel or (use_kernel is None
                               and jax.default_backend() == "tpu"
                               and tp_mesh is None)
    if kernel_on and tp_mesh is not None and packed:
        raise ValueError("packed int4 pools cannot run the sharded kernel "
                         "(split-half packing vs head shard); the engine "
                         "gating should have routed this to XLA")
    if kernel_on and tp_mesh is not None:
        attn_fn = functools.partial(paged_attention_sharded, mesh=tp_mesh)
    elif kernel_on:
        attn_fn = paged_attention
    else:
        attn_fn = paged_attention_xla

    k_scale, v_scale = pool.k_scale, pool.v_scale
    for li, layer in enumerate(params["layers"]):
        q, k, v = llama._decode_qkv(cfg, layer, x, angles,
                                    positions)              # [B,1,·,d]
        # scatter this token's k/v: [B, n_kv*d] -> pool[li, page, off]
        k_tok = k[:, 0].reshape(b, cfg.kv_dim)
        v_tok = v[:, 0].reshape(b, cfg.kv_dim)
        if pool.quantized:
            k_tok, ks = _quantize_kv(k_tok, packed)
            v_tok, vs = _quantize_kv(v_tok, packed)
            k_scale = k_scale.at[li].set(
                k_scale[li].at[page_ids, offsets].set(ks))
            v_scale = v_scale.at[li].set(
                v_scale[li].at[page_ids, offsets].set(vs))
        kp = pool.k[li].at[page_ids, offsets].set(k_tok)
        vp = pool.v[li].at[page_ids, offsets].set(v_tok)
        pool = PagePool(pool.k.at[li].set(kp), pool.v.at[li].set(vp),
                        k_scale, v_scale)
        if pool.quantized and kernel_on and tp_mesh is not None:
            attn = paged_attention_quant_sharded(
                q[:, 0], kp, vp, k_scale[li], v_scale[li], lengths + 1,
                block_tables, tp_mesh)
        elif pool.quantized and kernel_on:
            attn = paged_attention_quant(
                q[:, 0], kp, vp, k_scale[li], v_scale[li], lengths + 1,
                block_tables, packed=packed)
        elif pool.quantized:
            k_all = _gather_dequant_pages(kp, k_scale[li], block_tables,
                                          cfg.n_kv_heads, cfg.head_dim,
                                          dtype, packed)
            v_all = _gather_dequant_pages(vp, v_scale[li], block_tables,
                                          cfg.n_kv_heads, cfg.head_dim,
                                          dtype, packed)
            attn = decode_attention(q, k_all, v_all, lengths + 1)
        else:
            attn = attn_fn(q[:, 0], kp, vp, lengths + 1, block_tables)
        x = llama._decode_finish(cfg, layer, x,
                                 attn.reshape(b, 1, cfg.q_dim), ep_mesh)

    logits = llama._logits(cfg, params, x)[:, 0]
    return pool, logits


def paged_decode_multi(cfg: ModelConfig, params, pool: PagePool,
                       tokens: jnp.ndarray, lengths: jnp.ndarray,
                       block_tables: jnp.ndarray, ep_mesh=None):
    """Multi-token paged decode (speculative verification).

    tokens [B, T]: tokens[b, 0] is the current token, the rest drafts;
    all T writes for a slot must land in ONE page (the engine bounds T by
    each slot's in-page room), so the page id is computed once per slot.
    Attention runs over the gathered page view (XLA path; T queries per
    slot don't fit the single-query Pallas kernel's grid).  Returns
    (pool', greedy [B, T], logits [B, T, V]).
    """
    from k8s_llm_rca_tpu.ops.attention import decode_attention_multi

    b, t = tokens.shape
    page_size = pool.page_size
    dtype = jnp.dtype(cfg.dtype)
    packed = _pool_packed(cfg, pool)
    angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = lengths[:, None] + jnp.arange(t)[None, :]        # [B, T]
    x = gather_rows(params["embedding"], tokens).astype(dtype)

    page_idx = lengths // page_size
    page_ids = jnp.take_along_axis(
        block_tables, page_idx[:, None], axis=1)                 # [B, 1]
    offsets = (lengths % page_size)[:, None] + jnp.arange(t)[None, :]
    pages2d = jnp.broadcast_to(page_ids, (b, t))                 # [B, T]

    k_scale, v_scale = pool.k_scale, pool.v_scale
    for li, layer in enumerate(params["layers"]):
        q, k, v = llama._decode_qkv(cfg, layer, x, angles,
                                    positions)               # [B,T,·,d]
        k_tok = k.reshape(b, t, cfg.kv_dim)
        v_tok = v.reshape(b, t, cfg.kv_dim)
        if pool.quantized:
            k_tok, ks = _quantize_kv(k_tok, packed)
            v_tok, vs = _quantize_kv(v_tok, packed)
            k_scale = k_scale.at[li].set(
                k_scale[li].at[pages2d, offsets].set(ks))
            v_scale = v_scale.at[li].set(
                v_scale[li].at[pages2d, offsets].set(vs))
        kp = pool.k[li].at[pages2d, offsets].set(k_tok)
        vp = pool.v[li].at[pages2d, offsets].set(v_tok)
        pool = PagePool(pool.k.at[li].set(kp), pool.v.at[li].set(vp),
                        k_scale, v_scale)
        # gathered dense view [B, S_max, n_kv, d] for the multi-query mask
        k_all = _gather_dequant_pages(
            kp, k_scale[li] if pool.quantized else None, block_tables,
            cfg.n_kv_heads, cfg.head_dim, dtype, packed)
        v_all = _gather_dequant_pages(
            vp, v_scale[li] if pool.quantized else None, block_tables,
            cfg.n_kv_heads, cfg.head_dim, dtype, packed)
        attn = decode_attention_multi(q, k_all, v_all, lengths + 1)
        x = llama._decode_finish(cfg, layer, x,
                                 attn.reshape(b, t, cfg.q_dim), ep_mesh)

    logits = llama._logits(cfg, params, x)                       # [B, T, V]
    return pool, jnp.argmax(logits, axis=-1), logits


def paged_decode_scan(cfg: ModelConfig, params, pool: PagePool,
                      cur_tokens: jnp.ndarray, lengths: jnp.ndarray,
                      block_tables: jnp.ndarray, key, n_steps: int,
                      sampling: SamplingParams, eos_id: int,
                      use_kernel: Optional[bool] = None, ep_mesh=None,
                      tp_mesh=None, decode_fn=None):
    """``n_steps`` paged decode steps with zero host sync (the paged
    engine's chunked tick).  ``block_tables`` stays static for the whole
    scan; each per-step write indexes it dynamically (lengths // page),
    so the scan may cross page boundaries into pages the caller
    PRE-ALLOCATED for the window — the caller bounds ``n_steps`` by each
    slot's contiguous allocated run (engine._chunk_bound).

    Returns (pool', tokens [n_steps, B], lengths').  Slots
    that hit ``eos_id`` stop advancing (token repeats; host trims).
    ``decode_fn``: optional (cfg, params, pool, tokens, lengths,
    block_tables) -> (pool, logits) override (the PP engine's pipelined
    step)."""

    def body(carry, _):
        pool, cur, lens, done, key = carry
        if decode_fn is None:
            pool, logits = paged_decode_step(cfg, params, pool, cur, lens,
                                             block_tables,
                                             use_kernel=use_kernel,
                                             ep_mesh=ep_mesh,
                                             tp_mesh=tp_mesh)
        else:
            pool, logits = decode_fn(cfg, params, pool, cur, lens,
                                     block_tables)
        key, sub = jax.random.split(key)
        nxt = sample_tokens(logits, sub, sampling)
        newly_done = done | (nxt == eos_id)
        advance = jnp.logical_not(done)
        cur = jnp.where(advance, nxt, cur)
        lens = lens + advance.astype(lens.dtype)
        return (pool, cur, lens, newly_done, key), cur

    done0 = jnp.zeros_like(cur_tokens, dtype=bool)
    (pool, _, lengths, _, _), toks = jax.lax.scan(
        body, (pool, cur_tokens, lengths, done0, key), None,
        length=n_steps)
    return pool, toks, lengths


def paged_decode_scan_dfa(cfg: ModelConfig, params, pool: PagePool,
                          cur_tokens: jnp.ndarray, lengths: jnp.ndarray,
                          block_tables: jnp.ndarray, key, n_steps: int,
                          sampling: SamplingParams, eos_id: int,
                          states: jnp.ndarray, remaining: jnp.ndarray,
                          allow_t: jnp.ndarray, next_t: jnp.ndarray,
                          dist_t: jnp.ndarray, close_t: jnp.ndarray,
                          complete_t: jnp.ndarray,
                          use_kernel: Optional[bool] = None, ep_mesh=None,
                          tp_mesh=None, decode_fn=None):
    """``paged_decode_scan`` with the compiled grammar DFA riding inside
    the scan (mirrors engine.decode_scan_dfa: budget-aware mask, sample,
    state transition — all gathers on device).  Returns
    (pool', tokens [n_steps, B], lengths', states')."""

    from k8s_llm_rca_tpu.engine.engine import dfa_scan_step

    def body(carry, _):
        pool, cur, lens, done, states, remaining, key = carry
        if decode_fn is None:
            pool, logits = paged_decode_step(cfg, params, pool, cur, lens,
                                             block_tables,
                                             use_kernel=use_kernel,
                                             ep_mesh=ep_mesh,
                                             tp_mesh=tp_mesh)
        else:
            pool, logits = decode_fn(cfg, params, pool, cur, lens,
                                     block_tables)
        cur, lens, done, states, remaining, key = dfa_scan_step(
            logits, cur, lens, done, states, remaining, key, sampling,
            eos_id, allow_t, next_t, dist_t, close_t, complete_t)
        return (pool, cur, lens, done, states, remaining, key), cur

    done0 = jnp.zeros_like(cur_tokens, dtype=bool)
    (pool, _, lengths, _, states, _, _), toks = jax.lax.scan(
        body, (pool, cur_tokens, lengths, done0, states, remaining, key),
        None, length=n_steps)
    return pool, toks, lengths, states


def paged_overlap_step(cfg: ModelConfig, params, pool: PagePool,
                       cur_tokens: jnp.ndarray, lengths: jnp.ndarray,
                       block_tables: jnp.ndarray, key,
                       sampling: SamplingParams, cap: int,
                       use_kernel: Optional[bool] = None, ep_mesh=None,
                       tp_mesh=None, decode_fn=None):
    """One fused hot-loop step for the overlapped paged engine: decode +
    RNG split + sample + length advance in a single dispatch over the
    device-resident state (docs/performance.md).

    ``jax.random.split`` is deterministic, so splitting in-jit yields the
    identical subkey stream as the plain tick's host-side split — sampled
    tokens match token-for-token.  ALL slots advance (clamped at ``cap``,
    the last in-table position): a slot whose sequence already finished
    on the host keeps decoding garbage until the lagged flush retires it,
    which is safe because its tokens are never committed and its block-
    table row is reset to the trash page at retirement, so the garbage KV
    lands in page 0 (never attended).  Returns (pool', next_tokens,
    lengths', key')."""
    if decode_fn is None:
        pool, logits = paged_decode_step(cfg, params, pool, cur_tokens,
                                         lengths, block_tables,
                                         use_kernel=use_kernel,
                                         ep_mesh=ep_mesh, tp_mesh=tp_mesh)
    else:
        pool, logits = decode_fn(cfg, params, pool, cur_tokens, lengths,
                                 block_tables)
    key, sub = jax.random.split(key)
    nxt = sample_tokens(logits, sub, sampling)
    lengths = jnp.minimum(lengths + 1, cap).astype(lengths.dtype)
    return pool, nxt, lengths, key


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


class PagedInferenceEngine(EngineBase):
    """Continuous batching over the paged pool with on-demand page growth
    and preemption.

    Differences from engine.InferenceEngine (contiguous):
    - pages are allocated per sequence: ceil(prompt/page) at admission,
      +1 page whenever decode crosses a page boundary;
    - if the pool is exhausted when an active sequence must grow, the
      **youngest** active sequence is preempted: its pages are freed and it
      is requeued with prompt+generated as the new prompt (SURVEY §5
      failure-recovery: engine-level preemption/requeue).  Admission never
      preempts — queued requests wait for retirements instead of evicting
      running work;
    - block tables live on the host (numpy) and ship to the device as a
      [B, pages_per_seq] int32 each tick (tiny).
    """

    def __init__(self, model_cfg: ModelConfig, engine_cfg: EngineConfig,
                 params, tokenizer: Tokenizer,
                 use_kernel: Optional[bool] = None,
                 cp_mesh=None, cp_seq_axis: str = "seq",
                 cp_mode: str = "ring", ep_mesh=None, tp_mesh=None,
                 fsdp_mesh=None,
                 pp_mesh=None, pp_microbatches: Optional[int] = None,
                 pp_stage_axis: str = "stage", sp: bool = False,
                 draft_model=None, prefix_store: Optional[PrefixStore] = None):
        """``cp_mesh``: optional Mesh with a ``cp_seq_axis`` axis — prefill
        runs context-parallel over it (ring or Ulysses, as in the
        contiguous engine) and scatters the full-depth KV into pool pages.
        With axis size P > 1 the pool's PAGE axis is sharded over the
        axis and allocation is partition-aligned (PartitionedPageAllocator:
        a sequence's page j comes from the device owning positions
        [j*page, (j+1)*page)), so each device stores 1/P of a long
        context's paged KV — the same memory win as the contiguous CP
        cache.  Requires page-rounded buckets divisible by the axis size
        plus pages_per_seq and num_pages divisible by P, disables batched
        admission (prefill_kv_cp is per-sequence) and is mutually
        exclusive with the prefix cache (the chunked prefix prefill is not
        context-parallel)."""
        if cp_mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown cp_mode {cp_mode!r}")
        if sp and (tp_mesh is None or cp_mesh is not None
                   or pp_mesh is not None):
            raise ValueError("sp=True (Megatron sequence parallelism) "
                             "requires tp_mesh, is exclusive with cp_mesh "
                             "(CP already seq-shards activations), and is "
                             "unsupported on the PP paths (the pipelined "
                             "prefill/decode do not thread sp_mesh)")
        from k8s_llm_rca_tpu.engine.engine import (
            params_multi_device, validate_ep_mesh, validate_fsdp_mesh,
            validate_pp_mesh, validate_tp_mesh,
        )
        validate_ep_mesh(ep_mesh, model_cfg, engine_cfg, cp_mesh,
                         cp_seq_axis)
        validate_tp_mesh(tp_mesh, model_cfg, engine_cfg, cp_mesh,
                         cp_seq_axis)
        validate_fsdp_mesh(fsdp_mesh, model_cfg, engine_cfg, tp_mesh=tp_mesh,
                           cp_mesh=cp_mesh, ep_mesh=ep_mesh, pp_mesh=pp_mesh,
                           sp=sp)
        self._pp_m = validate_pp_mesh(pp_mesh, model_cfg, engine_cfg,
                                      cp_mesh, ep_mesh, tp_mesh,
                                      pp_microbatches, pp_stage_axis,
                                      params=params)
        self._pp = pp_mesh is not None
        if self._pp:
            if engine_cfg.prefix_cache and ep_mesh is not None:
                raise ValueError(
                    "prefix_cache composes with stage-only PP and PP×TP "
                    "(the pipelined chunked prefix prefill runs the "
                    "manual-TP chunk layer); it is not EP-composed — "
                    "use prefix_cache=False under PP×EP")
            if use_kernel:
                raise ValueError(
                    "use_kernel=True is incompatible with pp_mesh (the "
                    "pipelined decode reads the gathered XLA page view)")
            use_kernel = False
        # Pallas has no SPMD partitioning rule, so a sharded config can
        # only run the kernel PER HEAD SHARD via shard_map
        # (ops.paged_attention_sharded, the flash_attention_sharded
        # pattern).  That needs: the TP mesh itself, whole GQA groups per
        # shard, a page axis that is NOT seq-sharded (CP pools distribute
        # pages across devices), and an unpacked pool (int4's split-half
        # nibble packing does not commute with the head shard).
        self._kernel_mesh = None
        if (tp_mesh is not None or cp_mesh is not None
                or fsdp_mesh is not None or params_multi_device(params)):
            n_tp = tp_mesh.shape["model"] if tp_mesh is not None else 0
            sharded_ok = (tp_mesh is not None and cp_mesh is None
                          and fsdp_mesh is None
                          and n_tp > 0
                          and model_cfg.n_heads % n_tp == 0
                          and model_cfg.n_kv_heads % n_tp == 0
                          and engine_cfg.kv_cache_dtype != "int4")
            if use_kernel and not sharded_ok:
                raise ValueError(
                    "use_kernel=True under sharding requires a tp_mesh "
                    "with n_heads/n_kv_heads divisible by its 'model' "
                    "axis, no cp_mesh (the CP pool's page axis is "
                    "seq-sharded), no fsdp_mesh (the head-sharded "
                    "shard_map would consume a weight shard as the full "
                    "tensor), and kv_cache_dtype != 'int4' (nibble "
                    "packing does not commute with the head shard); pass "
                    "use_kernel=None/False to serve this config on the "
                    "XLA paged-attention path")
            if use_kernel is None:
                use_kernel = bool(sharded_ok
                                  and jax.default_backend() == "tpu")
            if use_kernel:
                self._kernel_mesh = tp_mesh
        if engine_cfg.host_overlap and cp_mesh is not None:
            raise ValueError(
                "host_overlap=True is unsupported with cp_mesh: CP admits "
                "per-sequence through prefill_kv_cp and its multi-process "
                "host_np collectives must line up SPMD-identically across "
                "processes — a lagged commit would reorder them; serve CP "
                "engines with host_overlap=False")
        pcb = engine_cfg.prefill_chunk_budget
        if pcb:
            if pcb < 0 or pcb % engine_cfg.page_size:
                raise ValueError(
                    f"prefill_chunk_budget={pcb} must be a positive "
                    f"multiple of page_size={engine_cfg.page_size}: each "
                    f"per-tick chunk scatters whole pages, so its growing "
                    f"prefix stays page-aligned for the next chunk's "
                    f"gather")
            if cp_mesh is not None:
                raise ValueError(
                    "prefill_chunk_budget is unsupported with cp_mesh "
                    "(the chunk-prefill path is not context-parallel; CP "
                    "prefills whole sequences through prefill_kv_cp)")
            if pp_mesh is not None:
                raise ValueError(
                    "prefill_chunk_budget is unsupported with pp_mesh: "
                    "the pipelined chunk prefill serves whole prefix-hit "
                    "admissions within one tick; spreading one admission "
                    "across ticks would interleave its stage schedule "
                    "with the GPipe decode microbatches — serve PP "
                    "engines with prefill_chunk_budget=0")
        msp = engine_cfg.max_spilled_pages
        if msp:
            if msp < 0:
                raise ValueError(
                    f"max_spilled_pages={msp} must be >= 0 (0 disables "
                    f"KV spill-to-host preemption)")
            if cp_mesh is not None:
                raise ValueError(
                    "max_spilled_pages (KV spill-to-host) is unsupported "
                    "with cp_mesh: the CP pool's PAGE axis is sequence-"
                    "sharded, so one logical page is not one host buffer "
                    "— a spill gather/restore scatter would reshard the "
                    "pool through host memory every preemption; serve CP "
                    "engines with max_spilled_pages=0 (free-and-re-"
                    "prefill)")
            if pp_mesh is not None:
                raise ValueError(
                    "max_spilled_pages (KV spill-to-host) is unsupported "
                    "with pp_mesh: the pool's LAYER axis is stage-sharded "
                    "(possibly across hosts over DCN), so spill d2h / "
                    "restore h2d would issue cross-stage collectives that "
                    "must interleave with the GPipe microbatch schedule "
                    "deterministically on every process; serve PP engines "
                    "with max_spilled_pages=0 (free-and-re-prefill)")
        tiered = bool(engine_cfg.prefix_host_pages
                      or engine_cfg.prefix_disk_dir
                      or engine_cfg.prefix_disk_pages
                      or prefix_store is not None)
        if tiered:
            if engine_cfg.prefix_host_pages < 0:
                raise ValueError(
                    f"prefix_host_pages={engine_cfg.prefix_host_pages} "
                    f"must be >= 0 (0 disables the host-RAM prefix tier)")
            if engine_cfg.prefix_disk_pages < 0:
                raise ValueError(
                    f"prefix_disk_pages={engine_cfg.prefix_disk_pages} "
                    f"must be >= 0 (0 with prefix_disk_dir = unbounded)")
            if engine_cfg.prefix_disk_pages and not engine_cfg.prefix_disk_dir:
                raise ValueError(
                    f"prefix_disk_pages={engine_cfg.prefix_disk_pages} "
                    f"needs prefix_disk_dir: the cap bounds a disk tier "
                    f"that does not exist without a directory")
            if not engine_cfg.prefix_cache:
                raise ValueError(
                    "the tiered prefix cache (prefix_host_pages / "
                    "prefix_disk_dir / prefix_disk_pages / a shared "
                    "prefix_store) requires prefix_cache=True: the tiers "
                    "demote FROM and promote INTO the resident L0 chain "
                    "— without it there is nothing to key pages by")
            if cp_mesh is not None:
                raise ValueError(
                    "the tiered prefix cache is unsupported with cp_mesh: "
                    "the CP pool's PAGE axis is sequence-sharded, so one "
                    "logical page is not one host buffer — a demote "
                    "gather / promote scatter would reshard the pool "
                    "through host memory (and cp_mesh already requires "
                    "prefix_cache=False); serve CP engines without the "
                    "prefix tier knobs")
            if pp_mesh is not None:
                raise ValueError(
                    "the tiered prefix cache is unsupported with pp_mesh: "
                    "the pool's LAYER axis is stage-sharded (possibly "
                    "across hosts over DCN), so demote d2h / promote h2d "
                    "would issue cross-stage collectives that must "
                    "interleave with the GPipe microbatch schedule "
                    "deterministically on every process — the same "
                    "physics as the max_spilled_pages exclusion; serve "
                    "PP engines without the prefix tier knobs")
        if engine_cfg.prefix_hbm_watermark:
            if engine_cfg.prefix_hbm_watermark < 0:
                raise ValueError(
                    f"prefix_hbm_watermark="
                    f"{engine_cfg.prefix_hbm_watermark} must be >= 0 "
                    f"(0 disables pressure-driven demotion)")
            if not engine_cfg.prefix_cache:
                raise ValueError(
                    "prefix_hbm_watermark requires prefix_cache=True: "
                    "pressure-driven demotion frees refcount-0 PREFIX "
                    "pages — without a prefix cache there is nothing "
                    "evictable to demote")
            if engine_cfg.prefix_hbm_watermark >= engine_cfg.num_pages:
                raise ValueError(
                    f"prefix_hbm_watermark="
                    f"{engine_cfg.prefix_hbm_watermark} is over capacity "
                    f"(num_pages={engine_cfg.num_pages}): a watermark at "
                    f"or above the whole pool demotes every cached page "
                    f"the moment one sequence admits — the policy "
                    f"degenerates to prefix_cache=False with extra "
                    f"gathers; pick a watermark below num_pages")
        if engine_cfg.prefix_store_writethrough and not tiered:
            raise ValueError(
                "prefix_store_writethrough=True without a store "
                "(prefix_host_pages / prefix_disk_dir / prefix_disk_pages "
                "/ a shared prefix_store): write-through publishes "
                "resident chains TO a store — with nowhere to write it "
                "is a config bug, not a degraded mode")
        self._cp_parts = 0
        if cp_mesh is not None:
            if engine_cfg.prefix_cache:
                raise ValueError(
                    "cp_mesh requires prefix_cache=False (the chunked "
                    "prefix prefill path is not context-parallel)")
            page = engine_cfg.page_size
            validate_cp_divisibility(
                cp_seq_axis, cp_mesh.shape[cp_seq_axis],
                [-(-s // page) * page           # page-rounded, as _bucket does
                 for s in tuple(engine_cfg.prefill_buckets)
                 + (engine_cfg.max_seq_len,)])
            n_cp = cp_mesh.shape[cp_seq_axis]
            if n_cp > 1:
                # seq-sharded pool: each CP device owns the page RANGE
                # covering its sequence shard, so long-context paged
                # serving stores 1/P of the KV bytes per device — the
                # memory win the contiguous CP cache already has
                pages_per_seq = -(-engine_cfg.max_seq_len
                                  // engine_cfg.page_size)
                if pages_per_seq % n_cp:
                    raise ValueError(
                        f"max_seq_len={engine_cfg.max_seq_len} spans "
                        f"{pages_per_seq} pages, not divisible into "
                        f"{n_cp} CP partitions (page-aligned CP splits "
                        f"need pages_per_seq % n_cp == 0)")
                if engine_cfg.num_pages % n_cp:
                    raise ValueError(
                        f"num_pages={engine_cfg.num_pages} not divisible "
                        f"by the CP axis {n_cp} (the pool page axis "
                        f"shards evenly)")
                self._cp_parts = n_cp
        self._batch_admission = cp_mesh is None
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.params = params
        self.tokenizer = tokenizer
        self.use_kernel = use_kernel
        from k8s_llm_rca_tpu.engine.engine import setup_draft

        self._draft = setup_draft(draft_model, model_cfg, engine_cfg)
        if self._draft is not None:
            # account the draft scan's blocking token fetch with the
            # engine's own sync counter (docs/performance.md)
            self._draft.on_sync = (
                lambda: self._count("engine.d2h_syncs"))
        self.sampling = SamplingParams(
            temperature=engine_cfg.temperature,
            top_k=engine_cfg.top_k,
            top_p=engine_cfg.top_p,
        )

        b = engine_cfg.max_batch
        self.page_size = engine_cfg.page_size
        self.pages_per_seq = -(-engine_cfg.max_seq_len // self.page_size)
        if (engine_cfg.speculative_k > 0
                and engine_cfg.speculative_k + 1 > self.page_size):
            # _spec_room_ok could never hold: speculation would silently
            # never fire.  Fail loudly on the impossible config instead.
            raise ValueError(
                f"speculative_k={engine_cfg.speculative_k} needs "
                f"k+1 <= page_size={self.page_size} (all verify-step "
                f"writes must fit one page)")
        if engine_cfg.num_pages - 1 < self.pages_per_seq:
            # guarantees any single sequence is admittable once the pool is
            # drained, so preemption always makes progress
            raise ValueError(
                f"num_pages={engine_cfg.num_pages} cannot hold one full "
                f"sequence ({self.pages_per_seq} pages + trash page)")
        if engine_cfg.kv_cache_dtype not in (None, "int8", "int4"):
            raise ValueError(
                f"unsupported kv_cache_dtype {engine_cfg.kv_cache_dtype!r} "
                f"(None, 'int8' or 'int4')")
        self.pool = init_paged_cache(
            model_cfg, engine_cfg.num_pages, self.page_size,
            kv_dtype=engine_cfg.kv_cache_dtype)
        if self._cp_parts:
            # CP seq-sharded pool: the PAGE axis shards over the seq mesh
            # axis — device p holds pages [p*N/P, (p+1)*N/P), exactly the
            # range the partitioned allocator draws from for sequence
            # positions [p*S/P, (p+1)*S/P) (page-aligned CP splits); with
            # CP×TP the merged kv axis additionally shards over "model".
            # Scale pools shard their page axis the same way.
            from jax.sharding import PartitionSpec as _P

            from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

            cp_kv_spec = _P(None, cp_seq_axis, None,
                            "model" if tp_mesh is not None else None)
            cp_scale_spec = _P(None, cp_seq_axis, None)
            self.pool = shard_pytree(
                self.pool,
                PagePool(cp_kv_spec, cp_kv_spec, cp_scale_spec,
                         cp_scale_spec),
                cp_mesh)
        elif pp_mesh is not None and tp_mesh is not None:
            # paged PP×TP: the pool's LAYER axis shards over "stage" AND
            # its merged kv axis over "model" — each device holds its
            # stage's layers × its TP shard of every page (the realistic
            # multi-host serving shape: paged KV, stages over DCN, TP
            # over ICI).  Scale pools shard layer-over-stage and
            # replicate across model (every TP shard writes the identical
            # pmax full-row scale — llama._quantize_kv axis_name).
            from k8s_llm_rca_tpu.parallel.pipeline import (
                kv_cache_stage_specs, kv_scale_stage_specs,
            )
            from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

            kv_spec = kv_cache_stage_specs("model", pp_stage_axis)
            self.pool = shard_pytree(
                self.pool,
                PagePool(kv_spec, kv_spec, kv_scale_stage_specs(pp_stage_axis),
                         kv_scale_stage_specs(pp_stage_axis)),
                pp_mesh)
        elif tp_mesh is not None or fsdp_mesh is not None:
            # pool pages sharded on the merged kv axis over "model": each
            # device stores 1/P of every page's bytes (the paged analog of
            # kv_cache_specs); tiny per-token scale pools replicate.  fsdp
            # never shards the pool (rules.paged_pool_specs) — an
            # fsdp-only mesh places it on the weights' device set with the
            # "model" axis degenerate
            from k8s_llm_rca_tpu.runtime.sharding import (
                paged_pool_specs, shard_pytree,
            )

            pool_spec, scale_spec = paged_pool_specs()
            self.pool = shard_pytree(
                self.pool,
                PagePool(pool_spec, pool_spec, scale_spec, scale_spec),
                tp_mesh if tp_mesh is not None else fsdp_mesh)
        elif pp_mesh is not None:
            # PP serving: the pool's LAYER axis shards over "stage" —
            # each device holds only its stage's layers' pages (the cache
            # half of the per-stage split; weights below)
            from k8s_llm_rca_tpu.parallel.pipeline import (
                kv_cache_stage_specs, kv_scale_stage_specs,
            )
            from k8s_llm_rca_tpu.runtime.sharding import shard_pytree

            self.pool = shard_pytree(
                self.pool,
                PagePool(kv_cache_stage_specs(), kv_cache_stage_specs(),
                         kv_scale_stage_specs(pp_stage_axis), kv_scale_stage_specs(pp_stage_axis)),
                pp_mesh)
        if self._cp_parts:
            # partition-aware allocation has no C++ twin (the native
            # allocator is partition-blind); the Python partitioned
            # allocator keeps identical invariants
            self.allocator = PartitionedPageAllocator(engine_cfg.num_pages,
                                                      self._cp_parts)
        else:
            self.allocator = make_allocator(engine_cfg.num_pages,
                                            engine_cfg.native)
        # tiered prefix cache (docs/performance.md): a passed store is
        # SHARED (cluster warm-start — build_replicas / supervisor
        # restarts hand every incarnation the same one); otherwise the
        # tier knobs build a private store.  The demote/promote hooks
        # close over this engine's pool; ``count=self._count`` routes
        # tier-hit counters into the TickSample/Prometheus mirrors.
        self.prefix_store = prefix_store
        if tiered and self.prefix_store is None:
            self.prefix_store = PrefixStore(
                host_pages=engine_cfg.prefix_host_pages,
                disk_dir=engine_cfg.prefix_disk_dir,
                disk_pages=engine_cfg.prefix_disk_pages)
        if self.prefix_store is not None and hasattr(self.prefix_store,
                                                     "bind_count"):
            # a RemoteStore (cluster/store.py) counts its degraded ops
            # through the engine's _count so misses reach TickSample /
            # Chrome / Prometheus alongside the other prefix counters
            self.prefix_store.bind_count(self._count)
        self.prefix_cache = (
            PrefixCache(self.allocator, self.page_size,
                        store=self.prefix_store,
                        demote=self._demote_prefix_pages,
                        promote=self._promote_prefix_records,
                        count=self._count)
            if engine_cfg.prefix_cache else None)
        # pressure-driven demotion + write-through (docs/performance.md
        # "cache fabric"): both act at tick boundaries in the eviction
        # phase; _wt_resident tracks the last flushed resident count so
        # write-through only pays a store sweep on growth
        self._hbm_watermark = int(engine_cfg.prefix_hbm_watermark)
        self._writethrough = bool(engine_cfg.prefix_store_writethrough
                                  and self.prefix_store is not None)
        self._wt_resident = 0

        self.block_tables = np.full((b, self.pages_per_seq), TRASH_PAGE,
                                    np.int32)
        self.lengths = np.zeros((b,), np.int64)
        self.cur_tokens = np.zeros((b,), np.int64)
        self._key = jax.random.PRNGKey(engine_cfg.seed)
        # overlapped hot loop state (EngineBase machinery + the paged
        # device-resident mirrors; docs/performance.md).  _dev_* hold the
        # decode operands on device between ticks; _dev_dirty is the
        # single invalidation point (host mirrors changed wholesale —
        # re-upload before the next dispatch).  _inflight_n counts each
        # slot's dispatched-but-uncommitted fast-path steps so growth
        # covers the DEVICE length, not the lagging host mirror.
        self._overlap = engine_cfg.host_overlap
        self._inflight = []
        self._admit_pending = []
        self._flushed_out = []
        self._inflight_n: Dict[int, int] = {}
        self._dev_cur = None
        self._dev_lens = None
        self._dev_bt = None
        self._dev_dirty = True
        # fused-step clamp: the last in-table position (see
        # paged_overlap_step's garbage-containment contract)
        self._dev_cap = self.pages_per_seq * self.page_size - 1

        self._free_slots = list(range(b))
        self._active: Dict[int, _Active] = {}
        self._pending: List[_Pending] = []
        # slot -> in-progress chunked-prefill state (prefill_chunk_budget):
        # the request, its full page table (held OUT of block_tables until
        # activation — an inactive slot's block-table row must stay
        # TRASH_PAGE so decode garbage writes are contained), the acquired
        # cached-prefix pages, the freshly allocated pages, and the
        # tokens-written watermark
        self._prefilling: Dict[int, Dict[str, object]] = {}
        self._seq_counter = itertools.count()
        self._prompts: Dict[int, List[int]] = {}   # seq_id -> ORIGINAL prompt
        self._resumed: Dict[int, List[int]] = {}   # seq_id -> pre-preemption
                                                   #           generated tokens
        self._fault_pages: List[int] = []   # pages stolen by an injected
                                            # "oom" tick fault (one tick)
        # KV spill-to-host (engine_cfg.max_spilled_pages; docs/serving.md
        # "overload & priorities"): seq_id -> host record {k, v, k_scale,
        # v_scale (np arrays, [L, n, page, ...]), n_pages, n_shared,
        # shared_pages, length, cur_token}.  The sequence itself waits in
        # _pending (so snapshot/cancel see it normally); _tick_admission
        # restores it by h2d page scatter instead of re-prefill.
        self._spilled: Dict[int, Dict[str, object]] = {}
        self._spilled_pages_total = 0

        # donate the KV pool so XLA updates it in place — without donation
        # every tick copies the whole pool and peak HBM doubles.  (CPU has
        # no donation support and would warn on every compile, so gate it.)
        donate = (2,) if jax.default_backend() == "tpu" else ()
        pp_decode_fn = None
        pp_decode_multi_fn = None
        if pp_mesh is not None:
            # PP serving: layers restacked [P, L/P, ...] and sharded over
            # "stage"; self.params becomes (non-layer params, stacked) —
            # the stacked tree travels as a jit ARGUMENT, never a closure
            # (a closure would inline the weights as constants)
            from k8s_llm_rca_tpu.parallel import pipeline as pp

            pp_tp_axis = "model" if tp_mesh is not None else None
            pp_ep_axis = "expert" if ep_mesh is not None else None
            n_stages = pp_mesh.shape[pp_stage_axis]
            stacked = pp.shard_stacked_layers(
                pp.stack_llama_stages(params, n_stages), pp_mesh,
                pp_stage_axis, cfg=model_cfg, tp_axis=pp_tp_axis,
                ep_axis=pp_ep_axis)
            self.params = ({k: v for k, v in params.items()
                            if k != "layers"}, stacked)
            m = self._pp_m

            def _pp_prefill_batch(cfg, params_t, pool, toks, lens, maps):
                p, stk = params_t
                return pp.paged_pp_prefill(cfg, p, pool, toks, lens, maps,
                                           pp_mesh, m, pp_stage_axis, stk,
                                           tp_axis=pp_tp_axis,
                                           ep_axis=pp_ep_axis)

            def pp_decode_fn(cfg, params_t, pool, toks, lens, bt,
                             use_kernel=None):
                p, stk = params_t
                return pp.paged_pp_decode_step(cfg, p, pool, toks, lens, bt,
                                               pp_mesh, m, pp_stage_axis,
                                               stk, tp_axis=pp_tp_axis,
                                               ep_axis=pp_ep_axis)

            def pp_decode_multi_fn(cfg, params_t, pool, toks, lens, bt):
                p, stk = params_t
                return pp.paged_pp_decode_multi(cfg, p, pool, toks, lens,
                                                bt, pp_mesh, m,
                                                pp_stage_axis, stk,
                                                tp_axis=pp_tp_axis,
                                                ep_axis=pp_ep_axis)

            def _pp_prefill_chunk(cfg, params_t, pool, toks, chunk_len,
                                  prefix_len, prefix_table, page_map):
                p, stk = params_t
                return pp.paged_pp_prefill_chunk(
                    cfg, p, pool, toks, chunk_len, prefix_len,
                    prefix_table, page_map, pp_mesh, pp_stage_axis, stk,
                    tp_axis=pp_tp_axis)

            self._prefill = None     # PP admits through the batched path
            # ... except prefix-cache HITS, which admit singly through the
            # pipelined chunked prefill (each stage reuses its own layers'
            # cached prefix pages)
            self._prefill_batch = jax.jit(_pp_prefill_batch, static_argnums=0,
                                          donate_argnums=donate)
            self._prefill_chunk = jax.jit(_pp_prefill_chunk, static_argnums=0,
                                          donate_argnums=donate)
        elif cp_mesh is not None:
            # composed CP×TP names "model" so the ring/all-to-all runs per
            # head shard instead of all-gathering TP-sharded heads;
            # composed CP×EP threads ep_mesh so MoE MLPs dispatch over
            # (seq, expert) instead of densifying
            cp_head_axis = "model" if tp_mesh is not None else None

            def _prefill_cp(cfg, params, pool, toks, n, page_map):
                return paged_prefill_cp(cfg, params, pool, toks, n,
                                        page_map, cp_mesh, cp_seq_axis,
                                        cp_mode, cp_head_axis, ep_mesh)

            self._prefill = jax.jit(_prefill_cp, static_argnums=0,
                                    donate_argnums=donate)
        else:
            # fsdp-sharded weights exclude the per-shard flash kernel (the
            # head-sharded shard_map would consume a weight shard as the
            # full tensor); GSPMD all-gathers serve fsdp/fsdp×tp prefill
            use_flash, flash_mesh = flash_prefill_plan(
                params, None if fsdp_mesh is not None else tp_mesh,
                model_cfg, ep_mesh)
            self._prefill = jax.jit(
                functools.partial(paged_prefill, use_flash=use_flash,
                                  ep_mesh=ep_mesh, flash_mesh=flash_mesh,
                                  sp_mesh=tp_mesh if sp else None),
                static_argnums=0, donate_argnums=donate)
        if pp_mesh is None:
            if cp_mesh is not None:
                # batched admission is disabled under CP; keep the plain
                # plan (no TP-aware kernel) for the never-called jit
                use_flash, flash_mesh = flash_prefill_plan(params, None,
                                                           model_cfg,
                                                           ep_mesh)
            self._prefill_batch = jax.jit(
                functools.partial(paged_prefill_batch, use_flash=use_flash,
                                  ep_mesh=ep_mesh, flash_mesh=flash_mesh,
                                  sp_mesh=tp_mesh if sp else None),
                static_argnums=0, donate_argnums=donate)
        if pp_mesh is None:
            self._prefill_chunk = jax.jit(
                functools.partial(paged_prefill_chunk, ep_mesh=ep_mesh),
                static_argnums=0, donate_argnums=donate)
            self._prefill_chunk_batch = jax.jit(
                functools.partial(paged_prefill_chunk_batch,
                                  ep_mesh=ep_mesh),
                static_argnums=0, donate_argnums=donate)
        else:
            # PP's pipelined chunk prefill is per-sequence (GPipe m=1);
            # _admission_group keeps hit groups singleton under PP
            self._prefill_chunk_batch = None
        self._decode = jax.jit(
            pp_decode_fn if pp_decode_fn is not None
            else functools.partial(paged_decode_step, ep_mesh=ep_mesh,
                                    tp_mesh=self._kernel_mesh),
            static_argnums=(0,),
            donate_argnums=donate, static_argnames=("use_kernel",))
        # fused overlapped step (paged_overlap_step): decode + key split
        # + sample + length advance in ONE dispatch over the device-
        # resident state.  The in-jit jax.random.split computes the
        # identical subkey stream as the host split in the plain tick,
        # so sampled tokens match exactly.
        self._overlap_decode = jax.jit(
            functools.partial(paged_overlap_step, ep_mesh=ep_mesh,
                              tp_mesh=self._kernel_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 7, 8),
            donate_argnums=donate, static_argnames=("use_kernel",))
        self._decode_scan = jax.jit(
            functools.partial(paged_decode_scan, ep_mesh=ep_mesh,
                              tp_mesh=self._kernel_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 7, 8, 9),
            donate_argnums=donate, static_argnames=("use_kernel",))
        self._dfa_scan = True
        self._decode_scan_dfa = jax.jit(
            functools.partial(paged_decode_scan_dfa, ep_mesh=ep_mesh,
                              tp_mesh=self._kernel_mesh,
                              decode_fn=pp_decode_fn),
            static_argnums=(0, 7, 8, 9),
            donate_argnums=donate, static_argnames=("use_kernel",))
        self._decode_multi = jax.jit(
            pp_decode_multi_fn if pp_decode_multi_fn is not None
            else functools.partial(paged_decode_multi, ep_mesh=ep_mesh),
            static_argnums=0, donate_argnums=donate)
        from k8s_llm_rca_tpu.engine.engine import dfa_greedy_multi
        self._spec_dfa_greedy = jax.jit(dfa_greedy_multi, static_argnums=3)
        self._sample = jax.jit(sample_tokens, static_argnums=2)
        self._sample_masked = jax.jit(sample_tokens_masked, static_argnums=2)

        self._buckets = tuple(
            s for s in sorted(set(engine_cfg.prefill_buckets))
            if s <= engine_cfg.max_seq_len) or (engine_cfg.max_seq_len,)

    # ------------------------------------------------------------------ api

    def _register(self, seq_id: int, prompt_ids: List[int]) -> None:
        self._prompts[seq_id] = list(prompt_ids)

    def _stop_context(self, st: _Active) -> List[int]:
        # include pre-preemption tokens so stop strings spanning the
        # resume boundary still match
        prefix = self._resumed.get(st.seq_id)
        return prefix + st.generated if prefix else st.generated

    # -------------------------------------------------- fault injection

    def _tick_fault(self) -> None:
        # pages stolen by a previous tick's "oom" fault return first, so
        # exhaustion lasts exactly one tick (and the plan's disarm cleanup
        # covers a run that ends mid-fault)
        self._release_fault_pages()
        super()._tick_fault()

    def _release_fault_pages(self) -> None:
        if self._fault_pages:
            self.allocator.free(self._fault_pages, owner=FAULT_OWNER)
            self._fault_pages = []

    def _apply_tick_fault(self, fault, plan) -> None:
        """Paged tick faults: forced preemption wave ("preempt": evict the
        ``wave`` youngest sequences, exercising requeue/resume), allocator
        exhaustion ("oom": steal the whole free list for one tick, so this
        tick's growth pass runs the real pool-pressure machinery), plus
        the base host-stall kinds."""
        if fault.kind == "preempt":
            # forced preemption takes the normal victim path, INCLUDING
            # KV spill-to-host when enabled — this is how chaos plans
            # exercise the spill/restore machinery (faults/soak.py)
            for _ in range(max(1, fault.wave)):
                if not self._preempt_victim():
                    break
        elif fault.kind == "crash":
            # process-style teardown between ticks: EVERY active sequence
            # loses its device KV at once (what a worker kill does) and is
            # requeued for re-prefill — youngest first, so the requeue-at-
            # front discipline leaves the OLDEST sequence at the head and
            # admission order is preserved deterministically.  spill=False
            # by design: a crash models DEVICE KV LOSS, and spilling the
            # pages to host first would quietly defeat the fault
            n = 0
            while self._preempt_victim(spill=False):
                n += 1
            log.warning("tick fault 'crash': dropped device KV of %d "
                        "active sequence(s); all requeued for re-prefill",
                        n)
            self._count("engine.crash_evictions", n)
        elif fault.kind == "oom":
            if self._cp_parts:
                log.warning("oom tick fault skipped: partitioned CP pool")
                return
            n = self.allocator.n_free
            if n:
                self._fault_pages = self.allocator.alloc(n,
                                                         owner=FAULT_OWNER)
                plan.add_cleanup(self._release_fault_pages)
        else:
            super()._apply_tick_fault(fault, plan)

    # ---------------------------------------------------- observability

    def _tick_gauges(self):
        """Pool-pressure gauges for the tick timeline (obs/timeline.py):
        free pages from the allocator, evictable pages from the prefix
        cache's refcount-0 residency."""
        g = super()._tick_gauges()
        g["free_pages"] = self.allocator.n_free
        g["evictable_pages"] = (self.prefix_cache.n_evictable
                                if self.prefix_cache is not None else 0)
        return g

    # --------------------------------------------- device-resident state

    def _device_state(self):
        """The decode operands as device arrays (docs/performance.md).

        Plain mode uploads the three host mirrors every call — the
        pre-overlap behavior, now visible in ``engine.h2d_uploads``.
        Overlap mode keeps them device-resident: upload ONCE when dirty
        (host mirrors changed wholesale: sync-path commits, speculation,
        restore, faults), then mirror individual host writes with cheap
        ``.at[].set`` edits — steady-state ticks upload nothing."""
        if not self._overlap:
            self._count("engine.h2d_uploads", 3)
            return (jnp.asarray(self.cur_tokens, jnp.int32),
                    jnp.asarray(self.lengths, jnp.int32),
                    jnp.asarray(self.block_tables))
        if self._dev_dirty:
            self._count("engine.h2d_uploads", 3)
            self._dev_cur = jnp.asarray(self.cur_tokens, jnp.int32)
            self._dev_lens = jnp.asarray(self.lengths, jnp.int32)
            self._dev_bt = jnp.asarray(self.block_tables)
            self._dev_dirty = False
            # deferred admissions' first tokens exist only on device (the
            # host mirror is stale until the next drain/flush); re-apply
            # them on top of the fresh upload
            for st, a, i in self._admit_pending:
                if self._active.get(st.slot) is st:
                    self._dev_cur = self._dev_cur.at[st.slot].set(a[i])
        return self._dev_cur, self._dev_lens, self._dev_bt

    def _invalidate_device_state(self) -> None:
        self._dev_dirty = True

    def _dev_edit_token(self, slot: int, token) -> None:
        """Mirror one host ``cur_tokens`` write into the resident device
        array (an ``.at[].set`` edit, not a full upload — uncounted by
        design; ``token`` may be a host int or a device scalar)."""
        if self._overlap and not self._dev_dirty:
            self._dev_cur = self._dev_cur.at[slot].set(token)

    def _dev_edit_len(self, slot: int, n: int) -> None:
        if self._overlap and not self._dev_dirty:
            self._dev_lens = self._dev_lens.at[slot].set(n)

    def _dev_edit_bt_row(self, slot: int) -> None:
        """Mirror one block-table row after a host-side write (growth,
        admission, retirement/preemption trash reset).  Keeping retired
        rows at TRASH_PAGE on device is what contains the fused step's
        garbage writes to page 0 (paged_overlap_step)."""
        if self._overlap and not self._dev_dirty:
            self._dev_bt = self._dev_bt.at[slot].set(
                jnp.asarray(self.block_tables[slot]))

    def _covered_len(self, slot: int) -> int:
        """Logical sequence length INCLUDING dispatched-but-uncommitted
        fast-path steps — what growth must cover so a lagged tick never
        writes into an unallocated page."""
        return int(self.lengths[slot]) + self._inflight_n.get(slot, 0)

    def _note_flush_entry(self, entry: dict) -> None:
        # every slot in the entry was dispatched once, live or not
        for s, _ in entry["slots"]:
            n = self._inflight_n.get(s, 0) - 1
            if n > 0:
                self._inflight_n[s] = n
            else:
                self._inflight_n.pop(s, None)

    def _overlap_post_commit(self, slot: int, token: int) -> None:
        # lagged-flush commit: host mirrors catch up to where the device
        # already is, so the resident state stays CLEAN
        self.lengths[slot] += 1
        self.cur_tokens[slot] = token

    def _note_first_token(self, slot: int, token: int,
                          update_dev: bool) -> None:
        self.cur_tokens[slot] = token
        if update_dev:
            # grammar-constrained first tokens can differ from the
            # sampled device value; deferred admissions already hold the
            # right value (written at _admit time), making this a
            # same-value no-op edit.  update_dev=False at a lagged
            # flush: the device array has advanced past the first token.
            self._dev_edit_token(slot, token)

    def _tick(self) -> List[SequenceResult]:
        finished: List[SequenceResult] = self._reap_deadlines()
        if self._flushed_out:
            # results finished by an out-of-tick flush (cancel/snapshot/
            # fault barrier) surface here so step() callers never lose them
            finished.extend(self._flushed_out)
            self._flushed_out = []
        fast = self._overlap_fast()
        if self._inflight and not fast:
            # a sync path (grammar, speculation, scan) runs this tick:
            # commit the lag first so it observes fully committed state
            finished.extend(self._overlap_flush())
        if self._prefilling:
            # advance every in-progress chunked prefill by ONE chunk
            # BEFORE admission: budget-limited sequences make progress
            # each tick even while new admissions compete for pages
            finished.extend(self._tick_prefill_chunks())
        if self._pending and self._free_slots:
            with profiling.annotate("engine.tick.admission"):
                finished.extend(self._tick_admission())
        if not fast:
            # one coalesced fetch commits every deferred admission first
            # token before any state-dependent path (spec drafts, scan
            # chunk bounds, a dirty re-upload) reads host mirrors
            finished.extend(self._drain_admission_commits())
        if not self._active:
            finished.extend(self._overlap_flush())
            return finished

        with profiling.annotate("engine.tick.eviction"):
            self._tick_pressure()
            self._tick_growth()
        active_slots = sorted(self._active)
        if not active_slots:
            finished.extend(self._overlap_flush())
            return finished

        if self._speculation_applies():
            finished.extend(self._speculative_tick(active_slots))
            return finished

        chunk = self._scan_chunk()
        if chunk > 1:
            finished.extend(self._scan_tick(chunk, active_slots))
            return finished

        if fast:
            finished.extend(self._overlap_step_tick(active_slots))
            return finished

        forced, allow = self._tick_constraints(
            active_slots, self.engine_cfg.max_batch,
            self.model_cfg.vocab_size)
        cur_d, lens_d, bt_d = self._device_state()
        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.pool, logits = self._decode(
                self.model_cfg, self.params, self.pool,
                cur_d, lens_d, bt_d,
                use_kernel=self.use_kernel)
            self._key, sub = jax.random.split(self._key)
            if allow is not None:
                next_tokens = self._sample_masked(
                    logits, sub, self.sampling, jnp.asarray(allow))
            else:
                next_tokens = self._sample(logits, sub, self.sampling)
        self._count("engine.decode_tokens", len(active_slots))

        (host_next,) = self._fetch(next_tokens)
        for slot in active_slots:
            self.lengths[slot] += 1
            st = self._active[slot]
            token = forced.get(slot, int(host_next[slot]))
            self.cur_tokens[slot] = token
            st.generated.append(token)
            if st.grammar is not None:
                st.grammar.advance(token)
            reason = self._finish_reason(st, token, int(self.lengths[slot]))
            if reason is not None:
                finished.append(self._retire(slot, reason))
        # the plain step does not advance the device lengths/tokens; the
        # host commit above is authoritative — re-upload next dispatch
        self._invalidate_device_state()
        return finished

    def _overlap_step_tick(self, active_slots) -> List[SequenceResult]:
        """Fast-path paged tick: ONE fused dispatch over the device-
        resident state, no blocking fetch — the token vector joins
        ``_inflight`` and commits when the lag flushes.  decode_tokens
        are counted at commit (_commit_scanned), so totals match the
        plain path exactly."""
        # device state FIRST: a dirty upload re-applies _admit_pending
        # device tokens over the stale host mirror, so take the admits
        # only after the resident arrays are materialised
        cur_d, lens_d, bt_d = self._device_state()
        admits = self._take_admit_pending()
        slots = [(s, self._active[s].seq_id) for s in active_slots]
        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.pool, nxt, new_lens, self._key = self._overlap_decode(
                self.model_cfg, self.params, self.pool, cur_d, lens_d,
                bt_d, self._key, self.sampling, self._dev_cap,
                use_kernel=self.use_kernel)
        self._dev_cur, self._dev_lens = nxt, new_lens
        for s in active_slots:
            self._inflight_n[s] = self._inflight_n.get(s, 0) + 1
        self._inflight.append({"slots": slots, "toks": nxt,
                               "admits": admits})
        if len(self._inflight) >= self._overlap_lag:
            return self._overlap_flush()
        return []

    def _tick_admission(self) -> List[SequenceResult]:
        """Admit pending requests into free slots (the tick's admission
        phase, annotated for XProf/flight records)."""
        finished: List[SequenceResult] = []
        budget = self.engine_cfg.prefill_chunk_budget
        while self._pending and self._free_slots:
            if self._spilled and self._pending[0].seq_id in self._spilled:
                # KV-spilled sequence at the head: resume by h2d page
                # restore — no prefill dispatch, byte-identical decode
                # state to the moment it was preempted
                try:
                    self._admit_spilled(self._pending[0])
                except OutOfPages:
                    # record kept; the pool refills on retirements and
                    # the head retries next tick (never preempt to admit
                    # — the anti-livelock rule below)
                    self._count("engine.admission_rejections")
                    break
                del self._pending[:1]
                continue
            if budget and len(self._pending[0].prompt_ids) > budget:
                # long prompt: admit through the chunked-prefill path —
                # the first chunk dispatches now, the rest spread one per
                # tick (_tick_prefill_chunks) instead of stalling this
                # tick on a monolithic prefill
                try:
                    early = self._admit_chunked(self._pending[0])
                except OutOfPages:
                    self._count("engine.admission_rejections")
                    break
                del self._pending[:1]
                if early is not None:
                    finished.append(early)
                continue
            group, matches = self._admission_group()
            try:
                # PP has no single-sequence FULL prefill: admissions go
                # through the batched pipelined path (padded to a
                # microbatch multiple in _admit_batch) — except prefix-
                # cache HITS, which _admit routes through the pipelined
                # chunked prefill (prefix KV reuse per stage)
                if len(group) == 1 and (not self._pp or matches[0][1]):
                    early = self._admit(group[0], matches[0])
                    admitted = [early] if early is not None else []
                elif matches[0][1]:
                    # equal-prefix HIT group: one batched chunked prefill
                    admitted = self._admit_batch_hits(group, matches)
                else:
                    admitted = self._admit_batch(group)
            except OutOfPages:
                # Admission never preempts: evicting a running sequence to
                # admit a queued one just swaps which request waits while
                # paying a re-prefill (and it livelocks when the evictee is
                # requeued at the front).  Wait for retirements to free
                # pages; only the growth path below preempts, because a
                # sequence that cannot grow cannot make progress at all.
                self._count("engine.admission_rejections")
                break
            del self._pending[:len(group)]
            finished.extend(admitted)
        return finished

    def _tick_pressure(self) -> None:
        """Pressure-driven demotion + write-through, both tick-boundary
        policies on the prefix cache (EngineConfig.prefix_hbm_watermark /
        prefix_store_writethrough; docs/performance.md "cache fabric").

        Watermark: when the allocator's free count dips below the mark,
        refcount-0 prefix pages demote through the SAME coalesced
        ``PrefixCache.evict`` -> ``_demote_prefix_pages`` gather that
        explicit eviction uses (oldest chains first), until the mark is
        restored or the evictable set runs dry — so growth/admission in
        the SAME tick already sees the freed pages.  Write-through: when
        the resident set grew since the last flush, newly-inserted full-
        page chains are published to the store WITHOUT freeing them
        (``flush_to_store``), which is what makes another engine's
        crash-restart / drain / disagg-fallback re-prefill a store hit.
        Reading prefix pages without an overlap barrier is safe: cache
        pages are refcount-shared read-only — in-flight decode steps
        write only to active slots' private current pages."""
        if self.prefix_cache is None:
            return
        if self._hbm_watermark:
            deficit = self._hbm_watermark - self.allocator.n_free
            if deficit > 0:
                demoted = self.prefix_cache.evict(deficit)
                if demoted:
                    self._count("engine.prefix_watermark_demotions",
                                demoted)
        if self._writethrough:
            resident = self.prefix_cache.n_resident
            if resident != self._wt_resident:
                flushed = self.prefix_cache.flush_to_store()
                self._wt_resident = resident
                if flushed:
                    self._count("engine.prefix_writethrough_pages",
                                flushed)

    def _tick_growth(self) -> None:
        # grow block tables to cover this tick's scan window: the
        # per-step KV write indexes the table dynamically (lengths //
        # page via take_along_axis), so pages pre-allocated for
        # positions lengths..lengths+decode_chunk-1 let a chunked scan
        # CROSS page boundaries while the table stays static.  The page
        # holding position `lengths` is MANDATORY (a slot that cannot
        # take one step preempts, as before); lookahead pages are
        # best-effort — under pool pressure the slot's chunk bound just
        # shrinks to its allocated run (_chunk_bound).
        # Two passes: every slot's MANDATORY page first, then best-effort
        # lookahead across slots.  Interleaving them let an earlier slot's
        # scan-window lookahead drain the pool and push a later slot's
        # mandatory grow into a preemption — avoidable churn under pool
        # pressure.
        chunk_goal = max(1, self.engine_cfg.decode_chunk)
        for slot in sorted(self._active):
            if slot not in self._active:
                # a previous iteration's _preempt_victim() evicted it
                continue
            # _covered_len, not the host mirror: with a lagged commit the
            # device is up to _overlap_lag steps ahead, and the NEXT
            # dispatch writes at the device length
            if self._covered_len(slot) % self.page_size == 0:
                # keep evicting youngest-first until the grow succeeds: one
                # eviction is always enough for the plain pool, but under
                # the CP seq-sharded pool the freed pages may fall in a
                # DIFFERENT partition than the one this slot's next page
                # must come from, so the retry can fail repeatedly
                while slot in self._active:
                    try:
                        self._grow(slot)
                        break
                    except OutOfPages:
                        if not self._preempt_victim(exclude=slot):
                            # evict this one instead (it cannot take a step)
                            self._preempt_slot(slot)
                            break
        if chunk_goal > 1:
            for slot in sorted(self._active):
                st = self._active[slot]
                pos = self._covered_len(slot)
                last = min(pos + chunk_goal - 1,
                           self.pages_per_seq * self.page_size - 1)
                grew = False
                for idx in range(pos // self.page_size + 1,
                                 last // self.page_size + 1):
                    if self.block_tables[slot, idx] != TRASH_PAGE:
                        continue
                    try:
                        # best-effort: plain alloc (never evicts prefix
                        # pages), partition-aligned under the CP pool
                        if self._cp_parts:
                            (page,) = self.allocator.alloc(
                                1, owner=st.seq_id,
                                part=self._page_part(idx))
                        else:
                            (page,) = self.allocator.alloc(1,
                                                           owner=st.seq_id)
                    except OutOfPages:
                        break          # best-effort: bound shrinks instead
                    self.block_tables[slot, idx] = page
                    grew = True
                if grew:
                    self._dev_edit_bt_row(slot)

    # --------------------------------------------- speculative decoding

    def _spec_room_ok(self, slot: int, t: int, lengths_host) -> bool:
        # all T writes must land in the slot's CURRENT page (the page id
        # is computed once per slot in paged_decode_multi) and within the
        # sequence cap
        length = int(lengths_host[slot])
        return (length % self.page_size + t <= self.page_size
                and length + t <= self.engine_cfg.max_seq_len)

    def _speculative_tick(self, active_slots) -> List[SequenceResult]:
        """Paged verification tick: drafts scored by one paged_decode_multi,
        committed via the shared _verify_and_commit loop.  Grammar slots
        sharing one compiled DFA verify constrained ON DEVICE
        (engine.dfa_greedy_multi) — no [B, T, V] logits transfer."""
        tokens_in, drafts = self._build_drafts(active_slots, self.cur_tokens)
        # the verify step reshapes the batch to [B, T] drafts, so it
        # cannot reuse the resident [B] cur array; lengths + block tables
        # are the named-array uploads it pays
        self._count("engine.h2d_uploads", 2)
        with profiling.annotate("engine.decode_step"):
            self._count("engine.dispatches")
            self.pool, greedy, logits = self._decode_multi(
                self.model_cfg, self.params, self.pool,
                jnp.asarray(tokens_in), jnp.asarray(self.lengths, jnp.int32),
                jnp.asarray(self.block_tables))
            greedy_host, logits_host, constrained = \
                self._spec_constrained_greedy(greedy, logits, active_slots)

        def post_commit(slot: int, token: int) -> None:
            self.lengths[slot] += 1
            self.cur_tokens[slot] = token

        out = self._verify_and_commit(active_slots, drafts, greedy_host,
                                      logits_host, post_commit,
                                      constrained)
        # host mirrors advanced by a variable accepted count per slot —
        # single invalidation point, re-upload before the next dispatch
        self._invalidate_device_state()
        return out

    # ------------------------------------------------- chunked scan tick

    def _chunk_bound(self, slot: int) -> int:
        # paged-only bound: the scan may cross page boundaries into
        # PRE-ALLOCATED pages (the per-step write indexes the block
        # table dynamically; step()'s growth pass allocates the scan
        # window ahead), so the bound is the slot's contiguous
        # allocated run from its current position — with lookahead
        # growth this is >= decode_chunk except under pool pressure,
        # where it shrinks instead of collapsing the whole batch
        pos = int(self.lengths[slot])
        idx = pos // self.page_size
        while (idx < self.pages_per_seq
               and self.block_tables[slot, idx] != TRASH_PAGE):
            idx += 1
        return idx * self.page_size - pos

    def _scan_tick(self, chunk: int, active_slots) -> List[SequenceResult]:
        """Commit ``chunk`` paged decode steps from one on-device scan;
        accounting identical to the stepwise tick (shared commit loop)."""
        setup = self._scan_dfa_setup()
        self._key, sub = jax.random.split(self._key)
        cur_d, lens_d, bt_d = self._device_state()
        if setup is None:
            with profiling.annotate("engine.decode_step"):
                self._count("engine.dispatches")
                self.pool, toks, new_lens = self._decode_scan(
                    self.model_cfg, self.params, self.pool,
                    cur_d, lens_d, bt_d, sub, chunk,
                    self.sampling, self.tokenizer.eos_id,
                    use_kernel=self.use_kernel)
        else:
            (allow_t, next_t, dist_t, close_t, complete_t), states, \
                remaining = setup
            with profiling.annotate("engine.decode_step"):
                self._count("engine.dispatches")
                self.pool, toks, new_lens, _ = self._decode_scan_dfa(
                    self.model_cfg, self.params, self.pool,
                    cur_d, lens_d, bt_d, sub, chunk,
                    self.sampling, self.tokenizer.eos_id,
                    jnp.asarray(states), jnp.asarray(remaining),
                    allow_t, next_t, dist_t, close_t, complete_t,
                    use_kernel=self.use_kernel)
        if self._overlap:
            # surviving slots' host mirrors advance to EXACTLY these
            # values in the commit loop below (a slot that stops short is
            # always retired, trashing its row), so the resident state
            # stays clean: the next scan dispatches with zero uploads
            self._dev_cur, self._dev_lens = toks[-1], new_lens
        (toks_host,) = self._fetch(toks)                # [chunk, B]

        def post_commit(slot: int, token: int) -> None:
            self.lengths[slot] += 1
            self.cur_tokens[slot] = token
            self._grammar_post_commit(slot, token)

        return self._commit_scanned(active_slots, toks_host, chunk,
                                    post_commit)

    # ------------------------------------------------------------- internals

    def _bucket(self, n: int) -> int:
        # bucket to a page multiple so prefill scatters whole pages
        for b in self._buckets:
            if n <= b:
                return -(-b // self.page_size) * self.page_size
        return self.pages_per_seq * self.page_size

    def _alloc_with_evict(self, n: int, owner: int) -> List[int]:
        """Allocate, evicting refcount-0 prefix-cache pages on pressure."""
        try:
            return self.allocator.alloc(n, owner=owner)
        except OutOfPages:
            if self.prefix_cache is None:
                raise
            need = n - self.allocator.n_free
            if self.prefix_cache.evict(need) < need:
                raise
            return self.allocator.alloc(n, owner=owner)

    def _page_part(self, seq_page_idx: int) -> int:
        """CP partition owning a sequence's page index: page j covers
        positions [j*page, (j+1)*page), which live on CP device
        j * P // pages_per_seq — the same contiguous position split the
        contiguous CP cache uses."""
        return seq_page_idx * self._cp_parts // self.pages_per_seq

    def _alloc_seq_pages(self, seq_page_idxs, owner: int) -> List[int]:
        """Allocate one page per sequence-page index.  Under the CP
        seq-sharded pool each page comes from the partition owning that
        index's position range (all-or-nothing: a partial failure frees
        what was taken); otherwise one plain allocation."""
        idxs = list(seq_page_idxs)
        if not self._cp_parts:
            return self._alloc_with_evict(len(idxs), owner=owner)
        pages: List[int] = []
        try:
            for j in idxs:
                pages.extend(self.allocator.alloc(
                    1, owner=owner, part=self._page_part(j)))
        except OutOfPages:
            if pages:
                self.allocator.free(pages, owner=owner)
            raise
        return pages

    def _admission_group(self) -> Tuple[List[_Pending],
                                        List[Tuple[List[int], int]]]:
        """Peek (without popping) a FIFO run of same-bucket pending
        requests for one batched prefill, plus the ACQUIRED prefix-cache
        match per member (so admission doesn't match twice).

        A head WITH a cached prefix groups with subsequent same-bucket
        requests whose match has the SAME cached length (the agent-wave
        case: one shared preamble) and the whole group admits through
        ONE batched chunked prefill (_admit_batch_hits) — hits used to
        admit single-file, measured 5x slower than the miss path for
        same-prefix waves.  A hit with a different cached length ends
        the group (it admits on a later iteration with its own shape).
        Under PP the pipelined chunk prefill is per-sequence, so hit
        groups stay singletons there.  Miss groups are unchanged: a
        member with ANY cached prefix ends a miss group (batch-
        prefilling it would forgo its KV reuse)."""
        head = self._pending[0]
        matched: Tuple[List[int], int] = ([], 0)
        if self.prefix_cache is not None:
            matched = self.prefix_cache.match(head.prompt_ids)
        if matched[1] and (self._pp or not self._batch_admission):
            return [head], [matched]
        b0 = self._bucket(len(head.prompt_ids))
        group, matches = [head], [matched]
        if matched[1]:
            # hit group: extend with same-bucket, equal-cached-length
            # hits.  Wider cap than miss groups (16 vs 8): a hit row
            # prefills only its SUFFIX, so the batched dispatch stays
            # small even at twice the rows.  Like the miss path, the
            # group is also bounded by the CURRENT free list (worst-case
            # suffix pages per member) — an all-or-nothing allocation
            # sized past the pool would fail forever where a smaller
            # group makes progress
            n_pages_hit = max(1, self._bucket(
                max(1, b0 - matched[1])) // self.page_size)
            # the cap mirrors what _alloc_with_evict can actually satisfy:
            # free pages PLUS refcount-0 prefix-cache pages (evictable on
            # pressure) — counting n_free alone split hit waves into more
            # dispatches than the pool could really serve
            supply = self.allocator.n_free + self.prefix_cache.n_evictable
            cap = min(16, len(self._free_slots),
                      max(1, supply // n_pages_hit))
            for req in itertools.islice(self._pending, 1, None):
                if (len(group) >= cap
                        or self._bucket(len(req.prompt_ids)) != b0):
                    break
                m = self.prefix_cache.match(req.prompt_ids)
                if m[1] != matched[1]:
                    self.prefix_cache.release(m[0])
                    break
                group.append(req)
                matches.append(m)
            return group, matches
        if not self._batch_admission:
            return [head], [matched]
        # bound the group so every member's pages fit the CURRENT free
        # list: _admit_batch's allocation is all-or-nothing, and a group
        # sized past the pool would fail forever where admitting the head
        # alone (which can also evict prefix pages) makes progress
        n_pages = max(1, b0 // self.page_size)
        # same supply arithmetic as the hit cap: _admit_batch allocates via
        # _alloc_with_evict, which can also reclaim refcount-0 prefix pages
        supply = self.allocator.n_free + (
            self.prefix_cache.n_evictable
            if self.prefix_cache is not None else 0)
        cap = min(8, len(self._free_slots),
                  max(1, supply // n_pages))
        for req in itertools.islice(self._pending, 1, None):
            if (len(group) >= cap
                    or self._bucket(len(req.prompt_ids)) != b0):
                break
            # a member with a cached prefix must not be batch-prefilled
            # (the batch path would redundantly prefill + allocate its
            # whole prompt); end the group so it admits through the
            # chunked path — batched with its fellow hits — next iteration
            if self.prefix_cache is not None \
                    and self.prefix_cache.has_prefix(req.prompt_ids):
                break
            group.append(req)
            matches.append(([], 0))
        return group, matches

    def _admit(self, req: _Pending,
               matched: Optional[Tuple[List[int], int]] = None
               ) -> Optional[SequenceResult]:
        n = len(req.prompt_ids)
        if matched is None:
            matched = (self.prefix_cache.match(req.prompt_ids)
                       if self.prefix_cache is not None else ([], 0))
        cached_pages, n_cached = matched
        n_cp = len(cached_pages)
        rest = req.prompt_ids[n_cached:]
        # suffix bucket capped at the table space left after the cached
        # prefix (utils/pages.py — one definition with _admit_chunked
        # and _admit_spilled, so allocator state evolves identically)
        bucket, n_pages = suffix_bucket(self._bucket, len(rest), n_cp,
                                        self.page_size, self.pages_per_seq)
        assert len(rest) <= bucket, (len(rest), bucket)
        try:
            # sequence-page indices n_cp..n_cp+n_pages-1 (partition-aligned
            # under the CP seq-sharded pool; plain allocation otherwise)
            pages = self._alloc_seq_pages(range(n_cp, n_cp + n_pages),
                                          owner=req.seq_id)
        except OutOfPages:
            if cached_pages:
                self.prefix_cache.release(cached_pages)
            raise
        slot = self._free_slots.pop(0)

        table = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
        table[:n_cp] = cached_pages
        table[n_cp:n_cp + n_pages] = pages
        self.block_tables[slot] = table

        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(rest)] = rest
        with profiling.annotate("engine.prefill"):
            self._count("engine.dispatches")
            if n_cached:
                # pad the prefix table to the next power of two of page
                # counts: the chunk-prefill gathers/attends over the whole
                # passed table, so its length should track the actual
                # prefix (bounded compile count, ~log2(pages_per_seq))
                pb = 1
                while pb < n_cp:
                    pb *= 2
                prefix_table = np.full((pb,), TRASH_PAGE, np.int32)
                prefix_table[:n_cp] = table[:n_cp]
                self.pool, logits = self._prefill_chunk(
                    self.model_cfg, self.params, self.pool,
                    jnp.asarray(padded), jnp.int32(len(rest)),
                    jnp.int32(n_cached), jnp.asarray(prefix_table),
                    jnp.asarray(table[n_cp:n_cp + n_pages]))
                self._count("engine.prefix_hit_tokens", n_cached)
            else:
                self.pool, logits = self._prefill(
                    self.model_cfg, self.params, self.pool,
                    jnp.asarray(padded), jnp.int32(n),
                    jnp.asarray(table[:n_pages]))
            self._key, sub = jax.random.split(self._key)
            first = self._sample(logits, sub, self.sampling)
        self._count("engine.prefill_tokens", len(rest))

        if req.grammar is not None:
            # grammar first tokens stay synchronous: the FSM needs the
            # sampled value (and possibly a masked resample off these
            # logits) before the next dispatch
            return self._activate_paged(req, slot, table, n_cp, logits,
                                        int(self._fetch(first)[0][0]))
        # deferred admission (docs/performance.md): the device value goes
        # straight into the resident cur array; the HOST value lands at
        # the next coalesced drain/flush — single-sequence admission no
        # longer pays a blocking per-admission fetch (it used to cost one
        # ~0.25 s tunnel round-trip per admission)
        st = self._preactivate_paged(req, slot, table, n_cp)
        self._dev_edit_token(slot, first[0])
        self._defer_first(st, first, 0)
        return None

    def _admit_chunked(self, req: _Pending) -> Optional[SequenceResult]:
        """Admit a long prompt through the chunk-prefill path spread
        across ticks (``EngineConfig.prefill_chunk_budget``).

        All pages allocate UP FRONT (all-or-nothing, like _admit: a
        sequence that may stall mid-prefill waiting for pages would hold
        its written chunks' pages while blocking the pool — the same
        livelock admission's no-preemption rule exists to prevent), but
        the prefill work itself spreads over ticks: one <=budget chunk
        per tick through the SAME jitted ``_prefill_chunk`` the prefix-
        cache hit path compiles, each chunk's pages becoming the next
        chunk's gathered prefix.  Byte-parity with the monolithic path
        holds because chunked attention over (written prefix + chunk) is
        exactly the prefix-hit computation the engine already trusts.

        A prompt whose post-prefix-hit SUFFIX fits the budget admits
        normally — the cache already did the spreading."""
        matched = (self.prefix_cache.match(req.prompt_ids)
                   if self.prefix_cache is not None else ([], 0))
        cached_pages, n_cached = matched
        rest = req.prompt_ids[n_cached:]
        if len(rest) <= self.engine_cfg.prefill_chunk_budget:
            return self._admit(req, matched)
        n_cp = len(cached_pages)
        bucket, n_pages = suffix_bucket(self._bucket, len(rest), n_cp,
                                        self.page_size, self.pages_per_seq)
        try:
            pages = self._alloc_seq_pages(range(n_cp, n_cp + n_pages),
                                          owner=req.seq_id)
        except OutOfPages:
            if cached_pages:
                self.prefix_cache.release(cached_pages)
            raise
        slot = self._free_slots.pop(0)
        # the full table lives in _prefilling, NOT block_tables: the slot
        # stays inactive (row TRASH_PAGE) until the final chunk activates
        # it, so interleaved decode ticks' garbage writes for this slot
        # cannot land in the chunk pages being filled
        table = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
        table[:n_cp] = cached_pages
        table[n_cp:n_cp + n_pages] = pages
        self._prefilling[slot] = {
            "req": req, "table": table, "n_cp": n_cp,
            "cached": [int(p) for p in cached_pages],
            "pages": [int(p) for p in pages],
            "done": n_cached, "total": len(req.prompt_ids),
        }
        if n_cached:
            self._count("engine.prefix_hit_tokens", n_cached)
        return self._advance_prefill(slot)   # first chunk dispatches NOW

    def _advance_prefill(self, slot: int) -> Optional[SequenceResult]:
        """Dispatch ONE chunk of a slot's in-progress chunked prefill;
        on the final chunk, sample the first token and activate."""
        st = self._prefilling[slot]
        req, table = st["req"], st["table"]
        budget = self.engine_cfg.prefill_chunk_budget
        done, total = st["done"], st["total"]
        chunk_len = min(budget, total - done)
        ps = self.page_size
        # ``done`` is page-aligned here: it starts at the (whole-page)
        # cached-prefix length and every non-final chunk advances it by
        # the page-multiple budget
        n_pre_pages = done // ps
        pb = 1
        while pb < n_pre_pages:
            pb *= 2
        prefix_table = np.full((pb,), TRASH_PAGE, np.int32)
        prefix_table[:n_pre_pages] = table[:n_pre_pages]
        # fixed [1, budget] compile shape for every chunk; the final
        # (short) chunk right-pads and maps only its valid pages — the
        # padding positions scatter to TRASH_PAGE, the engine's standing
        # garbage-containment convention
        padded = np.zeros((1, budget), np.int32)
        padded[0, :chunk_len] = req.prompt_ids[done:done + chunk_len]
        page_map = np.full((budget // ps,), TRASH_PAGE, np.int32)
        n_chunk_pages = -(-chunk_len // ps)
        page_map[:n_chunk_pages] = table[n_pre_pages:
                                         n_pre_pages + n_chunk_pages]
        with profiling.annotate("engine.tick.prefill_chunk"):
            self._count("engine.dispatches")
            self._count("engine.prefill_chunks")
            self.pool, logits = self._prefill_chunk(
                self.model_cfg, self.params, self.pool,
                jnp.asarray(padded), jnp.int32(chunk_len),
                jnp.int32(done), jnp.asarray(prefix_table),
                jnp.asarray(page_map))
        self._count("engine.prefill_tokens", chunk_len)
        st["done"] = done + chunk_len
        if st["done"] < total:
            return None
        # final chunk: its last-valid-token logits are the whole prompt's
        # — sample the first token with exactly the monolithic _admit's
        # single RNG split, publish the table, activate
        del self._prefilling[slot]
        self.block_tables[slot] = table
        self._key, sub = jax.random.split(self._key)
        first = self._sample(logits, sub, self.sampling)
        if req.grammar is not None:
            return self._activate_paged(req, slot, table, st["n_cp"],
                                        logits,
                                        int(self._fetch(first)[0][0]))
        act = self._preactivate_paged(req, slot, table, st["n_cp"])
        self._dev_edit_token(slot, first[0])
        self._defer_first(act, first, 0)
        return None

    def _tick_prefill_chunks(self) -> List[SequenceResult]:
        """The tick's chunked-prefill phase: every in-progress slot
        advances by one chunk."""
        finished: List[SequenceResult] = []
        for slot in sorted(self._prefilling):
            early = self._advance_prefill(slot)
            if early is not None:
                finished.append(early)
        return finished

    def _abort_prefilling(self, slot: int) -> None:
        """Cancel an in-progress chunked prefill: drop the cached-prefix
        refcounts, free the allocated pages, return the slot."""
        st = self._prefilling.pop(slot)
        seq_id = st["req"].seq_id
        if st["cached"]:
            self.prefix_cache.release(st["cached"])
        if st["pages"]:
            self.allocator.free(st["pages"], owner=seq_id)
        self.block_tables[slot] = TRASH_PAGE
        self._dev_edit_bt_row(slot)
        self._free_slots.append(slot)
        self._prompts.pop(seq_id, None)
        self._resumed.pop(seq_id, None)
        if self._deadlines:
            self._deadlines.pop(seq_id, None)

    @property
    def has_work(self) -> bool:
        return bool(self._active or self._pending or self._prefilling)

    def cancel_seq(self, seq_id: int) -> bool:
        for slot, st in list(self._prefilling.items()):
            if st["req"].seq_id == seq_id:
                self._abort_prefilling(slot)
                return True
        return super().cancel_seq(seq_id)

    def snapshot_sequences(self) -> Dict[str, object]:
        """Chunked-prefill-aware snapshot: a mid-prefill sequence exports
        as a pending-style entry (original prompt, nothing generated) —
        its written pages are device state a restart cannot reuse, so
        restore re-admits it through a fresh prefill, between the active
        sequences and the pending queue (its scheduler position).

        With a shared store attached, active sequences' written pages
        are published first (``_publish_sequence_pages``) so whoever
        restores this snapshot — a restarted incarnation, a drain
        target, a disagg fallback — promotes instead of recomputing."""
        self._publish_sequence_pages()
        snap = super().snapshot_sequences()
        if not self._prefilling:
            return snap
        pre = []
        for slot in sorted(self._prefilling):
            req = self._prefilling[slot]["req"]
            pre.append({
                "seq_id": req.seq_id,
                "prompt_ids": list(self._prompts.get(req.seq_id,
                                                     req.prompt_ids)),
                "generated": list(self._resumed.get(req.seq_id, ())),
                "remaining_new_tokens": req.max_new_tokens,
                "stop_strings": list(req.stop_strings),
                "grammar": req.grammar is not None,
                "priority": req.priority,
                "deadline": (self._deadlines or {}).get(req.seq_id),
            })
        seqs = snap["sequences"]
        n_active = len(self._active)
        snap["sequences"] = seqs[:n_active] + pre + seqs[n_active:]
        return snap

    def _preactivate_paged(self, req: _Pending, slot: int, table,
                           n_cp: int) -> _Active:
        """Token-independent half of paged activation: chain pages into
        the prefix cache, register the slot, set its length and block-
        table mirrors (the first token is handled separately —
        synchronously for grammar slots, deferred otherwise)."""
        n = len(req.prompt_ids)
        n_shared = n_cp
        if self.prefix_cache is not None:
            n_shared = self.prefix_cache.insert(req.prompt_ids, table,
                                                req.seq_id, n_cp)
        st = _Active(seq_id=req.seq_id, slot=slot, prompt_tokens=n,
                     max_new_tokens=req.max_new_tokens,
                     stop_strings=req.stop_strings, grammar=req.grammar,
                     n_shared=n_shared, priority=req.priority)
        self._active[slot] = st
        self.lengths[slot] = n
        self._dev_edit_len(slot, n)
        self._dev_edit_bt_row(slot)
        return st

    def _activate_paged(self, req: _Pending, slot: int, table, n_cp: int,
                        logits_1v, first_token: int
                        ) -> Optional[SequenceResult]:
        """Synchronous paged activation: grammar-constrain the first
        token, register the slot, early-retire if already terminal."""
        st = self._preactivate_paged(req, slot, table, n_cp)
        token = first_token
        if st.grammar is not None:
            remaining = min(st.max_new_tokens,
                            self.engine_cfg.max_seq_len
                            - st.prompt_tokens - 1)
            token = self._grammar_first_token(st.grammar, logits_1v, token,
                                              remaining)
            st.grammar.advance(token)
        # the first sampled token may already terminate the sequence
        return self._commit_first(st, token, update_dev=True)

    def _admit_batch_hits(self, reqs: List[_Pending],
                          matches: List[Tuple[List[int], int]]
                          ) -> List[SequenceResult]:
        """Admit N same-bucket prefix-HIT sequences with EQUAL cached
        length through ONE batched chunked prefill
        (paged_prefill_chunk_batch) — the hits keep their KV reuse AND
        the miss path's single-dispatch admission (hits used to admit
        single-file: measured 5x slower for same-prefix waves on the
        dispatch-bound bench host).  Matches arrive ACQUIRED from
        _admission_group; on allocation failure every ref is released
        before the OutOfPages escapes (retry next tick)."""
        n_cached = matches[0][1]
        n_cp = len(matches[0][0])
        rests = [r.prompt_ids[n_cached:] for r in reqs]
        bucket = min(self._bucket(max(len(rest) for rest in rests)),
                     (self.pages_per_seq - n_cp) * self.page_size)
        assert all(len(rest) <= bucket for rest in rests)
        n_pages = bucket // self.page_size
        n = len(reqs)
        allocated: List[List[int]] = []
        try:
            for r in reqs:
                allocated.append(
                    self._alloc_with_evict(n_pages, owner=r.seq_id))
        except OutOfPages:
            for r, pages in zip(reqs, allocated):
                self.allocator.free(pages, owner=r.seq_id)
            for m in matches:
                self.prefix_cache.release(m[0])
            raise
        slots = [self._free_slots.pop(0) for _ in range(n)]

        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        pb = 1
        while pb < n_cp:
            pb *= 2
        tokens = np.zeros((n_pad, bucket), np.int32)
        clens = np.zeros((n_pad,), np.int32)
        plens = np.full((n_pad,), n_cached, np.int32)
        ptabs = np.full((n_pad, pb), TRASH_PAGE, np.int32)
        maps = np.zeros((n_pad, n_pages), np.int32)
        tables = []
        for i, (r, m, rest) in enumerate(zip(reqs, matches, rests)):
            tokens[i, :len(rest)] = rest
            clens[i] = len(rest)
            ptabs[i, :n_cp] = m[0]
            maps[i] = allocated[i]
            table = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
            table[:n_cp] = m[0]
            table[n_cp:n_cp + n_pages] = allocated[i]
            self.block_tables[slots[i]] = table
            tables.append(table)
        # padding rows repeat the last real row (tokens, prefix AND
        # pages): the duplicate scatter writes recompute identical KV
        # into the same pages — idempotent, the paged_prefill_batch
        # contract
        tokens[n:] = tokens[n - 1]
        clens[n:] = clens[n - 1]
        ptabs[n:] = ptabs[n - 1]
        maps[n:] = maps[n - 1]

        with profiling.annotate("engine.prefill"):
            self._count("engine.dispatches")
            self.pool, logits = self._prefill_chunk_batch(
                self.model_cfg, self.params, self.pool,
                jnp.asarray(tokens), jnp.asarray(clens),
                jnp.asarray(plens), jnp.asarray(ptabs),
                jnp.asarray(maps))
            self._key, sub = jax.random.split(self._key)
            firsts = self._sample(logits, sub, self.sampling)
        self._count("engine.prefill_tokens",
                    sum(len(rest) for rest in rests))
        self._count("engine.prefix_hit_tokens", n_cached * n)
        self._count("engine.prefix_batch_hit_admissions", n)

        if any(r.grammar is not None for r in reqs):
            # grammar groups stay synchronous (FSM needs the values now)
            finished: List[SequenceResult] = []
            (firsts_host,) = self._fetch(firsts)
            for i, (req, m) in enumerate(zip(reqs, matches)):
                early = self._activate_paged(req, slots[i], tables[i], n_cp,
                                             logits[i:i + 1],
                                             int(firsts_host[i]))
                if early is not None:
                    finished.append(early)
            return finished
        # deferred batch admission: ONE coalesced fetch at the next
        # drain/flush covers the whole wave (docs/performance.md)
        for i, req in enumerate(reqs):
            st = self._preactivate_paged(req, slots[i], tables[i], n_cp)
            self._dev_edit_token(slots[i], firsts[i])
            self._defer_first(st, firsts, i)
        return []

    def _admit_batch(self, reqs: List[_Pending]) -> List[SequenceResult]:
        """Admit N same-bucket prefix-miss sequences with ONE batched
        paged prefill (pads to a power of two by repeating the last real
        row's tokens AND pages — the duplicate scatter writes are
        idempotent, same contract as llama.prefill_batch slots)."""
        n = len(reqs)
        bucket = min(self._bucket(max(len(r.prompt_ids) for r in reqs)),
                     self.pages_per_seq * self.page_size)
        n_pages = bucket // self.page_size
        allocated: List[List[int]] = []
        try:
            for r in reqs:
                allocated.append(
                    self._alloc_with_evict(n_pages, owner=r.seq_id))
        except OutOfPages:
            for r, pages in zip(reqs, allocated):
                self.allocator.free(pages, owner=r.seq_id)
            raise
        slots = [self._free_slots.pop(0) for _ in range(n)]

        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        if self._pp and n_pad % self._pp_m:
            # the pipelined prefill microbatches its rows: pad to a
            # microbatch multiple (padding rows repeat the last real row's
            # tokens AND pages, so duplicate scatter writes stay idempotent)
            n_pad = -(-n_pad // self._pp_m) * self._pp_m
        tokens = np.zeros((n_pad, bucket), np.int32)
        lens = np.zeros((n_pad,), np.int32)
        maps = np.zeros((n_pad, n_pages), np.int32)
        tables = []
        for i, r in enumerate(reqs):
            tokens[i, :len(r.prompt_ids)] = r.prompt_ids
            lens[i] = len(r.prompt_ids)
            maps[i] = allocated[i]
            table = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
            table[:n_pages] = allocated[i]
            self.block_tables[slots[i]] = table
            tables.append(table)
        tokens[n:] = tokens[n - 1]
        lens[n:] = lens[n - 1]
        maps[n:] = maps[n - 1]

        with profiling.annotate("engine.prefill"):
            self._count("engine.dispatches")
            self.pool, logits = self._prefill_batch(
                self.model_cfg, self.params, self.pool,
                jnp.asarray(tokens), jnp.asarray(lens), jnp.asarray(maps))
            self._key, sub = jax.random.split(self._key)
            firsts = self._sample(logits, sub, self.sampling)
        self._count("engine.prefill_tokens", int(lens[:n].sum()))
        self._count("engine.batched_admissions", n)

        if any(r.grammar is not None for r in reqs):
            # grammar groups stay synchronous (FSM needs the values now)
            finished: List[SequenceResult] = []
            (firsts_host,) = self._fetch(firsts)
            for i, req in enumerate(reqs):
                early = self._activate_paged(req, slots[i], tables[i], 0,
                                             logits[i:i + 1],
                                             int(firsts_host[i]))
                if early is not None:
                    finished.append(early)
            return finished
        # deferred batch admission: ONE coalesced fetch at the next
        # drain/flush covers the whole wave (docs/performance.md)
        for i, req in enumerate(reqs):
            st = self._preactivate_paged(req, slots[i], tables[i], 0)
            self._dev_edit_token(slots[i], firsts[i])
            self._defer_first(st, firsts, i)
        return []

    def _grow(self, slot: int) -> None:
        st = self._active[slot]
        # covered length: the next dispatch writes at the DEVICE length,
        # which leads the host mirror by the in-flight lag
        idx = self._covered_len(slot) // self.page_size
        if idx >= self.pages_per_seq:
            return                              # at cap; finish_reason handles
        if self.block_tables[slot, idx] != TRASH_PAGE:
            return                              # page already present
        if self._cp_parts:
            (page,) = self._alloc_seq_pages([idx], owner=st.seq_id)
        else:
            (page,) = self._alloc_with_evict(1, owner=st.seq_id)
        self.block_tables[slot, idx] = page
        self._dev_edit_bt_row(slot)

    def _preempt_victim(self, exclude: Optional[int] = None,
                        spill: bool = True) -> bool:
        """Evict one active sequence and requeue it: LOWEST priority
        class first (largest priority int), youngest (most-recently-
        admitted) within the class — so a BATCH sweep run always yields
        pages before a CRITICAL incident does, and the pre-priority
        behavior (plain youngest-first) is preserved exactly when every
        sequence is NORMAL.  ``spill=False`` forces the free-and-
        re-prefill path even when spill is enabled (the "crash" tick
        fault models device KV loss)."""
        candidates = [s for s in self._active if s != exclude]
        if not candidates:
            return False
        slot = max(candidates,
                   key=lambda s: (self._active[s].priority,
                                  self._active[s].seq_id))
        self._preempt_slot(slot, spill=spill)
        return True

    def _release_slot_pages(self, slot: int, st: _Active) -> None:
        """Return a slot's pages: shared prefix back to the prefix cache
        (refcount drop), private pages to the allocator."""
        table = self.block_tables[slot]
        shared = [int(p) for p in table[:st.n_shared]]
        private = [int(p) for p in table[st.n_shared:] if p != TRASH_PAGE]
        if shared:
            self.prefix_cache.release(shared)
        if private:
            self.allocator.free(private, owner=st.seq_id)

    def _preempt_slot(self, slot: int, spill: bool = True,
                      budget_exempt: bool = False) -> None:
        st = self._active.pop(slot)
        spilled = spill and self._maybe_spill(slot, st,
                                              budget_exempt=budget_exempt)
        if not spilled:
            self._release_slot_pages(slot, st)
        self.block_tables[slot] = TRASH_PAGE
        self._dev_edit_bt_row(slot)     # contain in-flight garbage writes
        self._free_slots.append(slot)
        # requeue at the FRONT (within the priority class) with context so
        # far.  If the KV spilled, _tick_admission resumes it by h2d page
        # restore; otherwise re-prefill resumes it.  Either way generated-
        # so-far moves into the resume prompt and is remembered in
        # _resumed so the final SequenceResult still reports the ORIGINAL
        # prompt/completion split.
        prefix = self._resumed.get(st.seq_id, []) + st.generated
        self._resumed[st.seq_id] = prefix
        resumed_prompt = self._prompts[st.seq_id] + prefix
        remaining = max(1, st.max_new_tokens - len(st.generated))
        log.info("preempting seq %d (slot %d, %d tokens, %s) to free pages",
                 st.seq_id, slot, len(resumed_prompt),
                 "kv spilled" if spilled else "re-prefill")
        self._count("engine.preemptions", 1)
        # the grammar FSM rides along: its state already reflects every
        # generated token now baked into the resume prompt
        self._enqueue(_Pending(
            st.seq_id, resumed_prompt, remaining, st.stop_strings,
            st.grammar, priority=st.priority), front=True)

    def _demote_prefix_pages(self, pages: List[int]
                             ) -> Optional[List[Dict[str, object]]]:
        """PrefixCache demote hook: ONE coalesced d2h gather of resident
        prefix pages (the same page-record layout ``_maybe_spill``
        builds, utils/pages.py) split into per-page store entries.
        Counted as ``engine.prefix_demotions`` per page.  The gather
        never touches the spill budget: demoted PREFIX pages live in the
        PrefixStore under its own prefix_host_pages/prefix_disk_pages
        caps, while ``max_spilled_pages`` keeps governing spilled RUN
        pages only."""
        with profiling.annotate("engine.prefix_demote"):
            rec = gather_pages(self.pool, self._fetch, pages)
            self._count("engine.prefix_demotions", len(pages))
            return split_pages(rec)

    def _promote_prefix_records(self, recs: List[Dict[str, object]]
                                ) -> Optional[List[int]]:
        """PrefixCache promote hook: allocate fresh CACHE_OWNER pages and
        h2d-scatter demoted records back (``_admit_spilled``'s restore
        scatter via utils/pages.py).  Returns the page ids, or None —
        treated as a cold miss by the tier-aware ``match`` — when the
        records don't fit this engine's pool (a store shared across
        engine configs) or the allocator has no room.  Allocation is
        PLAIN (no evict-on-pressure): evicting L0 to promote L1 would
        demote inside a match, churning pages for zero net gain."""
        if not recs or not all(records_compatible(self.pool, r)
                               for r in recs):
            return None
        try:
            pages = self.allocator.alloc(len(recs), owner=CACHE_OWNER)
        except OutOfPages:
            return None
        with profiling.annotate("engine.prefix_promote"):
            rec = stack_pages(recs)
            self.pool = restore_pages(self.pool, rec, pages)
            self._count("engine.prefix_promoted_pages", len(pages))
            self._count("engine.prefix_bytes_restored",
                        record_nbytes(rec))
        return pages

    def flush_prefix_store(self, limit: Optional[int] = None) -> int:
        """Publish resident prefix pages into the shared ``PrefixStore``
        WITHOUT freeing them (one coalesced gather; already-stored
        digests skipped) — the cluster warm-start seam: a replica
        flushes before ``drain_replica`` snapshots it (and ahead of
        planned restarts), so fresh/restarted replicas sharing the
        store restore-by-pages instead of re-prefilling.  Returns the
        number of pages copied; 0 without a store."""
        if self.prefix_cache is None or self.prefix_store is None:
            return 0
        self._overlap_barrier()
        return self.prefix_cache.flush_to_store(limit)

    def _publish_sequence_pages(self) -> int:
        """Store-backed instant recovery, the publish half
        (docs/durability.md "store-backed restore"): push every ACTIVE
        sequence's full written pages — prompt AND generated-so-far, not
        just the cached prefix chains — into the shared store, keyed by
        the same chained page digests ``PrefixCache.match`` probes.

        Called by ``snapshot_sequences`` (so crash snapshots and drain
        migrations leave a warm fabric behind) and harmless without a
        store (returns 0).  The restore side needs NO new machinery:
        ``restore_sequences`` re-admits through a normal prefill of
        prompt + generated, and tier-aware ``match`` promotes these
        pages back — spill-identical bucket math (``suffix_bucket``),
        one h2d scatter, re-prefilling only the sub-page tail.  ONE
        coalesced d2h gather for the whole publish set; already-stored
        digests and pages shared between sequences are skipped."""
        if self.prefix_cache is None or self.prefix_store is None:
            return 0
        self._overlap_barrier()
        resumed = self._resumed or {}
        P = self.page_size
        pend_pages: List[int] = []
        pend_keys: List[bytes] = []
        seen = set()
        for slot in sorted(self._active):
            st = self._active[slot]
            n_full = int(self.lengths[slot]) // P
            if n_full <= 0:
                continue
            tokens = (list(self._prompts.get(st.seq_id, []))
                      + list(resumed.get(st.seq_id, ()))
                      + list(st.generated))
            if len(tokens) < n_full * P:
                continue            # defensive: mirrors out of sync
            keys = _page_keys(tokens, n_full, P)
            table = self.block_tables[slot]
            for i, key in enumerate(keys):
                page = int(table[i])
                if page == TRASH_PAGE or key in seen:
                    continue
                seen.add(key)
                if self.prefix_store.contains(key):
                    continue
                pend_pages.append(page)
                pend_keys.append(key)
        if not pend_pages:
            return 0
        with profiling.annotate("engine.prefix_publish"):
            rec = gather_pages(self.pool, self._fetch, pend_pages)
            for key, page_rec in zip(pend_keys, split_pages(rec)):
                self.prefix_store.put(key, page_rec)
        self._count("engine.prefix_snapshot_published", len(pend_keys))
        return len(pend_keys)

    def _maybe_spill(self, slot: int, st: _Active,
                     budget_exempt: bool = False) -> bool:
        """Spill a preempted slot's written private KV pages to host
        buffers (ONE coalesced d2h gather) so the sequence later resumes
        by h2d page restore instead of re-prefill.  Returns False — and
        leaves the caller on the free-and-re-prefill path — when spill is
        off, the slot's first token hasn't committed yet (deferred
        admission under host_overlap: its KV-covered length is ambiguous),
        a mid-chunk page is TRASH, or the host-page budget
        (``EngineConfig.max_spilled_pages``) would be exceeded.

        On success the private written pages are freed to the allocator
        (the record holds host copies), the shared prefix pages KEEP their
        prefix-cache refcounts (held by the record, transferred back to
        the slot at restore) so they cannot be evicted while spilled."""
        # budget_exempt (export_run, cluster/disagg.py): the gathered
        # pages leave for another replica as soon as the adopter acks —
        # charging them against max_spilled_pages (or requiring the
        # feature on) would couple handoff capacity to local spill policy
        if not budget_exempt and not self.engine_cfg.max_spilled_pages:
            return False
        prefix = self._resumed.get(st.seq_id, []) + st.generated
        if not prefix:
            return False
        # committed-state invariant (steady state):
        #   lengths[slot] == prompt_tokens + len(generated) - 1
        # a freshly-admitted slot whose deferred first token hasn't
        # committed yet breaks it (lengths == prompt_tokens, generated
        # empty) — not spillable, fall back to re-prefill
        length = int(self.lengths[slot])
        if length + 1 != st.prompt_tokens + len(st.generated):
            return False
        ps = self.page_size
        n_written = -(-length // ps)
        table = self.block_tables[slot]
        shared = [int(p) for p in table[:st.n_shared]]
        spill_idx = [int(p) for p in table[st.n_shared:n_written]]
        if any(p == TRASH_PAGE for p in spill_idx):
            return False
        if (not budget_exempt
                and self._spilled_pages_total + len(spill_idx)
                > self.engine_cfg.max_spilled_pages):
            self._count("engine.spill_budget_fallbacks")
            return False
        extra = [int(p) for p in table[n_written:] if p != TRASH_PAGE]
        with profiling.annotate("engine.spill"):
            rec: Dict[str, object] = {
                "n_pages": len(spill_idx), "n_shared": st.n_shared,
                "shared_pages": shared, "length": length,
                "cur_token": int(self.cur_tokens[slot]),
            }
            if spill_idx:
                # shared d2h page gather (utils/pages.py): the ONE
                # coalesced fetch the prefix-demote hook also uses
                rec.update(gather_pages(self.pool, self._fetch,
                                        spill_idx))
            self._spilled[st.seq_id] = rec
            self._spilled_pages_total += len(spill_idx)
            self._count("engine.spilled_pages", len(spill_idx))
        if spill_idx or extra:
            self.allocator.free(spill_idx + extra, owner=st.seq_id)
        return True

    def _admit_spilled(self, req: _Pending) -> None:
        """Resume a KV-spilled sequence: allocate a fresh page run (SAME
        bucket math as ``_admit``'s re-prefill path, so allocator state
        evolves identically either way), h2d-scatter the spilled pages
        back, and re-register the slot at its exact preemption state — no
        prefill dispatch, no re-sampled token, byte-identical decode."""
        rec = self._spilled[req.seq_id]
        ps = self.page_size
        n_shared = int(rec["n_shared"])
        length = int(rec["length"])
        # resume prompt = original prompt + generated-so-far; its length
        # is length + 1 (the last generated token is cur, its KV pending)
        resume_len = length + 1
        assert resume_len == len(req.prompt_ids), (resume_len,
                                                   len(req.prompt_ids))
        rest = resume_len - n_shared * ps
        bucket, n_pages = suffix_bucket(self._bucket, rest, n_shared, ps,
                                        self.pages_per_seq)
        pages = self._alloc_seq_pages(range(n_shared, n_shared + n_pages),
                                      owner=req.seq_id)
        n_spill = int(rec["n_pages"])
        with profiling.annotate("engine.restore"):
            if n_spill:
                # shared h2d page scatter (utils/pages.py): the same
                # restore the prefix-promote hook performs
                self.pool = restore_pages(self.pool, rec,
                                          pages[:n_spill])
            slot = self._free_slots.pop(0)
            table = np.full((self.pages_per_seq,), TRASH_PAGE, np.int32)
            table[:n_shared] = rec["shared_pages"]
            table[n_shared:n_shared + n_pages] = pages
            self.block_tables[slot] = table
            # prompt_tokens counts the RESUME prompt (like the re-prefill
            # path); _retire reports against _prompts/_resumed as usual.
            # The shared pages' prefix-cache refs transfer from the spill
            # record to the slot (released at retire, symmetric).
            st = _Active(seq_id=req.seq_id, slot=slot,
                         prompt_tokens=resume_len,
                         max_new_tokens=req.max_new_tokens,
                         stop_strings=req.stop_strings, grammar=req.grammar,
                         n_shared=n_shared, priority=req.priority)
            self._active[slot] = st
            self.lengths[slot] = length
            self.cur_tokens[slot] = int(rec["cur_token"])
            self._dev_edit_len(slot, length)
            self._dev_edit_token(slot, int(rec["cur_token"]))
            self._dev_edit_bt_row(slot)
            del self._spilled[req.seq_id]
            self._spilled_pages_total -= n_spill
            self._count("engine.restored_pages", n_spill)

    def _drop_spill(self, seq_id: int) -> None:
        """Discard a spill record (cancel / deadline expiry while queued):
        free the host buffers and drop the shared-prefix refcounts the
        record was holding."""
        rec = self._spilled.pop(seq_id, None)
        if rec is None:
            return
        self._spilled_pages_total -= int(rec["n_pages"])
        if rec["shared_pages"] and self.prefix_cache is not None:
            self.prefix_cache.release(rec["shared_pages"])

    # ------------------------------------------- per-run export / adopt

    def export_run(self, seq_id: int
                   ) -> Optional[Tuple[Dict[str, object],
                                       Optional[Dict[str, object]]]]:
        """Paged EXPORT: an actively-decoding run is frozen via the
        preemption path (``_preempt_slot`` with the spill budget waived —
        the pages are leaving, not parking) so the returned kv record
        carries its computed KV; a still-queued run exports entry-only
        (the adopter re-prefills byte-identically).  The sequence stays
        pinned in the pending queue WITH its spill record until the
        caller cancels it (RELEASE) — export is idempotent across retry
        attempts.  None = not exportable this pump (mid-chunked-prefill,
        or a deferred first token not yet committed)."""
        self._overlap_barrier()
        for pst in self._prefilling.values():
            if pst["req"].seq_id == seq_id:
                return None
        for slot, st in list(self._active.items()):
            if st.seq_id == seq_id:
                prefix = self._resumed.get(seq_id, []) + st.generated
                length = int(self.lengths[slot])
                if (not prefix or length + 1
                        != st.prompt_tokens + len(st.generated)):
                    # nothing generated yet / deferred first token not
                    # committed — the next tick commits; retry then
                    return None
                self._preempt_slot(slot, spill=True, budget_exempt=True)
                break
        for req in self._pending:
            if req.seq_id == seq_id:
                return (self._export_entry(req, self._resumed),
                        self._transfer_record(seq_id))
        raise ValueError(f"export_run: seq {seq_id} is not live")

    def _transfer_record(self, seq_id: int
                         ) -> Optional[Dict[str, object]]:
        """The host-safe page record a handoff frame ships: the spill
        record's private pages plus a READ-ONLY gather of its shared
        prefix pages, flattened to one self-contained run (n_shared=0 on
        the wire — the adopter owns every page it restores; its own
        prefix cache re-shares on later runs).  The local record and its
        prefix refcounts are untouched: RELEASE (cancel_seq →
        ``_drop_spill``) frees them only after the adopter acks."""
        rec = self._spilled.get(seq_id)
        if rec is None:
            return None
        parts: List[Dict[str, object]] = []
        shared = [int(p) for p in rec["shared_pages"]]
        if shared:
            parts.append(gather_pages(self.pool, self._fetch, shared))
        n_priv = int(rec["n_pages"])
        if n_priv:
            part: Dict[str, object] = {"n_pages": n_priv}
            for f in record_fields(rec):
                part[f] = rec[f]
            parts.append(part)
        if not parts:
            return None
        out = dict(parts[0]) if len(parts) == 1 else stack_pages(parts)
        out["n_shared"] = 0
        out["shared_pages"] = []
        out["length"] = int(rec["length"])
        out["cur_token"] = int(rec["cur_token"])
        return out

    def adopt_run(self, entry: Dict[str, object], kv=None,
                  grammar=None) -> int:
        """Paged ADOPT: re-admit the entry, then stage the transferred
        KV record as a local spill so ``_admit_spilled`` resumes it by
        h2d restore at the exact preemption state.  EVERY validation —
        and any cross-layout conversion — runs BEFORE the entry is
        admitted, so a refusal raised here leaves no engine state for
        the router's retry to duplicate.  Three outcomes per record:

        - geometry matches this pool → staged verbatim
          (``engine.handoff_kv_adopted``);
        - page_size differs but dtype/kv_dim/layer-count/field-set
          match → deterministically re-chunked onto this pool's page
          size (``utils.pages.convert_page_record``,
          ``engine.handoff_kv_relayout`` counted alongside the adopt);
        - torn frame (shared pages on the wire, length mismatch, page
          overflow after conversion) → dropped whole and the run
          re-prefills, counted ``engine.handoff_kv_rejected``; while a
          dtype/kv_dim/field-set mismatch is a loud ValueError — that
          is a MISCONFIGURED tier pair (TierRouter refuses to build
          one), not a transient the retry loop could ever fix."""
        relayout = False
        if kv is not None:
            resume_len = (len(entry["prompt_ids"])
                          + len(entry["generated"]))
            n = int(kv.get("n_pages", 0))
            frame_ok = (int(kv.get("n_shared", 1)) == 0
                        and not kv.get("shared_pages")
                        and n >= 1
                        and int(kv.get("length", -1)) + 1 == resume_len)
            if not frame_ok:
                self._count("engine.handoff_kv_rejected")
                kv = None
            else:
                want_fields = (("k", "v", "k_scale", "v_scale")
                               if self.pool.quantized else ("k", "v"))
                karr = np.asarray(kv["k"])
                ref = self.pool.k
                if (record_fields(kv) != want_fields
                        or karr.ndim != 4
                        or karr.shape[0] != ref.shape[0]
                        or karr.shape[3] != ref.shape[3]
                        or karr.dtype != ref.dtype):
                    raise ValueError(
                        f"adopt_run: transfer record geometry "
                        f"(fields={record_fields(kv)}, "
                        f"shape={karr.shape}, dtype={karr.dtype}) is "
                        f"incompatible with this pool "
                        f"(fields={want_fields}, layers={ref.shape[0]}, "
                        f"kv_dim={ref.shape[3]}, dtype={ref.dtype}): "
                        f"only page_size may differ between tiers — "
                        f"this is a misconfigured tier pair, not a "
                        f"retryable frame fault")
                if karr.shape[2] != ref.shape[2]:
                    converted = convert_page_record(
                        kv, int(kv["length"]), int(ref.shape[2]))
                    converted.update(
                        n_shared=0, shared_pages=[],
                        length=int(kv["length"]),
                        cur_token=int(kv["cur_token"]))
                    kv, relayout = converted, True
                    n = int(kv["n_pages"])
                if n > self.pages_per_seq or not pool_compatible(
                        self.pool, kv):
                    self._count("engine.handoff_kv_rejected")
                    kv = None
        sid = super().adopt_run(entry, kv=None, grammar=grammar)
        if kv is None:
            return sid
        self._spilled[sid] = kv
        self._spilled_pages_total += int(kv["n_pages"])
        if relayout:
            self._count("engine.handoff_kv_relayout")
        self._count("engine.handoff_kv_adopted")
        return sid

    def _expire_extra(self, seq_id: int) -> Optional[SequenceResult]:
        """Deadline-reap a mid-chunked-prefill sequence: build its result
        BEFORE _abort_prefilling pops the _prompts/_resumed records."""
        for slot, pst in list(self._prefilling.items()):
            if pst["req"].seq_id == seq_id:
                res = self._expired_result(seq_id, pst["req"])
                self._abort_prefilling(slot)
                return res
        return None

    def _retire(self, slot: int, reason: str) -> SequenceResult:
        st = self._active.pop(slot)
        if self._deadlines:
            self._deadlines.pop(st.seq_id, None)
        self._release_slot_pages(slot, st)
        self.allocator.check()
        self.block_tables[slot] = TRASH_PAGE
        self._dev_edit_bt_row(slot)     # contain in-flight garbage writes
        self._free_slots.append(slot)
        # a preempted-and-resumed sequence's st.generated holds only the
        # post-resume tokens; stitch the pre-preemption prefix back on and
        # report against the ORIGINAL prompt
        orig_prompt = self._prompts.pop(st.seq_id)
        generated = self._resumed.pop(st.seq_id, []) + st.generated
        text = self._final_text(generated, reason, st.stop_strings)
        return SequenceResult(
            seq_id=st.seq_id, token_ids=list(generated), text=text,
            finish_reason=reason, prompt_tokens=len(orig_prompt),
            completion_tokens=len(generated))
