"""Speculative decoding drafts: n-gram prompt lookup and a draft MODEL.

Draft tokens are proposed either by matching the sequence's most recent
n-gram against its own earlier context (prompt lookup — no model), or by
a small draft Llama running ahead greedily (``ModelDraft``).  Either way
verification runs ONE multi-token decode step (models/llama.decode_multi)
scoring all draft positions at once; the longest prefix of drafts that
matches the target model's own greedy choice is accepted, plus one bonus
token from the first mismatching position.  Output is therefore
IDENTICAL to plain greedy decoding — speculation only changes how many
tokens each engine tick commits, and the draft's quality only moves the
acceptance rate, never correctness.

Why it fits this workload: decode ticks are latency-bound (a fixed-cost
sweep over the layer stack), so scoring K+1 positions instead of 1 is
nearly free, and the RCA stages emit highly repetitive structured output
(JSON field names, kinds, kubectl phrases that already appear in the
prompt), which is exactly where prompt-lookup acceptance is high; a
distilled draft (rca/distill.py produces one) lifts acceptance on the
free-text spans the n-gram lookup cannot predict.  The reference has no
decoding loop to accelerate at all (tokens stream from the OpenAI
server, reference common/openai_generic_assistant.py:92-115).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


def ngram_draft(context: Sequence[int], n: int, k: int) -> List[int]:
    """Propose up to ``k`` draft tokens by prompt lookup.

    Finds the most recent earlier occurrence of the last ``n`` tokens of
    ``context`` and returns the tokens that followed it.  Empty when the
    n-gram has no earlier occurrence (caller falls back to plain decode).
    """
    if n <= 0 or k <= 0 or len(context) <= n:
        return []
    pattern = list(context[-n:])
    # scan right-to-left over earlier windows; the most recent prior
    # occurrence predicts the continuation best
    for start in range(len(context) - n - 1, -1, -1):
        if list(context[start:start + n]) == pattern:
            cont = list(context[start + n:start + n + k])
            if cont:
                return cont
            return []
    return []


class ModelDraft:
    """Draft-model speculation state: a small Llama with its own
    contiguous cache mirrors the target engine's slots and proposes k
    greedy tokens per tick (one ``decode_scan`` over the whole batch).

    Correctness never depends on the draft — the target verifies every
    token — so the draft cache tolerates two approximations:

    - **lazy slot sync**: each tick, a slot whose (seq_id, context
      length) key diverged from the draft's bookkeeping (admission,
      preemption-resume, interleaved non-speculative ticks) re-prefills
      its draft cache row from the authoritative context; on the
      steady-state speculative path ``advance`` keeps the key current so
      the re-prefill never fires;
    - **garbage past the committed length**: rejected draft positions
      leave stale KV above ``lengths``, which the next tick's sequential
      writes overwrite and attention masks out by length.

    Contexts longer than the draft's cache keep only their TAIL (draft
    quality degrades gracefully; verification is unaffected).
    """

    def __init__(self, cfg, params, engine_cfg):
        import jax
        import numpy as np

        from k8s_llm_rca_tpu.engine.sampling import SamplingParams
        from k8s_llm_rca_tpu.models import llama

        self.cfg = cfg
        self.params = params
        b = engine_cfg.max_batch
        self.k = engine_cfg.speculative_k
        self.max_seq = min(cfg.max_seq_len, engine_cfg.max_seq_len)
        self.cache = llama.init_cache(cfg, b, self.max_seq)
        self.lengths = np.zeros((b,), np.int64)
        self.cur = np.zeros((b,), np.int64)
        self._owner: Dict[int, Tuple[int, int]] = {}   # slot -> (seq, ctxlen)
        self.prefills = 0          # sync re-prefill count (diagnostics/tests)
        self._buckets = tuple(
            s for s in sorted(set(engine_cfg.prefill_buckets))
            if s <= self.max_seq) or (self.max_seq,)
        self._greedy = SamplingParams()                # temperature 0
        from k8s_llm_rca_tpu.engine.engine import decode_scan

        self._prefill = jax.jit(llama.prefill, static_argnums=0)
        self._scan = jax.jit(decode_scan, static_argnums=(0, 6, 7, 8))
        self._key = jax.random.PRNGKey(0)              # greedy: unused noise
        # owning engines hook this to account the draft scan's blocking
        # token fetch in their engine.d2h_syncs counter (docs/performance.md)
        self.on_sync = None

    def _bucket(self, n: int) -> int:
        for s in self._buckets:
            if n <= s:
                return s
        return self.max_seq

    def sync(self, slot: int, seq_id: int, context: Sequence[int]) -> None:
        import jax.numpy as jnp
        import numpy as np

        if self._owner.get(slot) == (seq_id, len(context)):
            return
        # tail-clip leaving a real DRAFTING WINDOW (a quarter of the
        # cache, at least one full k+1 scan): clipping to the cache edge
        # would leave no headroom, so the slot would re-prefill its full
        # tail every 1-2 ticks while drafting almost nothing — a pure
        # dispatch tax, worst on dispatch-bound hosts.  The shorter tail
        # only affects draft QUALITY; one re-prefill then buys ~window/c
        # drafting ticks
        window = max(self.k + 2, self.max_seq // 4)
        ctx = list(context[-max(2, self.max_seq - window):])
        n = len(ctx) - 1                               # cur token stays out
        if n <= 0:
            self.lengths[slot] = 0
            self.cur[slot] = ctx[-1] if ctx else 0
            self._owner[slot] = (seq_id, len(context))
            return
        padded = np.zeros((1, self._bucket(n)), np.int32)
        padded[0, :n] = ctx[:-1]
        self.prefills += 1
        self.cache, _ = self._prefill(self.cfg, self.params, self.cache,
                                      jnp.asarray(padded), jnp.int32(n),
                                      jnp.int32(slot))
        self.lengths[slot] = n
        self.cur[slot] = ctx[-1]
        self._owner[slot] = (seq_id, len(context))

    def draft(self, active_slots, k: int, eos_id: int):
        """One greedy scan for the whole batch; returns {slot: draft
        tokens} (empty for slots without cache room).

        The scan runs k+1 steps, one MORE than the k drafts returned:
        step j writes the KV of its INPUT token, so k steps would leave
        the LAST draft's KV unwritten — and on full acceptance ``advance``
        would then mark that never-written position as valid, silently
        corrupting the draft context exactly in the high-acceptance case
        this feature targets.  The k+1-th step writes it (its emitted
        token is discarded)."""
        import jax.numpy as jnp
        import numpy as np

        roomy = {s for s in active_slots
                 if int(self.lengths[s]) + k + 1 < self.max_seq}
        if not roomy:
            # no scan ran, so not even cur's KV gets written this tick —
            # drop the keys or the bonus-token commit would mark an
            # unwritten position as valid (same hole as above)
            for s in active_slots:
                self._owner.pop(s, None)
            return {s: [] for s in active_slots}
        self.cache, toks, _ = self._scan(
            self.cfg, self.params, self.cache,
            jnp.asarray(self.cur, jnp.int32),
            jnp.asarray(self.lengths, jnp.int32),
            self._key, k + 1, self._greedy, eos_id)
        from k8s_llm_rca_tpu.engine.engine import host_np
        if self.on_sync is not None:
            self.on_sync()
        toks_host = host_np(toks)                      # [k+1, B]
        out = {}
        for s in active_slots:
            if s in roomy:
                out[s] = [int(toks_host[j, s]) for j in range(k)]
            else:
                out[s] = []
                self._owner.pop(s, None)       # force re-sync when room frees
        return out

    def advance(self, slot: int, seq_id: int,
                committed: Sequence[int]) -> None:
        """Record a verified commit: the accepted prefix's KV is already
        in the draft cache (those positions were written with the same
        tokens during the draft scan); the bonus token becomes the next
        cur.  Anything inconsistent just drops the key and re-syncs."""
        owner = self._owner.get(slot)
        if owner is None or not committed:
            return
        seq, ctxlen = owner
        if seq != seq_id:
            self._owner.pop(slot, None)
            return
        new_len = int(self.lengths[slot]) + len(committed)
        if new_len >= self.max_seq:
            self._owner.pop(slot, None)                # tail re-prefill later
            return
        self.lengths[slot] = new_len
        self.cur[slot] = committed[-1]
        self._owner[slot] = (seq, ctxlen + len(committed))
