"""N-gram speculative decoding (prompt-lookup drafts).

Draft tokens are proposed by matching the sequence's most recent n-gram
against its own earlier context (prompt + generation) — no draft model.
Verification runs ONE multi-token decode step (models/llama.decode_multi)
scoring all draft positions at once; the longest prefix of drafts that
matches the model's own greedy choice is accepted, plus one bonus token
from the first mismatching position.  Output is therefore IDENTICAL to
plain greedy decoding — speculation only changes how many tokens each
engine tick commits.

Why it fits this workload: decode ticks are latency-bound (a fixed-cost
sweep over the layer stack), so scoring K+1 positions instead of 1 is
nearly free, and the RCA stages emit highly repetitive structured output
(JSON field names, kinds, kubectl phrases that already appear in the
prompt), which is exactly where prompt-lookup acceptance is high.  The
reference has no decoding loop to accelerate at all (tokens stream from
the OpenAI server, reference common/openai_generic_assistant.py:92-115).
"""

from __future__ import annotations

from typing import List, Sequence


def ngram_draft(context: Sequence[int], n: int, k: int) -> List[int]:
    """Propose up to ``k`` draft tokens by prompt lookup.

    Finds the most recent earlier occurrence of the last ``n`` tokens of
    ``context`` and returns the tokens that followed it.  Empty when the
    n-gram has no earlier occurrence (caller falls back to plain decode).
    """
    if n <= 0 or k <= 0 or len(context) <= n:
        return []
    pattern = list(context[-n:])
    # scan right-to-left over earlier windows; the most recent prior
    # occurrence predicts the continuation best
    for start in range(len(context) - n - 1, -1, -1):
        if list(context[start:start + n]) == pattern:
            cont = list(context[start + n:start + n + k])
            if cont:
                return cont
            return []
    return []
