"""Page-granular prefix cache: KV reuse across requests that share a prompt
prefix.

Why this exists: the reference's agent threads grow monotonically — every
retry and every per-entity audit appends to one OpenAI thread whose full
history is re-submitted on each run (reference check_state/
analyze_root_cause.py:184,243, test_all.py:70-83) — so consecutive runs in
an RCA incident share almost their entire prompt.  Server-side that cost is
invisible; in-tree it means re-prefilling thousands of identical tokens per
run.  This cache shares the paged KV of page-aligned prompt prefixes
between sequences (vLLM "automatic prefix caching" re-designed for this
engine's page pool).

Design:
- The key of page ``i`` of a prompt is a digest of tokens ``[0, (i+1)*P)``
  (P = page_size): KV at a position depends on every earlier token, so a
  page is reusable only under an exact full-prefix match.
- Shared pages are owned by the allocator owner tag ``CACHE_OWNER``;
  per-page refcounts track active users.  Pages at refcount 0 stay
  resident (and chained) in an LRU pool; ``evict`` frees them back to the
  allocator under memory pressure.  A page with refcount > 0 is never
  evicted, so block tables of running sequences stay valid.
- Sharing is read-only by construction: a shared page covers positions
  < n_cached <= prompt_len, and decode only writes at positions >=
  prompt_len; sequences never write into a page they share.
- ``insert`` keeps the shared run contiguous: it stops at the first full
  page whose key is already chained to a *different* page (a concurrent
  duplicate prefill) — that page stays private to its sequence.

The reference has no KV reuse of any kind (every run re-bills the full
prompt, reference common/openai_generic_assistant.py:117-135); this is a
TPU-native engine feature the build adds on top of the paged pool.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

# allocator owner tag for shared pages (sequence ids are >= 0)
CACHE_OWNER = -2


def _page_keys(prompt_ids: Sequence[int], n_pages: int,
               page_size: int) -> List[bytes]:
    """Chained digests: key_i = H(key_{i-1} || tokens of page i).

    Chaining keeps each page's key dependent on the FULL prefix (KV at a
    position depends on every earlier token) while costing O(n) total,
    not O(n^2) of re-hashing the whole prefix per page."""
    keys: List[bytes] = []
    prev = b""
    arr = np.asarray(prompt_ids[:n_pages * page_size], np.int32)
    for i in range(n_pages):
        h = hashlib.sha1(prev)
        h.update(arr[i * page_size:(i + 1) * page_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class PrefixCache:
    """Host-side index of shared prompt-prefix pages.

    The allocator stays the single owner-of-record of page ids; this class
    only re-tags ownership (seq <-> CACHE_OWNER via ``transfer``) and
    decides which refcount-0 pages to evict.
    """

    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self._chain: Dict[bytes, int] = {}           # prefix digest -> page
        self._key_of: Dict[int, bytes] = {}          # page -> its digest
        self._ref: Dict[int, int] = {}               # page -> active users
        self._lru: OrderedDict[int, None] = OrderedDict()   # refcount-0 pages

    # ------------------------------------------------------------- stats

    @property
    def n_resident(self) -> int:
        return len(self._key_of)

    @property
    def n_evictable(self) -> int:
        return len(self._lru)

    # ------------------------------------------------------------- match

    def match(self, prompt_ids: Sequence[int]) -> Tuple[List[int], int]:
        """Longest chained page-aligned prefix of ``prompt_ids``.

        Returns (pages, n_cached_tokens) and bumps each returned page's
        refcount.  Reuse is capped at the last FULL page strictly before
        the prompt end, so at least one prompt token is always re-prefilled
        (the sampler needs the last token's logits).
        """
        P = self.page_size
        limit = (len(prompt_ids) - 1) // P          # pages eligible for reuse
        pages: List[int] = []
        for key in _page_keys(prompt_ids, limit, P):
            page = self._chain.get(key)
            if page is None:
                break
            pages.append(page)
        for p in pages:
            self._acquire(p)
        return pages, len(pages) * P

    def has_prefix(self, prompt_ids: Sequence[int]) -> bool:
        """Cheap non-acquiring probe: would ``match`` return any pages?
        Checks only the first page's chain digest — enough for admission
        grouping to route prefix-hitting requests to the single-admit
        chunked path instead of redundantly prefilling them in a batch."""
        P = self.page_size
        if (len(prompt_ids) - 1) // P < 1:
            return False
        for key in _page_keys(prompt_ids, 1, P):
            return self._chain.get(key) is not None
        return False

    def _acquire(self, page: int) -> None:
        if self._ref.get(page, 0) == 0:
            self._lru.pop(page, None)
        self._ref[page] = self._ref.get(page, 0) + 1

    # ------------------------------------------------------------- insert

    def insert(self, prompt_ids: Sequence[int], table: Sequence[int],
               owner: int, n_matched_pages: int) -> int:
        """Chain the full prompt pages of a just-prefilled sequence.

        ``table``: the sequence's block-table prefix (page ids in prompt
        order).  Pages ``[0, n_matched_pages)`` came from ``match`` and are
        already shared; each later FULL page is transferred from ``owner``
        to the cache and chained, stopping at the first digest that is
        already chained to a different page (concurrent duplicate — stays
        private).  Returns the total number of leading shared pages this
        sequence now holds references to.
        """
        P = self.page_size
        n_full = len(prompt_ids) // P
        n_shared = n_matched_pages
        keys = _page_keys(prompt_ids, n_full, P)
        for i in range(n_matched_pages, n_full):
            key = keys[i]
            existing = self._chain.get(key)
            page = int(table[i])
            if existing is not None:
                if existing != page:
                    break                        # duplicate: keep private
                self._acquire(page)              # re-chained same page
            else:
                self.allocator.transfer([page], owner, CACHE_OWNER)
                self._chain[key] = page
                self._key_of[page] = key
                self._ref[page] = 1
            n_shared = i + 1
        return n_shared

    # ------------------------------------------------------------ release

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; refcount-0 pages become evictable
        (most recently released = last evicted)."""
        for p in pages:
            n = self._ref.get(p)
            if n is None or n <= 0:
                raise RuntimeError(f"release of unreferenced page {p}")
            if n == 1:
                self._ref[p] = 0
                self._lru[p] = None
                self._lru.move_to_end(p)
            else:
                self._ref[p] = n - 1

    # -------------------------------------------------------------- evict

    def evict(self, n: int) -> int:
        """Free up to ``n`` least-recently-used refcount-0 pages back to
        the allocator.  Returns how many were freed."""
        freed = 0
        while freed < n and self._lru:
            page, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(page)
            del self._chain[key]
            del self._ref[page]
            self.allocator.free([page], CACHE_OWNER)
            freed += 1
        if freed:
            METRICS.inc("engine.prefix_evicted_pages", freed)
        return freed
