"""Page-granular prefix cache: KV reuse across requests that share a prompt
prefix — tiered HBM (L0) → host RAM (L1) → disk (L2).

Why this exists: the reference's agent threads grow monotonically — every
retry and every per-entity audit appends to one OpenAI thread whose full
history is re-submitted on each run (reference check_state/
analyze_root_cause.py:184,243, test_all.py:70-83) — so consecutive runs in
an RCA incident share almost their entire prompt.  Server-side that cost is
invisible; in-tree it means re-prefilling thousands of identical tokens per
run.  This cache shares the paged KV of page-aligned prompt prefixes
between sequences (vLLM "automatic prefix caching" re-designed for this
engine's page pool).

Design:
- The key of page ``i`` of a prompt is a digest of tokens ``[0, (i+1)*P)``
  (P = page_size): KV at a position depends on every earlier token, so a
  page is reusable only under an exact full-prefix match.
- Shared pages are owned by the allocator owner tag ``CACHE_OWNER``;
  per-page refcounts track active users.  Pages at refcount 0 stay
  resident (and chained) in an LRU pool; ``evict`` frees them back to the
  allocator under memory pressure.  A page with refcount > 0 is never
  evicted, so block tables of running sequences stay valid.
- Sharing is read-only by construction: a shared page covers positions
  < n_cached <= prompt_len, and decode only writes at positions >=
  prompt_len; sequences never write into a page they share.
- ``insert`` keeps the shared run contiguous: it stops at the first full
  page whose key is already chained to a *different* page (a concurrent
  duplicate prefill) — that page stays private to its sequence.

Tiers (EngineConfig.prefix_host_pages / prefix_disk_dir /
prefix_disk_pages; docs/performance.md "tiered prefix cache"): with a
``PrefixStore`` attached, ``evict`` DEMOTES page KV into the store (one
coalesced d2h gather through the engine hook — the same page-record
layout KV spill uses, utils/pages.py) before freeing, and ``match``
extends past the resident chain into the store, PROMOTING hits back by
h2d page writes.  Store entries are keyed by the same chained digests,
so a promoted page is byte-identical to the page eviction demoted —
greedy parity across cold / L0 / L1 / L2 holds through the already-
trusted prefix-hit prefill path.  The store is shareable across engines
(cluster/replica.py ``build_replicas(prefix_store=...)``): replicas and
supervisor-restarted incarnations warm-start from pages their siblings
demoted or ``flush_to_store`` published.

The reference has no KV reuse of any kind (every run re-bills the full
prompt, reference common/openai_generic_assistant.py:117-135); this is a
TPU-native engine feature the build adds on top of the paged pool.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import (
    Callable, Dict, List, Optional, Sequence, Tuple,
)

import numpy as np

from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger
from k8s_llm_rca_tpu.utils.pages import (
    decode_page_record, encode_page_record,
)

log = get_logger(__name__)

# allocator owner tag for shared pages (sequence ids are >= 0)
CACHE_OWNER = -2


def _page_keys(prompt_ids: Sequence[int], n_pages: int,
               page_size: int) -> List[bytes]:
    """Chained digests: key_i = H(key_{i-1} || tokens of page i).

    Chaining keeps each page's key dependent on the FULL prefix (KV at a
    position depends on every earlier token) while costing O(n) total,
    not O(n^2) of re-hashing the whole prefix per page."""
    keys: List[bytes] = []
    prev = b""
    arr = np.asarray(prompt_ids[:n_pages * page_size], np.int32)
    for i in range(n_pages):
        h = hashlib.sha1(prev)
        h.update(arr[i * page_size:(i + 1) * page_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class PrefixStore:
    """Host-RAM (L1) + disk (L2) tiers of demoted prefix-page KV.

    Entries are per-page records (utils/pages.py layout, page axis length
    1) keyed by the chain digest of the page they held.  L1 is an LRU
    ``OrderedDict`` capped at ``host_pages``; overflow (and every put
    when ``host_pages == 0``) lands on disk when ``disk_dir`` is set,
    else is dropped (plain discard — exactly the pre-tier behavior).

    Disk entries are written with the WAL atomic recipe (utils/wal.py
    ``scan_wal``'s temp + fsync + ``os.replace``): a crash mid-write
    leaves either no file or a whole file, and the CRC frame catches a
    torn/corrupt one at load — ``get`` then answers None (silent cold
    miss) and drops the entry, never raising.  A fresh store pointed at
    an existing ``disk_dir`` re-indexes the surviving ``*.page`` files,
    which is how a restarted process (or a new replica handed the same
    directory) warm-starts across process death.

    The store is engine-agnostic and shareable: it never touches a page
    allocator or device memory — engines gather INTO it and scatter OUT
    of it through their own hooks.  Single-threaded by design, like the
    cluster pump that shares it.
    """

    def __init__(self, host_pages: int = 0,
                 disk_dir: Optional[str] = None,
                 disk_pages: int = 0):
        if host_pages < 0:
            raise ValueError(f"host_pages={host_pages} must be >= 0")
        if disk_pages < 0:
            raise ValueError(f"disk_pages={disk_pages} must be >= 0")
        if disk_pages and not disk_dir:
            raise ValueError(
                f"disk_pages={disk_pages} needs disk_dir: the cap bounds "
                f"a disk tier that does not exist without a directory")
        self.host_pages = host_pages
        self.disk_dir = disk_dir
        self.disk_pages = disk_pages
        self._l1: "OrderedDict[bytes, Dict[str, object]]" = OrderedDict()
        self._l2: "OrderedDict[bytes, str]" = OrderedDict()
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
            # deterministic re-index order (sorted names, not mtime):
            # LRU age across a restart is unknowable anyway, and sorted
            # keeps which-entry-gets-capped a pure function of the set
            for name in sorted(os.listdir(disk_dir)):
                if name.endswith(".page"):
                    try:
                        key = bytes.fromhex(name[:-len(".page")])
                    except ValueError:
                        continue        # foreign file, not an entry
                    self._l2[key] = os.path.join(disk_dir, name)

    # ------------------------------------------------------------- stats

    @property
    def n_host(self) -> int:
        return len(self._l1)

    @property
    def n_disk(self) -> int:
        return len(self._l2)

    def contains(self, key: bytes) -> bool:
        """Cheap probe (no load, no LRU touch): either tier holds it."""
        return key in self._l1 or key in self._l2

    # --------------------------------------------------------------- put

    def put(self, key: bytes, rec: Dict[str, object]) -> None:
        """Admit one demoted page record under its chain digest.  L1
        first; overflow demotes the LRU L1 entry to disk.  Re-putting a
        present key only refreshes recency — the digest pins the bytes,
        so rewriting them is pure waste."""
        if key in self._l1:
            self._l1.move_to_end(key)
            return
        if self.host_pages > 0:
            self._l1[key] = rec
            self._l1.move_to_end(key)
            while len(self._l1) > self.host_pages:
                old_key, old_rec = self._l1.popitem(last=False)
                self._to_disk(old_key, old_rec)
        else:
            self._to_disk(key, rec)

    def _to_disk(self, key: bytes, rec: Dict[str, object]) -> None:
        """Persist one record as ``<digest hex>.page`` with the atomic
        temp + fsync + ``os.replace`` recipe; without a ``disk_dir`` the
        record is dropped (legacy discard).  A record too large for the
        WAL frame is dropped too — persistence is best-effort, parity
        never depends on it (a missing entry is just a cold miss)."""
        if not self.disk_dir:
            return
        if key in self._l2:
            self._l2.move_to_end(key)
            return
        path = os.path.join(self.disk_dir, key.hex() + ".page")
        try:
            frame = encode_page_record(rec)
        except ValueError:
            return
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._l2[key] = path
        while self.disk_pages and len(self._l2) > self.disk_pages:
            self._drop_disk(*self._l2.popitem(last=False))

    @staticmethod
    def _drop_disk(key: bytes, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass                        # already gone: cap still holds

    # --------------------------------------------------------------- get

    def get(self, key: bytes
            ) -> Optional[Tuple[Dict[str, object], int]]:
        """Fetch one record; returns ``(record, tier)`` with tier 1 (host
        RAM) or 2 (disk), or None.  A disk hit is CRC-verified and
        re-admitted to L1 (it may overflow another entry back to disk);
        any torn/corrupt/missing file drops the index entry and answers
        None — the caller re-prefills, exactly the cold path."""
        rec = self._l1.get(key)
        if rec is not None:
            self._l1.move_to_end(key)
            return rec, 1
        path = self._l2.get(key)
        if path is None:
            return None
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        rec = decode_page_record(data)
        if rec is None:
            self._l2.pop(key, None)
            self._drop_disk(key, path)
            log.warning("prefix store: corrupt/unreadable disk entry "
                        "%s dropped (cold miss)", os.path.basename(path))
            return None
        self._l2.move_to_end(key)
        if self.host_pages > 0:
            # promote into L1 without re-writing the (present) disk copy
            self._l1[key] = rec
            while len(self._l1) > self.host_pages:
                old_key, old_rec = self._l1.popitem(last=False)
                self._to_disk(old_key, old_rec)
        return rec, 2


class PrefixCache:
    """Host-side index of shared prompt-prefix pages.

    The allocator stays the single owner-of-record of page ids; this class
    only re-tags ownership (seq <-> CACHE_OWNER via ``transfer``) and
    decides which refcount-0 pages to evict.

    Tier hooks (wired by the paged engine when a ``PrefixStore`` is
    attached; all None on a plain cache — behavior then is exactly the
    pre-tier discard cache):

    - ``demote(pages) -> per-page records | None``: ONE coalesced d2h
      gather of resident pages (engine ``_demote_prefix_pages``);
    - ``promote(records) -> page ids | None``: allocate CACHE_OWNER
      pages and h2d-scatter the records into them (engine
      ``_promote_prefix_records``); None means no room / incompatible
      records — treated as a cold miss;
    - ``count(name, value)``: the engine's ``_count`` so tier-hit
      counters land in TickSample/Prometheus mirrors, not just METRICS.
    """

    def __init__(self, allocator, page_size: int,
                 store: Optional[PrefixStore] = None,
                 demote: Optional[Callable] = None,
                 promote: Optional[Callable] = None,
                 count: Optional[Callable] = None):
        self.allocator = allocator
        self.page_size = page_size
        self.store = store
        self._demote = demote
        self._promote = promote
        self._count = count or (
            lambda name, value=1.0: METRICS.inc(name, value))
        self._chain: Dict[bytes, int] = {}           # prefix digest -> page
        self._key_of: Dict[int, bytes] = {}          # page -> its digest
        self._ref: Dict[int, int] = {}               # page -> active users
        self._lru: OrderedDict[int, None] = OrderedDict()   # refcount-0 pages

    # ------------------------------------------------------------- stats

    @property
    def n_resident(self) -> int:
        return len(self._key_of)

    @property
    def n_evictable(self) -> int:
        return len(self._lru)

    # ------------------------------------------------------------- match

    def match(self, prompt_ids: Sequence[int]) -> Tuple[List[int], int]:
        """Longest chained page-aligned prefix of ``prompt_ids``,
        extended tier-aware: where the resident (L0) chain ends, store
        hits for the NEXT keys are promoted back into fresh CACHE_OWNER
        pages (one h2d scatter) and chained, so the caller sees one
        contiguous shared run either way.

        Returns (pages, n_cached_tokens) and bumps each returned page's
        refcount.  Reuse is capped at the last FULL page strictly before
        the prompt end, so at least one prompt token is always re-prefilled
        (the sampler needs the last token's logits).
        """
        P = self.page_size
        limit = (len(prompt_ids) - 1) // P          # pages eligible for reuse
        keys = _page_keys(prompt_ids, limit, P)
        pages: List[int] = []
        for key in keys:
            page = self._chain.get(key)
            if page is None:
                break
            pages.append(page)
        if pages:
            self._count("engine.prefix_hits_l0", len(pages))
        pages += self._match_store(keys[len(pages):])
        for p in pages:
            self._acquire(p)
        return pages, len(pages) * P

    def _match_store(self, keys: Sequence[bytes]) -> List[int]:
        """Promote the store's run of consecutive key hits past the
        resident chain; returns the newly-chained page ids ([] without
        a store / hooks / hits / room).  Promotion allocates WITHOUT
        evicting (no demote reentrancy inside match); on OutOfPages the
        suffix simply re-prefills — a performance miss, never an error.
        """
        if self.store is None or self._promote is None or not keys:
            return []
        recs: List[Dict[str, object]] = []
        tiers: List[int] = []
        for key in keys:
            got = self.store.get(key)
            if got is None:
                break
            recs.append(got[0])
            tiers.append(got[1])
        if not recs:
            return []
        new_pages = self._promote(recs)
        if new_pages is None:
            return []
        assert len(new_pages) == len(recs)
        for key, page, tier in zip(keys, new_pages, tiers):
            self._chain[key] = page
            self._key_of[page] = key
            self._ref[page] = 0
            self._lru[page] = None      # _acquire pops it right after
            self._count(f"engine.prefix_hits_l{tier}", 1)
        return new_pages

    def has_prefix(self, prompt_ids: Sequence[int]) -> bool:
        """Cheap non-acquiring probe: would ``match`` return any pages?
        Checks only the first page's chain digest (or its store
        presence) — enough for admission grouping to route prefix-
        hitting requests to the single-admit chunked path instead of
        redundantly prefilling them in a batch."""
        P = self.page_size
        if (len(prompt_ids) - 1) // P < 1:
            return False
        for key in _page_keys(prompt_ids, 1, P):
            if self._chain.get(key) is not None:
                return True
            return self.store is not None and self.store.contains(key)
        return False

    def _acquire(self, page: int) -> None:
        if self._ref.get(page, 0) == 0:
            self._lru.pop(page, None)
        self._ref[page] = self._ref.get(page, 0) + 1

    # ------------------------------------------------------------- insert

    def insert(self, prompt_ids: Sequence[int], table: Sequence[int],
               owner: int, n_matched_pages: int) -> int:
        """Chain the full prompt pages of a just-prefilled sequence.

        ``table``: the sequence's block-table prefix (page ids in prompt
        order).  Pages ``[0, n_matched_pages)`` came from ``match`` and are
        already shared; each later FULL page is transferred from ``owner``
        to the cache and chained, stopping at the first digest that is
        already chained to a different page (concurrent duplicate — stays
        private).  Returns the total number of leading shared pages this
        sequence now holds references to.
        """
        P = self.page_size
        n_full = len(prompt_ids) // P
        n_shared = n_matched_pages
        keys = _page_keys(prompt_ids, n_full, P)
        for i in range(n_matched_pages, n_full):
            key = keys[i]
            existing = self._chain.get(key)
            page = int(table[i])
            if existing is not None:
                if existing != page:
                    break                        # duplicate: keep private
                self._acquire(page)              # re-chained same page
            else:
                self.allocator.transfer([page], owner, CACHE_OWNER)
                self._chain[key] = page
                self._key_of[page] = key
                self._ref[page] = 1
            n_shared = i + 1
        return n_shared

    # ------------------------------------------------------------ release

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; refcount-0 pages become evictable
        (most recently released = last evicted)."""
        for p in pages:
            n = self._ref.get(p)
            if n is None or n <= 0:
                raise RuntimeError(f"release of unreferenced page {p}")
            if n == 1:
                self._ref[p] = 0
                self._lru[p] = None
                self._lru.move_to_end(p)
            else:
                self._ref[p] = n - 1

    # -------------------------------------------------------------- evict

    def evict(self, n: int) -> int:
        """Free up to ``n`` least-recently-used refcount-0 pages back to
        the allocator.  With a store attached the victims' KV is DEMOTED
        first — one coalesced d2h gather of the whole victim set, then
        one ``put`` per page — so what eviction used to destroy becomes
        an L1/L2 entry a later ``match`` promotes back.  Returns how
        many pages were freed (demotion never changes the count: the
        allocator sees the identical free either way)."""
        victims: List[int] = []
        while len(victims) < n and self._lru:
            victims.append(self._lru.popitem(last=False)[0])
        if not victims:
            return 0
        if self.store is not None and self._demote is not None:
            page_recs = self._demote(victims)
            if page_recs is not None:
                for page, rec in zip(victims, page_recs):
                    self.store.put(self._key_of[page], rec)
        for page in victims:
            key = self._key_of.pop(page)
            del self._chain[key]
            del self._ref[page]
            self.allocator.free([page], CACHE_OWNER)
        METRICS.inc("engine.prefix_evicted_pages", len(victims))
        return len(victims)

    # -------------------------------------------------------------- flush

    def flush_to_store(self, limit: Optional[int] = None) -> int:
        """Copy up to ``limit`` resident pages into the store WITHOUT
        freeing them (refcounts, chain, LRU all untouched) — the warm-
        start seam: a replica flushes before a drain/snapshot, or
        periodically, so fresh/restarted replicas sharing the store
        promote instead of re-prefilling.  Pages whose digest the store
        already holds are skipped (the digest pins the bytes).  Returns
        the number of pages copied."""
        if self.store is None or self._demote is None:
            return 0
        pending = [(p, k) for p, k in self._key_of.items()
                   if not self.store.contains(k)]
        if limit is not None:
            pending = pending[:limit]
        if not pending:
            return 0
        page_recs = self._demote([p for p, _ in pending])
        if page_recs is None:
            return 0
        for (_, key), rec in zip(pending, page_recs):
            self.store.put(key, rec)
        return len(pending)
