from k8s_llm_rca_tpu.engine.engine import InferenceEngine, SequenceResult  # noqa: F401
from k8s_llm_rca_tpu.engine.sampling import sample_tokens, SamplingParams  # noqa: F401
