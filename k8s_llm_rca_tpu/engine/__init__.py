from k8s_llm_rca_tpu.engine.engine import InferenceEngine, SequenceResult  # noqa: F401
from k8s_llm_rca_tpu.engine.sampling import sample_tokens, SamplingParams  # noqa: F401


def make_engine(model_cfg, engine_cfg, params, tokenizer, **kw):
    """Engine factory: PagedInferenceEngine when ``engine_cfg.paged`` (page
    pool + preemption + prefix caching), else the contiguous-slot engine.
    Both expose the same EngineBase surface."""
    if engine_cfg.paged:
        from k8s_llm_rca_tpu.engine.paged import PagedInferenceEngine

        return PagedInferenceEngine(model_cfg, engine_cfg, params, tokenizer,
                                    **kw)
    # forward kw so an unsupported kwarg raises instead of vanishing
    return InferenceEngine(model_cfg, engine_cfg, params, tokenizer, **kw)
