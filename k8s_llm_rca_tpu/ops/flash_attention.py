"""Pallas TPU flash-attention kernel (prefill path).

Tiled online-softmax attention: the [S_q, S_k] score matrix is never
materialised in HBM.  Grid is (batch, q_head, q_block, k_block) with the
k_block axis innermost so the running max / denominator / accumulator for
one q tile stay resident in VMEM scratch across the whole k sweep.  GQA is
expressed in the BlockSpec index map (q head h reads kv head h // n_rep) —
no repeat_kv materialisation.

Numerics match ops.attention.causal_attention (the pure-XLA reference path
used on CPU and in tests); see tests/test_kernels.py.  The reference
repository has no kernels at all — its attention runs server-side behind
the OpenAI API (reference common/openai_generic_assistant.py:45-51) — so
this file is the "native kernel" layer SURVEY §2.2 requires the TPU build
to add.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128          # VPU lane width: scratch rows are padded to this


def _flash_kernel(
    seq_lens_ref,       # SMEM [B]  (valid kv length per batch row)
    q_off_ref,          # SMEM [B]  (absolute position of q block row 0)
    q_ref,              # VMEM [1, 1, block_q, d]   (head-major layout)
    k_ref,              # VMEM [1, 1, block_k, d]
    v_ref,              # VMEM [1, 1, block_k, d]
    o_ref,              # VMEM [1, 1, block_q, d]
    acc_ref,            # VMEM scratch [block_q, d] f32
    m_ref,              # VMEM scratch [block_q, _LANES] f32
    l_ref,              # VMEM scratch [block_q, _LANES] f32
    *,
    block_q: int,
    block_k: int,
):
    bi = pl.program_id(0)
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32)                # [bq, d]
    k = k_ref[0, 0].astype(jnp.float32)                # [bk, d]
    v = v_ref[0, 0].astype(jnp.float32)                # [bk, d]

    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(d))
    s = jax.lax.dot_general(
        q * scale, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                  # [bq, bk]

    qi = pl.program_id(2)
    q_pos = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
             + qi * block_q + q_off_ref[bi])
    k_pos = (jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
             + ki * block_k)
    mask = (q_pos >= k_pos) & (k_pos < seq_lens_ref[bi])
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_ref[:, 0:1]                             # [bq, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)         # [bq, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    # fully-masked rows keep m == NEG_INF; shift so exp() stays finite
    p = jnp.exp(s - jnp.where(m_new <= NEG_INF / 2, 0.0, m_new))
    correction = jnp.exp(m_prev - jnp.where(m_new <= NEG_INF / 2, 0.0, m_new))

    l_prev = l_ref[:, 0:1]
    l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)           # padded q rows
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,          # [B, S_q, n_heads, d]
    k: jnp.ndarray,          # [B, S_k, n_kv, d]
    v: jnp.ndarray,          # [B, S_k, n_kv, d]
    seq_lens: jnp.ndarray,   # [B] valid kv lengths
    q_offset: jnp.ndarray | None = None,   # [B] absolute pos of q[:, 0]
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Drop-in for ops.attention.causal_attention on TPU.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU so the
    same code path is exercised hermetically in CPU tests.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, s_q, n_heads, d = q.shape
    s_k = k.shape[1]
    n_kv = k.shape[2]
    n_rep = n_heads // n_kv

    if q_offset is None:
        q_offset = jnp.zeros((b,), jnp.int32)

    block_q = min(block_q, max(8, s_q))
    block_k = min(block_k, max(8, s_k))
    # head-major layout [B, H, S, d]: Mosaic requires the last two block
    # dims to be (8k, 128k) multiples or the full array dim — (block_q, d)
    # qualifies (d is the full dim), whereas the natural [B, S, H, d]
    # blocks (.., block_q, 1, d) do not (the head axis block of 1).
    qp = _pad_to(q.transpose(0, 2, 1, 3), 2, block_q)   # [B, H, Sq', d]
    kp = _pad_to(k.transpose(0, 2, 1, 3), 2, block_k)   # [B, Kv, Sk', d]
    vp = _pad_to(v.transpose(0, 2, 1, 3), 2, block_k)
    n_q_blocks = qp.shape[2] // block_q
    n_k_blocks = kp.shape[2] // block_k

    grid = (b, n_heads, n_q_blocks, n_k_blocks)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, h, qi, ki: (bi, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, h, qi, ki: (bi, h // n_rep, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, h, qi, ki: (bi, h // n_rep, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, h, qi, ki: (bi, h, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(
        seq_lens.astype(jnp.int32),
        q_offset.astype(jnp.int32),
        qp, kp, vp,
    )
    return out[:, :, :s_q].transpose(0, 2, 1, 3)


def flash_attention_sharded(
    q: jnp.ndarray,          # [B, S_q, n_heads, d]
    k: jnp.ndarray,          # [B, S_k, n_kv, d]
    v: jnp.ndarray,          # [B, S_k, n_kv, d]
    seq_lens: jnp.ndarray,   # [B]
    mesh,
    q_offset: jnp.ndarray | None = None,
    head_axis: str = "model",
    **kw,
) -> jnp.ndarray:
    """``flash_attention`` under tensor parallelism.

    ``pallas_call`` has no SPMD partitioning rule, so calling the kernel
    on TP-sharded activations would silently replicate full attention on
    every device (the reason engine.flash_prefill_safe conceded sharded
    prefill to XLA).  The fix is the standard shard_map pattern: heads are
    independent in attention, so each device runs the kernel on ITS head
    block — q/k/v enter head-sharded over ``head_axis`` (their natural
    layout under column-parallel wq/wk/wv, so no resharding happens at
    the boundary) and GQA grouping is preserved per shard.  Both head
    counts must divide the axis; batch stays unsharded (admission groups
    are small and need no data split).
    """
    n_tp = mesh.shape[head_axis]
    if q.shape[2] % n_tp or k.shape[2] % n_tp:
        raise ValueError(
            f"heads {q.shape[2]}/{k.shape[2]} not divisible by "
            f"{head_axis}={n_tp}")
    if q_offset is None:
        q_offset = jnp.zeros((q.shape[0],), jnp.int32)

    def local(q, k, v, lens, off):
        return flash_attention(q, k, v, lens, off, **kw)

    spec = jax.sharding.PartitionSpec(None, None, head_axis, None)
    vec = jax.sharding.PartitionSpec(None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec, vec, vec),
        out_specs=spec, check_vma=False,
    )(q, k, v, seq_lens, q_offset)
