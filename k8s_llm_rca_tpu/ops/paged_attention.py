"""Pallas TPU paged-attention kernel (decode path).

Decode attention where each sequence's KV lives in non-contiguous
fixed-size pages of a shared pool (vLLM-style block tables, re-designed
for the TPU: the page gather is expressed through a scalar-prefetched
BlockSpec index map, so Pallas's own pipelining DMAs exactly the pages
named by the block table — no host-side gather, no dense [B, S_max]
cache).

Layouts:
- ``k_pages``/``v_pages``: [n_pages, page_size, n_kv*d] — the kv-head and
  head-dim axes are stored MERGED on the lane axis.  TPU tiles the last
  two axes to (sublane, 128-lane) tiles; a per-head [..., page, d=64]
  layout would pad d 64 -> 128 and double both pool HBM and page DMA
  traffic.  With the merged axis the lane dim is n_kv*d (a multiple of
  128 for every real config) and pages are stored/streamed unpadded.
- ``block_tables``: [B, pages_per_seq] int32 page ids; entries past a
  sequence's length MUST still be valid ids (the allocator uses 0) —
  they are fetched but masked out of the softmax.
- ``lengths``: [B] valid kv tokens per sequence (including the current
  decode position).

Because a page block now carries ALL kv heads side by side on lanes, the
kernel processes every query head in one grid step using a
block-diagonal-q trick: queries are pre-expanded to [n_heads, n_kv*d]
with each row zero everywhere except its own kv-head's d-slice, so the
single [n_heads, n_kv*d] x [page, n_kv*d]^T matmul contracts over the
merged axis and the zeros kill every cross-head term.  The p @ v matmul
produces [n_heads, n_kv*d] whose valid output lives on the row's own
d-slice; the caller extracts that block diagonal with one cheap gather.
This trades a constant-factor of extra MXU work (the zero blocks) for
halved DMA on an op that is bandwidth-bound — the right trade on TPU.

Grid is (batch, page); the page axis is innermost and carries running
max / denominator / accumulator scratch across the sweep (online
softmax, same scheme as ops/flash_attention.py).

The reference has no KV cache at all (server-side, reference
common/openai_generic_assistant.py:45-51); SURVEY §2.2 names the paged
KV cache + kernel as a required TPU-native component.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _flash_init(acc_ref, m_ref, l_ref):
    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)


def _flash_accumulate(s, v, acc_ref, m_ref, l_ref, p_scale=None):
    """One online-softmax accumulation over this page's scores ``s``
    [n_heads, page] and values ``v`` [page, KV] (shared by the bf16 and
    quantized kernels).  ``p_scale`` [page]: optional per-token value
    scale folded into the softmax weights (quantized pools)."""
    m_prev = m_ref[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - shift)
    correction = jnp.exp(m_prev - shift)

    l_ref[:, 0:1] = l_ref[:, 0:1] * correction + jnp.sum(
        p, axis=-1, keepdims=True)
    pv = p if p_scale is None else p * p_scale[None, :]
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        pv, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                  # [n_heads, KV]
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)


def _flash_finalize(o_ref, acc_ref, l_ref):
    l = l_ref[:, 0:1]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _paged_kernel(
    lengths_ref,        # SMEM [B]
    tables_ref,         # SMEM [B, pages_per_seq]  (index-map only)
    q_ref,              # VMEM [1, n_heads, KV]  (block-diagonal expanded)
    k_ref,              # VMEM [1, page_size, KV]
    v_ref,              # VMEM [1, page_size, KV]
    o_ref,              # VMEM [1, n_heads, KV]
    acc_ref,            # VMEM scratch [n_heads, KV] f32
    m_ref,              # VMEM scratch [n_heads, _LANES] f32
    l_ref,              # VMEM scratch [n_heads, _LANES] f32
    *,
    page_size: int,
    head_dim: int,
):
    del tables_ref
    bi = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        _flash_init(acc_ref, m_ref, l_ref)

    length = lengths_ref[bi]

    @pl.when(j * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [n_heads, KV]
        k = k_ref[0].astype(jnp.float32)               # [page, KV]
        v = v_ref[0].astype(jnp.float32)               # [page, KV]
        n_heads = q.shape[0]

        # rows of q are zero outside their own kv-head's d-slice, so
        # contracting over the merged axis equals the per-head q.k dot
        scale = jax.lax.rsqrt(jnp.float32(head_dim))
        s = jax.lax.dot_general(
            q * scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [n_heads, page]

        k_pos = (jax.lax.broadcasted_iota(jnp.int32, (n_heads, page_size), 1)
                 + j * page_size)
        s = jnp.where(k_pos < length, s, NEG_INF)
        _flash_accumulate(s, v, acc_ref, m_ref, l_ref)

    @pl.when(j == n_pages - 1)
    def _finalize():
        _flash_finalize(o_ref, acc_ref, l_ref)


def _paged_kernel_quant(
    lengths_ref,        # SMEM [B]
    tables_ref,         # SMEM [B, pages_per_seq]
    q_ref,              # VMEM [1, n_heads, KV]  (block-diagonal expanded)
    k_ref,              # VMEM [1, page_size, KV'] int8 (KV' = KV or KV/2)
    v_ref,              # VMEM [1, page_size, KV'] int8
    ks_ref,             # VMEM [8, page_size]  scale rows around this page
    vs_ref,             # VMEM [8, page_size]
    o_ref,              # VMEM [1, n_heads, KV]
    acc_ref,            # VMEM scratch [n_heads, KV] f32
    m_ref,              # VMEM scratch [n_heads, _LANES] f32
    l_ref,              # VMEM scratch [n_heads, _LANES] f32
    *,
    page_size: int,
    head_dim: int,
    packed: bool,
):
    """Quantized-pool variant of ``_paged_kernel``: pages are int8 (or
    split-half nibble-packed int4) with one scale per token.  The scales
    never touch the [page, KV] operands — the k scale multiplies the
    [n_heads, page] score columns and the v scale folds into the softmax
    weights, so dequantization costs two small row broadcasts.  Scale rows
    arrive as (8, page_size) blocks (a (1, page_size) block would violate
    the sublane tiling rule); the row select is a one-hot contraction."""
    bi = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        _flash_init(acc_ref, m_ref, l_ref)

    length = lengths_ref[bi]

    @pl.when(j * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [n_heads, KV]
        n_heads = q.shape[0]

        def unpack(ref):
            raw = ref[0].astype(jnp.int32)             # [page, KV']
            if not packed:
                return raw.astype(jnp.float32)
            lo = ((raw << 28) >> 28).astype(jnp.float32)   # sign-extended
            hi = (raw >> 4).astype(jnp.float32)
            return jnp.concatenate([lo, hi], axis=-1)  # [page, KV]

        k = unpack(k_ref)
        v = unpack(v_ref)

        # select this page's scale row from the (8, page_size) block.
        # where-then-sum, NOT multiply-by-onehot: rows past the pool's end
        # are uninitialized block padding that may hold inf/NaN, and
        # NaN * 0 would poison the sum
        row = tables_ref[bi, j] % 8
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0) == row)
        ks = jnp.sum(jnp.where(onehot, ks_ref[:, :], 0.0), axis=0)
        vs = jnp.sum(jnp.where(onehot, vs_ref[:, :], 0.0), axis=0)

        scale = jax.lax.rsqrt(jnp.float32(head_dim))
        s = jax.lax.dot_general(
            q * scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * ks[None, :]                                # [n_heads, page]

        k_pos = (jax.lax.broadcasted_iota(jnp.int32, (n_heads, page_size), 1)
                 + j * page_size)
        s = jnp.where(k_pos < length, s, NEG_INF)
        _flash_accumulate(s, v, acc_ref, m_ref, l_ref, p_scale=vs)

    @pl.when(j == n_pages - 1)
    def _finalize():
        _flash_finalize(o_ref, acc_ref, l_ref)


def _expand_block_diag(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """[B, n_heads, d] -> [B, n_heads, n_kv*d] with row i nonzero only on
    kv-head (i // n_rep)'s d-slice."""
    b, n_heads, d = q.shape
    n_rep = n_heads // n_kv
    head_kv = jnp.arange(n_heads) // n_rep                     # [n_heads]
    onehot = jax.nn.one_hot(head_kv, n_kv, dtype=q.dtype)      # [n_heads, n_kv]
    return (q[:, :, None, :] * onehot[None, :, :, None]).reshape(
        b, n_heads, n_kv * d)


def _extract_block_diag(out: jnp.ndarray, n_kv: int, d: int) -> jnp.ndarray:
    """[B, n_heads, n_kv*d] -> [B, n_heads, d], keeping each row's own
    kv-head d-slice."""
    b, n_heads, _ = out.shape
    n_rep = n_heads // n_kv
    head_kv = jnp.arange(n_heads) // n_rep
    out = out.reshape(b, n_heads, n_kv, d)
    return out[:, jnp.arange(n_heads), head_kv]


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention(
    q: jnp.ndarray,             # [B, n_heads, d]
    k_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d]
    v_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d]
    lengths: jnp.ndarray,       # [B] int32
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    *,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Single-step decode attention over a paged KV pool: [B, n_heads, d]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, n_heads, d = q.shape
    _, page_size, kv_dim = k_pages.shape
    assert kv_dim % d == 0, (kv_dim, d)
    n_kv = kv_dim // d
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    pages_per_seq = block_tables.shape[1]

    q_exp = _expand_block_diag(q, n_kv)
    grid = (b, pages_per_seq)

    out = pl.pallas_call(
        functools.partial(_paged_kernel, page_size=page_size, head_dim=d),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, n_heads, kv_dim),
                             lambda bi, j, lens, tabs: (bi, 0, 0)),
                pl.BlockSpec((1, page_size, kv_dim),
                             lambda bi, j, lens, tabs: (tabs[bi, j], 0, 0)),
                pl.BlockSpec((1, page_size, kv_dim),
                             lambda bi, j, lens, tabs: (tabs[bi, j], 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, n_heads, kv_dim),
                                   lambda bi, j, lens, tabs: (bi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_heads, kv_dim), jnp.float32),
                pltpu.VMEM((n_heads, _LANES), jnp.float32),
                pltpu.VMEM((n_heads, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_heads, kv_dim), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        q_exp, k_pages, v_pages,
    )
    return _extract_block_diag(out, n_kv, d)


@functools.partial(jax.jit, static_argnames=("packed", "interpret"))
def paged_attention_quant(
    q: jnp.ndarray,             # [B, n_heads, d]
    k_pages: jnp.ndarray,       # [n_pages, page_size, KV'] int8
    v_pages: jnp.ndarray,       # [n_pages, page_size, KV'] int8
    k_scales: jnp.ndarray,      # [n_pages, page_size]
    v_scales: jnp.ndarray,      # [n_pages, page_size]
    lengths: jnp.ndarray,       # [B] int32
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    *,
    packed: bool = False,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention over a QUANTIZED paged pool (int8, or split-half
    nibble-packed int4 when ``packed``): [B, n_heads, d]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, n_heads, d = q.shape
    _, page_size, kv_store = k_pages.shape
    kv_dim = kv_store * 2 if packed else kv_store
    assert kv_dim % d == 0, (kv_dim, d)
    n_kv = kv_dim // d
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    pages_per_seq = block_tables.shape[1]

    q_exp = _expand_block_diag(q, n_kv)
    grid = (b, pages_per_seq)

    out = pl.pallas_call(
        functools.partial(_paged_kernel_quant, page_size=page_size,
                          head_dim=d, packed=packed),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, n_heads, kv_dim),
                             lambda bi, j, lens, tabs: (bi, 0, 0)),
                pl.BlockSpec((1, page_size, kv_store),
                             lambda bi, j, lens, tabs: (tabs[bi, j], 0, 0)),
                pl.BlockSpec((1, page_size, kv_store),
                             lambda bi, j, lens, tabs: (tabs[bi, j], 0, 0)),
                # scale rows: (8, page) blocks — a (1, page) block would
                # break the sublane tiling rule; the kernel one-hot-selects
                # row tabs[bi, j] % 8
                pl.BlockSpec((8, page_size),
                             lambda bi, j, lens, tabs: (tabs[bi, j] // 8, 0)),
                pl.BlockSpec((8, page_size),
                             lambda bi, j, lens, tabs: (tabs[bi, j] // 8, 0)),
            ],
            out_specs=pl.BlockSpec((1, n_heads, kv_dim),
                                   lambda bi, j, lens, tabs: (bi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_heads, kv_dim), jnp.float32),
                pltpu.VMEM((n_heads, _LANES), jnp.float32),
                pltpu.VMEM((n_heads, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_heads, kv_dim), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        q_exp, k_pages, v_pages,
        # scales enter as f32 regardless of the pool's compute dtype: the
        # (8, page_size) scale BlockSpec is validated on-chip for f32
        # sublane tiling, and the cast is O(n_pages * page_size) — noise
        k_scales.astype(jnp.float32), v_scales.astype(jnp.float32),
    )
    return _extract_block_diag(out, n_kv, d)


def _validate_head_shard(n_heads: int, n_kv: int, n_tp: int) -> None:
    if n_heads % n_tp or n_kv % n_tp:
        raise ValueError(
            f"paged attention under TP needs n_heads={n_heads} and "
            f"n_kv={n_kv} divisible by the head axis size {n_tp} "
            f"(GQA groups must stay whole per shard)")


def paged_attention_sharded(
    q: jnp.ndarray,             # [B, n_heads, d]
    k_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d]
    v_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d]
    lengths: jnp.ndarray,       # [B] int32
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    mesh,
    head_axis: str = "model",
    **kw,
) -> jnp.ndarray:
    """``paged_attention`` under tensor parallelism.

    ``pallas_call`` has no SPMD partitioning rule, so calling the kernel
    on a TP-sharded pool would silently replicate full attention on every
    device (the reason the paged engine used to concede sharded decode to
    the XLA gather).  Same fix as ops.flash_attention_sharded: heads are
    independent, so each device runs the kernel over ITS kv-head shard —
    q enters head-sharded over ``head_axis`` and the pool enters sharded
    on its merged kv lane axis (their natural layouts under
    column-parallel wq/wk/wv and the engine's
    ``P(None, None, None, "model")`` pool placement, so no resharding at
    the boundary).  GQA grouping is preserved per shard: both head counts
    must divide the axis.  Batch stays unsharded, matching the decode
    activations (replicated across the TP group).
    """
    _validate_head_shard(q.shape[1], k_pages.shape[-1] // q.shape[-1],
                         mesh.shape[head_axis])

    def local(q, kp, vp, lens, bt):
        return paged_attention(q, kp, vp, lens, bt, **kw)

    q_spec = jax.sharding.PartitionSpec(None, head_axis, None)
    pool_spec = jax.sharding.PartitionSpec(None, None, head_axis)
    vec = jax.sharding.PartitionSpec(None)
    bt_spec = jax.sharding.PartitionSpec(None, None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(q_spec, pool_spec, pool_spec, vec, bt_spec),
        out_specs=q_spec, check_vma=False,
    )(q, k_pages, v_pages, lengths, block_tables)


def paged_attention_quant_sharded(
    q: jnp.ndarray,             # [B, n_heads, d]
    k_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d] int8
    v_pages: jnp.ndarray,       # [n_pages, page_size, n_kv*d] int8
    k_scales: jnp.ndarray,      # [n_pages, page_size]
    v_scales: jnp.ndarray,      # [n_pages, page_size]
    lengths: jnp.ndarray,       # [B] int32
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    mesh,
    head_axis: str = "model",
    **kw,
) -> jnp.ndarray:
    """``paged_attention_quant`` under tensor parallelism (int8 pools).

    The per-token scale is a FULL-ROW scalar (one per written token,
    recovered by pmax over the TP group at write time), so the scale
    pools replicate across ``head_axis`` and each shard's dequant
    ``int8 * scale`` is exact — per-shard attention then matches the
    global computation bit-for-bit up to the reduction order.

    Split-half nibble-packed int4 pools are NOT supported here: packing
    pairs lane i with lane i + kv_dim/2, so a contiguous shard of the
    PACKED lane axis unpacks to two non-contiguous head ranges — the
    shard-local unpack would attend the wrong heads.  The engine keeps
    int4 pools on the XLA gather path under TP (engine/paged.py gating).
    """
    if kw.pop("packed", False):
        raise ValueError(
            "paged_attention_quant_sharded does not support packed int4 "
            "pools (split-half packing does not commute with the head "
            "shard); use the XLA path")
    _validate_head_shard(q.shape[1], k_pages.shape[-1] // q.shape[-1],
                         mesh.shape[head_axis])

    def local(q, kp, vp, ks, vs, lens, bt):
        return paged_attention_quant(q, kp, vp, ks, vs, lens, bt,
                                     packed=False, **kw)

    q_spec = jax.sharding.PartitionSpec(None, head_axis, None)
    pool_spec = jax.sharding.PartitionSpec(None, None, head_axis)
    scale_spec = jax.sharding.PartitionSpec(None, None)
    vec = jax.sharding.PartitionSpec(None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(q_spec, pool_spec, pool_spec, scale_spec, scale_spec,
                  vec, scale_spec),
        out_specs=q_spec, check_vma=False,
    )(q, k_pages, v_pages, k_scales, v_scales, lengths, block_tables)


def paged_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    block_tables: jnp.ndarray,
) -> jnp.ndarray:
    """Pure-XLA reference implementation (gather + masked softmax).

    Ground truth for the kernel's unit tests and the fallback for
    platforms without Mosaic.
    """
    b, n_heads, d = q.shape
    _, page_size, kv_dim = k_pages.shape
    assert kv_dim % d == 0, (kv_dim, d)
    n_kv = kv_dim // d
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    n_rep = n_heads // n_kv

    # [B, pp, page, KV] -> [B, S_max, n_kv, d]
    k = jnp.take(k_pages, block_tables, axis=0)
    v = jnp.take(v_pages, block_tables, axis=0)
    k = k.reshape(b, -1, n_kv, d)
    v = v.reshape(b, -1, n_kv, d)

    k = jnp.repeat(k, n_rep, axis=2).astype(jnp.float32)
    v = jnp.repeat(v, n_rep, axis=2).astype(jnp.float32)
    qf = q.astype(jnp.float32) / jnp.sqrt(jnp.float32(d))

    s = jnp.einsum("bhd,bkhd->bhk", qf, k)
    k_pos = jnp.arange(k.shape[1])[None, None, :]
    s = jnp.where(k_pos < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v)
    return out.astype(q.dtype)
