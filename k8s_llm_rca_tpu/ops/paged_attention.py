"""Pallas TPU paged-attention kernel (decode path).

Decode attention where each sequence's KV lives in non-contiguous
fixed-size pages of a shared pool (vLLM-style block tables, re-designed
for the TPU: the page gather is expressed through a scalar-prefetched
BlockSpec index map, so Pallas's own pipelining DMAs exactly the pages
named by the block table — no host-side gather, no dense [B, S_max]
cache).

Layouts:
- ``k_pages``/``v_pages``: [n_kv_heads, n_pages, page_size, head_dim] —
  head-major so one (head, page) block is contiguous in HBM.
- ``block_tables``: [B, pages_per_seq] int32 page ids; entries past a
  sequence's length MUST still be valid ids (the allocator uses 0) —
  they are fetched but masked out of the softmax.
- ``lengths``: [B] valid kv tokens per sequence (including the current
  decode position).

Grid is (batch, kv_head, page); the page axis is innermost and carries
running max / denominator / accumulator scratch across the sweep
(online softmax, same scheme as ops/flash_attention.py).  All n_rep
GQA query heads for one kv head are processed together as the rows of
an [n_rep, d] tile.

The reference has no KV cache at all (server-side, reference
common/openai_generic_assistant.py:45-51); SURVEY §2.2 names the paged
KV cache + kernel as a required TPU-native component.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _paged_kernel(
    lengths_ref,        # SMEM [B]
    tables_ref,         # SMEM [B, pages_per_seq]  (index-map only)
    q_ref,              # VMEM [1, 1, n_rep, d]
    k_ref,              # VMEM [1, 1, page_size, d]
    v_ref,              # VMEM [1, 1, page_size, d]
    o_ref,              # VMEM [1, 1, n_rep, d]
    acc_ref,            # VMEM scratch [n_rep, d] f32
    m_ref,              # VMEM scratch [n_rep, _LANES] f32
    l_ref,              # VMEM scratch [n_rep, _LANES] f32
    *,
    page_size: int,
):
    del tables_ref
    bi = pl.program_id(0)
    j = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = lengths_ref[bi]

    @pl.when(j * page_size < length)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [n_rep, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [page, d]
        v = v_ref[0, 0].astype(jnp.float32)            # [page, d]
        n_rep = q.shape[0]

        scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
        s = jax.lax.dot_general(
            q * scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [n_rep, page]

        k_pos = (jax.lax.broadcasted_iota(jnp.int32, (n_rep, page_size), 1)
                 + j * page_size)
        s = jnp.where(k_pos < length, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift)
        correction = jnp.exp(m_prev - shift)

        l_ref[:, 0:1] = l_ref[:, 0:1] * correction + jnp.sum(
            p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == n_pages - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention(
    q: jnp.ndarray,             # [B, n_heads, d]
    k_pages: jnp.ndarray,       # [n_kv, n_pages, page_size, d]
    v_pages: jnp.ndarray,       # [n_kv, n_pages, page_size, d]
    lengths: jnp.ndarray,       # [B] int32
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    *,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Single-step decode attention over a paged KV pool: [B, n_heads, d]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, n_heads, d = q.shape
    n_kv, _, page_size, _ = k_pages.shape
    n_rep = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]

    q4 = q.reshape(b, n_kv, n_rep, d)
    grid = (b, n_kv, pages_per_seq)

    out = pl.pallas_call(
        functools.partial(_paged_kernel, page_size=page_size),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, n_rep, d),
                             lambda bi, h, j, lens, tabs: (bi, h, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, h, j, lens, tabs:
                             (h, tabs[bi, j], 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, h, j, lens, tabs:
                             (h, tabs[bi, j], 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, n_rep, d),
                                   lambda bi, h, j, lens, tabs:
                                   (bi, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((n_rep, d), jnp.float32),
                pltpu.VMEM((n_rep, _LANES), jnp.float32),
                pltpu.VMEM((n_rep, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, n_rep, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        block_tables.astype(jnp.int32),
        q4, k_pages, v_pages,
    )
    return out.reshape(b, n_heads, d)


def paged_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    block_tables: jnp.ndarray,
) -> jnp.ndarray:
    """Pure-XLA reference implementation (gather + masked softmax).

    Ground truth for the kernel's unit tests and the fallback for
    platforms without Mosaic.
    """
    b, n_heads, d = q.shape
    n_kv, _, page_size, _ = k_pages.shape
    n_rep = n_heads // n_kv

    # [B, n_kv, pages_per_seq, page, d] -> [B, S_max, n_kv, d]
    k = jnp.take(k_pages, block_tables, axis=1)        # [n_kv, B, pp, page, d]
    v = jnp.take(v_pages, block_tables, axis=1)
    k = k.transpose(1, 2, 3, 0, 4).reshape(b, -1, n_kv, d)
    v = v.transpose(1, 2, 3, 0, 4).reshape(b, -1, n_kv, d)

    k = jnp.repeat(k, n_rep, axis=2).astype(jnp.float32)
    v = jnp.repeat(v, n_rep, axis=2).astype(jnp.float32)
    qf = q.astype(jnp.float32) / jnp.sqrt(jnp.float32(d))

    s = jnp.einsum("bhd,bkhd->bhk", qf, k)
    k_pos = jnp.arange(k.shape[1])[None, None, :]
    s = jnp.where(k_pos < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v)
    return out.astype(q.dtype)
