"""Rotary position embeddings (rotate-half / NeoX convention, as used by the
Llama & Mixtral families)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float) -> jnp.ndarray:
    """[max_seq_len, head_dim//2] complex-free angle table (fp32)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    return jnp.outer(t, inv_freq)  # [S, D/2]


def apply_rope(x: jnp.ndarray, angles: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [B, S, n_heads, head_dim] by per-token angles.

    ``positions`` is [B, S] absolute token positions (continuous batching means
    each slot sits at its own offset, so positions are data, not an iota).
    """
    ang = angles[positions]                      # [B, S, D/2]
    cos = jnp.cos(ang)[:, :, None, :]            # [B, S, 1, D/2]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)
