from k8s_llm_rca_tpu.ops.norms import rms_norm, layer_norm  # noqa: F401
from k8s_llm_rca_tpu.ops.rope import rope_frequencies, apply_rope  # noqa: F401
from k8s_llm_rca_tpu.ops.attention import (  # noqa: F401
    causal_attention,
    decode_attention,
    repeat_kv,
)
from k8s_llm_rca_tpu.ops.quant_matmul import (  # noqa: F401
    qmm,
    qmm_experts,
    qmm_head,
    quant_matmul,
    quant_matmul_experts,
    quant_matmul_head,
)
