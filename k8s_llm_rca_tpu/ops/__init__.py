from k8s_llm_rca_tpu.ops.norms import rms_norm, layer_norm  # noqa: F401
from k8s_llm_rca_tpu.ops.rope import rope_frequencies, apply_rope  # noqa: F401
from k8s_llm_rca_tpu.ops.attention import (  # noqa: F401
    causal_attention,
    decode_attention,
    repeat_kv,
)
