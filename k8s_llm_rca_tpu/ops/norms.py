"""Normalization ops.  Computed in fp32 regardless of activation dtype (the
standard TPU recipe: VPU elementwise in fp32, MXU matmuls in bf16)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-12) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(dtype)
