"""Attention ops (XLA path).

These are the reference implementations every kernel must match: pure
jnp/lax, static shapes, fused by XLA onto MXU/VPU.  The Pallas flash /
paged-attention kernels (ops/flash_attention.py, ops/paged_attention.py)
are drop-in replacements validated against these in tests.

Two entry points because inference has two phases:
- ``causal_attention``  — prefill: [B, S] queries attend causally to [B, S].
- ``decode_attention``  — decode: [B, 1] queries attend to a KV cache of
  [B, S_max] with per-slot valid lengths (continuous batching: every slot
  sits at a different position).
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(kv: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, n_kv, d] -> [B, S, n_kv*n_rep, d] (GQA head expansion)."""
    if n_rep == 1:
        return kv
    b, s, h, d = kv.shape
    return jnp.broadcast_to(kv[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def causal_attention(
    q: jnp.ndarray,          # [B, S, n_heads, d]
    k: jnp.ndarray,          # [B, S, n_kv, d]
    v: jnp.ndarray,          # [B, S, n_kv, d]
    seq_lens: jnp.ndarray,   # [B] valid lengths (right-padded inputs)
    q_offset: jnp.ndarray | None = None,  # [B] absolute pos of q[...,0,...]
) -> jnp.ndarray:
    """Causal softmax attention for prefill.  Returns [B, S, n_heads, d].

    ``q_offset`` supports chunked prefill: queries at absolute positions
    offset+i attend to cached keys 0..offset+i (keys here are the chunk only
    when offset==0 covers the plain case).
    """
    b, s, n_heads, d = q.shape
    s_k = k.shape[1]          # == s for plain prefill; cache width if chunked
    n_rep = n_heads // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B, H, S, S_k]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale

    q_pos = jnp.arange(s)[None, :]                       # [1, S]
    if q_offset is not None:
        q_pos = q_pos + q_offset[:, None]                # [B, S]
    k_pos = jnp.arange(s_k)[None, :]                     # [1, S_k]
    causal = q_pos[:, :, None] >= k_pos[:, None, :]      # [B, S, S_k]
    valid = k_pos[:, None, :] < seq_lens[:, None, None]  # [B, 1->S, S_k]
    mask = (causal & valid)[:, None, :, :]               # [B, 1, S, S_k]

    logits = jnp.where(mask, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,          # [B, 1, n_heads, d]
    k_cache: jnp.ndarray,    # [B, S_max, n_kv, d]
    v_cache: jnp.ndarray,    # [B, S_max, n_kv, d]
    lengths: jnp.ndarray,    # [B] tokens valid in cache (incl. current)
) -> jnp.ndarray:
    """Single-step decode attention over the slot cache.  [B, 1, n_heads, d].

    The T=1 case of ``decode_attention_multi`` (delegated so the two paths
    cannot drift numerically)."""
    return decode_attention_multi(q, k_cache, v_cache, lengths)


def decode_attention_multi(
    q: jnp.ndarray,          # [B, T, n_heads, d] queries at pos lengths-1+i
    k_cache: jnp.ndarray,    # [B, S_max, n_kv, d]
    v_cache: jnp.ndarray,    # [B, S_max, n_kv, d]
    lengths: jnp.ndarray,    # [B] tokens valid incl. the FIRST query token
) -> jnp.ndarray:
    """Multi-token decode attention (speculative verification): query i of
    slot b attends to cache positions < lengths[b] + i.  [B, T, n_heads, d].
    """
    b, s_max, n_kv, d = k_cache.shape
    t, n_heads = q.shape[1], q.shape[2]
    n_rep = n_heads // n_kv
    k = repeat_kv(k_cache, n_rep)
    v = repeat_kv(v_cache, n_rep)

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale    # [B, H, T, S_max]
    k_pos = jnp.arange(s_max)[None, None, :]              # [1, 1, S]
    limit = lengths[:, None, None] + jnp.arange(t)[None, :, None]  # [B, T, 1]
    mask = (k_pos < limit)[:, None]                       # [B, 1, T, S]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
