"""Fused weight-dequant matmul kernels (Pallas TPU) + the ``qmm`` shim.

Decode on the quantized engines is weight-streaming bound, but the
``x @ dq(w)`` use sites re-materialize dense bf16 weights whenever XLA
fails to fuse ``_unpack_nibbles``'s lane-axis concat into the matmul
operand read — paying ~4x the int4 bytes the quantization bought
(ROADMAP item 1, the 4.8%-MFU gap).  These kernels stream the PACKED
int8/int4 weight tiles HBM->VMEM and dequantize in-register inside the
K-loop, with the per-channel scale folded into the accumulator epilogue.

Layouts (all three scale layouts quantize_params emits):

  kn  (wq/wk/wv/wo, MLP gate/up/down, MoE router)
      q [K, N] int8          scale [1, N]    y = x @ (q * s)
      int4: q [K, N/2] packed split-half — byte j holds column j in its
      low nibble and column j + N/2 in its high nibble, so the kernel's
      unpack is two shifts and the lo/hi products write the [M, 2, N/2]
      output halves directly (the layout was designed for exactly this:
      quant._pack_nibbles).

  nk  (lm head / tied embedding, per-ROW scales)
      q [V, K] int8          scale [V, 1]    y = x @ (q * s)^T
      int4: q [V, K/2] packed along K — x splits into (x_lo, x_hi)
      halves and the row product is x_lo @ lo^T + x_hi @ hi^T.

  ekn (stacked experts, per-(expert, column) scales)
      q [E, K, N]            scale [E, 1, N]
      the kn kernel with a leading expert grid dimension; serves both
      stacked einsums ("bsh,ehi->bsei" with x broadcast across experts,
      "bsei,eih->bseh" with per-expert x).

Every kernel accumulates in an f32 VMEM scratch across the K grid
(``dimension_semantics`` marks K "arbitrary") and applies the scale once
at the last K step: mathematically identical to scaling the weights
first (the scale is constant over K), numerically within bf16/f32
accumulation tolerance of the dq() reference — what
tests/test_quant_matmul.py pins for every (bits x layout x shape) cell.

Capability gating: this host has no Pallas-on-TPU lowering, so the
``qmm*`` shims take the kernel path only on a real TPU backend and fall
back to the byte-identical ``dq()`` XLA expressions everywhere else —
CPU engines with ``ModelConfig.fused_quant_matmul=True`` stay greedy
byte-identical by construction, and GSPMD-sharded consumption (which
pallas_call cannot partition) also lands on the fallback.  Shard-LOCAL
consumption inside shard_map stage bodies (PP×TP, weights repacked by
quant.repack_nibbles_grouped and unwrapped at the boundary) runs the
kernel on its self-contained split-half shard.  Grouped-repacked tensors
consumed GLOBALLY raise a loud ValueError (quant._reject_grouped).
Kernels themselves are validated in interpret mode on CPU, the
tests/test_kernels.py pattern.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# models/quant.py is imported LAZILY (inside _q()): models/__init__ pulls
# in llama.py which imports this module's shims, so a module-level import
# here would close an import cycle through the two package __init__s.
# ops/ stays models-free at import time, like every other ops module.


def _q():
    from k8s_llm_rca_tpu.models import quant
    return quant


# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# interpret-mode tests run on every jax this framework targets
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams", None)

# block-size targets: K tiles deep (weight streaming amortizes the
# revisit of x), M/N moderate so the f32 scratch stays small.  _blk
# clamps each to the largest divisor of the actual dim, so tiny test
# shapes run single-block while 8B shapes tile properly.
_BM, _BN, _BK = 256, 256, 512


def _interp(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _blk(dim: int, target: int) -> int:
    b = min(dim, target)
    while dim % b:
        b -= 1
    return b


def _params(sem):
    if _CompilerParams is None:
        return {}
    return {"compiler_params": _CompilerParams(dimension_semantics=sem)}


def _lo_nibbles(p):
    # (p << 4) >> 4 sign-extends the low nibble without a select — the
    # arithmetic-shift twin of quant._unpack_nibbles's where()
    return jnp.right_shift(jnp.left_shift(p, 4), 4)


def _hi_nibbles(p):
    return jnp.right_shift(p, 4)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _kn8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    acc_ref[...] += jnp.dot(x, q_ref[...].astype(x.dtype),
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[...] = (acc_ref[...]
                      * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _kn4_kernel(x_ref, q_ref, s_ref, o_ref, lo_ref, hi_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        lo_ref[...] = jnp.zeros_like(lo_ref)
        hi_ref[...] = jnp.zeros_like(hi_ref)

    x = x_ref[...]
    p = q_ref[...]
    lo_ref[...] += jnp.dot(x, _lo_nibbles(p).astype(x.dtype),
                           preferred_element_type=jnp.float32)
    hi_ref[...] += jnp.dot(x, _hi_nibbles(p).astype(x.dtype),
                           preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        s = s_ref[...].astype(jnp.float32)            # [2, bnp]
        o_ref[:, 0, :] = (lo_ref[...] * s[0:1]).astype(o_ref.dtype)
        o_ref[:, 1, :] = (hi_ref[...] * s[1:2]).astype(o_ref.dtype)


def _nk8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    acc_ref[...] += jax.lax.dot_general(
        x, q_ref[...].astype(x.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[...] = (acc_ref[...]
                      * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _nk4_kernel(xlo_ref, xhi_ref, q_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p = q_ref[...]
    dims = (((1,), (1,)), ((), ()))
    xlo = xlo_ref[...]
    acc_ref[...] += jax.lax.dot_general(
        xlo, _lo_nibbles(p).astype(xlo.dtype), dims,
        preferred_element_type=jnp.float32)
    xhi = xhi_ref[...]
    acc_ref[...] += jax.lax.dot_general(
        xhi, _hi_nibbles(p).astype(xhi.dtype), dims,
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[...] = (acc_ref[...]
                      * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ekn8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(3)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[0]
    acc_ref[...] += jnp.dot(x, q_ref[0].astype(x.dtype),
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[0] = (acc_ref[...]
                    * s_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def _ekn4_kernel(x_ref, q_ref, s_ref, o_ref, lo_ref, hi_ref, *, nk):
    k = pl.program_id(3)

    @pl.when(k == 0)
    def _():
        lo_ref[...] = jnp.zeros_like(lo_ref)
        hi_ref[...] = jnp.zeros_like(hi_ref)

    x = x_ref[0]
    p = q_ref[0]
    lo_ref[...] += jnp.dot(x, _lo_nibbles(p).astype(x.dtype),
                           preferred_element_type=jnp.float32)
    hi_ref[...] += jnp.dot(x, _hi_nibbles(p).astype(x.dtype),
                           preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        s = s_ref[0].astype(jnp.float32)              # [2, bnp]
        o_ref[0, :, 0, :] = (lo_ref[...] * s[0:1]).astype(o_ref.dtype)
        o_ref[0, :, 1, :] = (hi_ref[...] * s[1:2]).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers (one per storage layout)
# ---------------------------------------------------------------------------


def _matmul_kn(x2, w, interpret: bool):
    m, kdim = x2.shape
    bm, bk = _blk(m, _BM), _blk(kdim, _BK)
    if isinstance(w, _q().QuantTensor):
        n = w.q.shape[1]
        bn = _blk(n, _BN)
        grid = (m // bm, n // bn, kdim // bk)
        return pl.pallas_call(
            functools.partial(_kn8_kernel, nk=grid[2]),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
                pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
                pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
            out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            interpret=interpret,
            **_params(("parallel", "parallel", "arbitrary")),
        )(x2, w.q, w.scale.reshape(1, n))
    n_packed = w.q.shape[1]                           # logical N / 2
    bnp = _blk(n_packed, _BN)
    grid = (m // bm, n_packed // bnp, kdim // bk)
    out = pl.pallas_call(
        functools.partial(_kn4_kernel, nk=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bk, bnp), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((2, bnp), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, 2, bnp),
                               lambda mi, ni, ki: (mi, 0, ni)),
        out_shape=jax.ShapeDtypeStruct((m, 2, n_packed), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bnp), jnp.float32),
                        pltpu.VMEM((bm, bnp), jnp.float32)],
        interpret=interpret,
        **_params(("parallel", "parallel", "arbitrary")),
    )(x2, w.q, w.scale.reshape(2, n_packed))
    # [M, 2, N/2] -> [M, N]: row-major flatten restores the split-half
    # column order (lo block = columns [0, N/2), hi = [N/2, N))
    return out.reshape(m, 2 * n_packed)


def _matmul_nk(x2, w, interpret: bool):
    m, kdim = x2.shape
    n = w.q.shape[0]
    bm, bn = _blk(m, _BM), _blk(n, _BN)
    scale = w.scale.reshape(1, n)
    if isinstance(w, _q().QuantTensor):
        bk = _blk(kdim, _BK)
        grid = (m // bm, n // bn, kdim // bk)
        return pl.pallas_call(
            functools.partial(_nk8_kernel, nk=grid[2]),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
                pl.BlockSpec((bn, bk), lambda mi, ni, ki: (ni, ki)),
                pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
            out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            interpret=interpret,
            **_params(("parallel", "parallel", "arbitrary")),
        )(x2, w.q, scale)
    k_packed = w.q.shape[1]                           # K / 2
    bkp = _blk(k_packed, _BK)
    grid = (m // bm, n // bn, k_packed // bkp)
    # the packed axis pairs (k, k + K/2): feed the x halves as separate
    # operands so each streams block-aligned with the packed tiles
    x_lo, x_hi = x2[:, :k_packed], x2[:, k_packed:]
    return pl.pallas_call(
        functools.partial(_nk4_kernel, nk=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bkp), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bm, bkp), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bn, bkp), lambda mi, ni, ki: (ni, ki)),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
        **_params(("parallel", "parallel", "arbitrary")),
    )(x_lo, x_hi, w.q, scale)


def _matmul_ekn(xe, w, interpret: bool):
    e, m, kdim = xe.shape
    bm, bk = _blk(m, _BM), _blk(kdim, _BK)
    if isinstance(w, _q().QuantTensor):
        n = w.q.shape[2]
        bn = _blk(n, _BN)
        grid = (e, m // bm, n // bn, kdim // bk)
        return pl.pallas_call(
            functools.partial(_ekn8_kernel, nk=grid[3]),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bm, bk),
                             lambda ei, mi, ni, ki: (ei, mi, ki)),
                pl.BlockSpec((1, bk, bn),
                             lambda ei, mi, ni, ki: (ei, ki, ni)),
                pl.BlockSpec((1, 1, bn),
                             lambda ei, mi, ni, ki: (ei, 0, ni)),
            ],
            out_specs=pl.BlockSpec((1, bm, bn),
                                   lambda ei, mi, ni, ki: (ei, mi, ni)),
            out_shape=jax.ShapeDtypeStruct((e, m, n), xe.dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            interpret=interpret,
            **_params(("parallel", "parallel", "parallel", "arbitrary")),
        )(xe, w.q, w.scale.reshape(e, 1, n))
    n_packed = w.q.shape[2]
    bnp = _blk(n_packed, _BN)
    grid = (e, m // bm, n_packed // bnp, kdim // bk)
    out = pl.pallas_call(
        functools.partial(_ekn4_kernel, nk=grid[3]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bm, bk),
                         lambda ei, mi, ni, ki: (ei, mi, ki)),
            pl.BlockSpec((1, bk, bnp),
                         lambda ei, mi, ni, ki: (ei, ki, ni)),
            pl.BlockSpec((1, 2, bnp),
                         lambda ei, mi, ni, ki: (ei, 0, ni)),
        ],
        out_specs=pl.BlockSpec((1, bm, 2, bnp),
                               lambda ei, mi, ni, ki: (ei, mi, 0, ni)),
        out_shape=jax.ShapeDtypeStruct((e, m, 2, n_packed), xe.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bnp), jnp.float32),
                        pltpu.VMEM((bm, bnp), jnp.float32)],
        interpret=interpret,
        **_params(("parallel", "parallel", "parallel", "arbitrary")),
    )(xe, w.q, w.scale.reshape(e, 2, n_packed))
    return out.reshape(e, m, 2 * n_packed)


# ---------------------------------------------------------------------------
# public kernel entry points (always take the kernel; tests drive these
# in interpret mode on CPU)
# ---------------------------------------------------------------------------


def _require_quant(w, who: str):
    quant = _q()
    quant._reject_grouped(w, f"{who} over")
    if not isinstance(w, (quant.QuantTensor, quant.QuantTensor4)):
        raise ValueError(
            f"{who} needs a QuantTensor/QuantTensor4 weight, got "
            f"{type(w).__name__} (plain arrays take the XLA matmul — "
            f"use the qmm shim for transparent dispatch)")


def quant_matmul(x: jnp.ndarray, w, *,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """``x @ dq(w)`` through the fused kn kernel.

    ``w``: 2-D QuantTensor/QuantTensor4 ``[K, N]`` with per-output-COLUMN
    scales (quantize axis=-1); ``x`` [..., K].  Per-row tables (lm head /
    embedding) go through ``quant_matmul_head``; stacked experts through
    ``quant_matmul_experts``.  ``interpret=None`` auto-selects interpret
    mode off-TPU (the ops/paged_attention.py convention)."""
    _require_quant(w, "quant_matmul")
    if w.ndim != 2:
        raise ValueError(
            f"quant_matmul takes 2-D weights, got {w.ndim}-D "
            f"{w.shape} (stacked experts: quant_matmul_experts)")
    kdim, n = w.shape
    if w.scale.shape != (1, n):
        raise ValueError(
            f"quant_matmul needs per-column scales [1, {n}], got "
            f"{w.scale.shape} for weight {w.shape} (per-row tables: "
            f"quant_matmul_head)")
    if x.shape[-1] != kdim:
        raise ValueError(f"shape mismatch: x {x.shape} @ w {w.shape}")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, kdim)
    out = _matmul_kn(x2, w, _interp(interpret))
    return out.reshape(*lead, n)


def quant_matmul_head(x: jnp.ndarray, w, *,
                      interpret: Optional[bool] = None) -> jnp.ndarray:
    """``einsum("...h,vh->...v", x, dq(w))`` through the fused nk kernel:
    ``w`` [V, K] with per-ROW scales [V, 1] (quantize axis=0 — the lm
    head / tied embedding layout).  int4 packs along K, so the kernel
    splits x into split-half K blocks instead of the output columns."""
    _require_quant(w, "quant_matmul_head")
    if w.ndim != 2:
        raise ValueError(
            f"quant_matmul_head takes 2-D tables, got {w.ndim}-D {w.shape}")
    v, kdim = w.shape
    if w.scale.shape != (v, 1):
        raise ValueError(
            f"quant_matmul_head needs per-row scales [{v}, 1], got "
            f"{w.scale.shape} for table {w.shape} (per-column weights: "
            f"quant_matmul)")
    if x.shape[-1] != kdim:
        raise ValueError(f"shape mismatch: x {x.shape} @ w^T {w.shape}")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, kdim)
    out = _matmul_nk(x2, w, _interp(interpret))
    return out.reshape(*lead, v)


def quant_matmul_experts(x: jnp.ndarray, w, *,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """The stacked-expert einsums through the fused ekn kernel.

    ``w`` [E, K, N] with per-(expert, column) scales [E, 1, N] (quantize
    axis=(0, -1)).  ``x`` 3-D [B, S, K] computes ``"bsh,ehi->bsei"``
    (every token through every expert — the dense soft-dispatch MoE);
    4-D [B, S, E, K] computes ``"bsei,eih->bseh"`` (per-expert rows)."""
    _require_quant(w, "quant_matmul_experts")
    if w.ndim != 3:
        raise ValueError(
            f"quant_matmul_experts takes stacked [E, K, N] weights, got "
            f"{w.ndim}-D {w.shape} (2-D weights: quant_matmul)")
    e, kdim, n = w.shape
    if w.scale.shape != (e, 1, n):
        raise ValueError(
            f"quant_matmul_experts needs per-(expert, column) scales "
            f"[{e}, 1, {n}], got {w.scale.shape} for weight {w.shape}")
    interpret = _interp(interpret)
    if x.ndim == 3:
        b, s, xk = x.shape
        if xk != kdim:
            raise ValueError(f"shape mismatch: x {x.shape} @ w {w.shape}")
        xe = jnp.broadcast_to(x.reshape(1, b * s, kdim), (e, b * s, kdim))
        out = _matmul_ekn(xe, w, interpret)           # [E, B*S, N]
        return out.reshape(e, b, s, n).transpose(1, 2, 0, 3)
    if x.ndim == 4:
        b, s, xe_, xk = x.shape
        if xe_ != e or xk != kdim:
            raise ValueError(f"shape mismatch: x {x.shape} @ w {w.shape}")
        xe = x.transpose(2, 0, 1, 3).reshape(e, b * s, kdim)
        out = _matmul_ekn(xe, w, interpret)           # [E, B*S, N]
        return out.reshape(e, b, s, n).transpose(1, 2, 0, 3)
    raise ValueError(
        f"quant_matmul_experts takes 3-D [B,S,K] or 4-D [B,S,E,K] "
        f"activations, got {x.shape}")


# ---------------------------------------------------------------------------
# dispatch shims — the ModelConfig.fused_quant_matmul use-site surface
# ---------------------------------------------------------------------------


def _kernel_path(w) -> bool:
    """Run the Pallas kernel only for quantized weights on a real TPU
    backend.  Everything else — plain arrays, CPU/virtual-device hosts
    (where interpret mode would be pure overhead), GSPMD-jitted sharded
    params (pallas_call has no SPMD partitioning rule) — falls back to
    the byte-identical dq() XLA expression.  Grouped-repacked weights
    never reach here: the shims reject them first (a global qmm over a
    shard-local layout), and shard_map stage bodies unwrap them to plain
    QuantTensor4 before their GEMMs."""
    quant = _q()
    return (isinstance(w, (quant.QuantTensor, quant.QuantTensor4))
            and jax.default_backend() == "tpu")


def qmm(x: jnp.ndarray, w) -> jnp.ndarray:
    """Dispatch shim for every ``x @ dq(w)`` GEMM site."""
    _q()._reject_grouped(w, "qmm (global fused matmul) over")
    if _kernel_path(w):
        return quant_matmul(x, w, interpret=False)
    return x @ _q().dq(w)


def qmm_head(x: jnp.ndarray, w) -> jnp.ndarray:
    """Dispatch shim for the lm-head ``einsum("bsh,vh->bsv")`` site."""
    _q()._reject_grouped(w, "qmm_head (global fused matmul) over")
    if _kernel_path(w):
        return quant_matmul_head(x, w, interpret=False)
    return jnp.einsum("bsh,vh->bsv", x, _q().dq(w))


def qmm_experts(x: jnp.ndarray, w) -> jnp.ndarray:
    """Dispatch shim for the stacked-expert einsum sites (3-D x:
    ``"bsh,ehi->bsei"``; 4-D x: ``"bsei,eih->bseh"``)."""
    _q()._reject_grouped(w, "qmm_experts (global fused matmul) over")
    if _kernel_path(w):
        return quant_matmul_experts(x, w, interpret=False)
    if x.ndim == 3:
        return jnp.einsum("bsh,ehi->bsei", x, _q().dq(w))
    return jnp.einsum("bsei,eih->bseh", x, _q().dq(w))
