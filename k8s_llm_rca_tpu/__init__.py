"""k8s_llm_rca_tpu — a TPU-native LLM-agent framework for Kubernetes root-cause analysis.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
``freiris/k8s-llm-rca`` (see SURVEY.md): a three-stage LLM agent pipeline
(metapath planning -> Cypher compilation -> temporal state audit) that in the
reference ran against the remote OpenAI Assistants API and two external Neo4j
servers.  Here the whole loop runs locally:

- ``models/ ops/ parallel/`` — JAX/Pallas Llama / Mixtral / e5 model stacks with
  DP/TP/PP/SP/EP shardings over a ``jax.sharding.Mesh`` (ICI/DCN collectives).
- ``engine/`` — sharded prefill + autoregressive decode with slot-based and
  paged KV caches, on-device sampling, stop sequences and forced fenced output.
- ``serve/`` — an assistants-compatible local API (Assistant/Thread/Message/Run
  with the reference's run-state machine and token-usage windows; reference:
  common/openai_generic_assistant.py) on a continuous-batching scheduler.
- ``graph/`` — a graph query layer: in-memory property-graph store with a
  mini-Cypher executor (hermetic), plus an optional Neo4j bolt client
  (reference: common/neo4j_query_executor.py).
- ``rca/`` — the three agent stages, behavior-equivalent to the reference's
  find_metapath/, generate_query/ and check_state/ packages.
- ``sweeps/`` — interactive and metered batch drivers (reference: test_all.py,
  test_with_file.py).
"""

__version__ = "0.1.0"
