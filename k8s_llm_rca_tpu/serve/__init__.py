from k8s_llm_rca_tpu.serve.api import (  # noqa: F401
    Assistant,
    AssistantService,
    GenericAssistant,
    Message,
    Run,
    RunStatus,
    Thread,
)
from k8s_llm_rca_tpu.serve.backend import (  # noqa: F401
    EngineBackend,
    LMBackend,
    EchoBackend,
)
from k8s_llm_rca_tpu.serve.journal import (  # noqa: F401
    RunJournal,
    read_journal,
)
from k8s_llm_rca_tpu.serve.recover import (  # noqa: F401
    recover_service,
)
