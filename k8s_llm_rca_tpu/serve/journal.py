"""Write-ahead run journal for ``AssistantService``.

The reference pipeline keeps every assistant/thread/run server-side and
loses nothing when the client process dies; the in-tree service keeps them
in process memory and loses EVERYTHING (reference analyze_root_cause.py
holds only ids, the OpenAI backend holds the state).  This journal closes
that durability gap at run granularity: every service mutation —
create_assistant, create_thread, add_message, run submit, run settle — is
appended as a checksummed, length-prefixed record (utils/wal.py) with an
fsync before the mutation is acknowledged, so a crash at ANY point leaves
a journal from which ``serve/recover.py`` rebuilds the exact service
state and re-queues the runs that never settled.

Discipline (mirrors faults/inject.py): when no journal is configured the
service pays exactly one ``is None`` check per hook — no record building,
no I/O, nothing.  The journal is the armed path, not the default path.

Record format: each WAL payload is one compact JSON object
``{"kind": <str>, ...fields}`` with sorted keys.  Kinds:

- ``create_assistant``: id, name, instructions, model, gen (GenOptions
  fields minus the grammar OBJECT — grammar specs are journaled as given:
  "json" or a schema dict; compiled FSMs are rebuilt at recovery).
- ``create_thread``: id.
- ``add_message``: thread_id, id, role, content, created_at.
- ``run_submit``: id, thread_id, assistant_id, created_at, instructions
  (the per-run override or None), gen (per-run override or None), prompt
  (the rendered prompt actually sent to the backend — journaling it makes
  resubmission independent of prompt-rendering drift).
- ``run_settle``: id, status, completed_at, usage, error, response
  (message dict for completed runs, else None).  Written for EVERY
  terminal transition — completed, failed, cancelled, expired — so replay
  can tell a finished run from an interrupted one by the mere presence of
  this record.

A partial tail (the crash artifact: a record cut mid-write) is detected by
checksum/length and dropped atomically on open — same temp + fsync +
``os.replace`` recipe as ``sweeps/run_file.py:scan_output``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils import wal
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


def encode_gen(gen) -> Optional[Dict[str, Any]]:
    """GenOptions -> JSON-safe dict (grammar kept as its SPEC: "json" or a
    schema dict survive; a pre-compiled FSM object cannot be journaled and
    fails loudly rather than silently dropping the constraint)."""
    if gen is None:
        return None
    grammar = gen.grammar
    if grammar is not None and not isinstance(grammar, (str, dict)):
        raise ValueError(
            "journal requires grammar as a spec (\"json\" or a schema "
            f"dict), got compiled object {type(grammar).__name__}; pass "
            "the spec to GenOptions and let the backend compile it")
    return {"max_new_tokens": gen.max_new_tokens, "stop": list(gen.stop),
            "forced_prefix": gen.forced_prefix, "suffix": gen.suffix,
            "grammar": grammar, "assistant_name": gen.assistant_name,
            "session": gen.session,
            "priority": gen.priority, "deadline_s": gen.deadline_s}


def decode_gen(d: Optional[Dict[str, Any]]):
    from k8s_llm_rca_tpu.serve.backend import GenOptions

    if d is None:
        return None
    grammar = d.get("grammar")
    return GenOptions(
        max_new_tokens=int(d["max_new_tokens"]), stop=tuple(d["stop"]),
        forced_prefix=d["forced_prefix"], suffix=d["suffix"],
        grammar=grammar, assistant_name=d.get("assistant_name", ""),
        session=d.get("session", ""),   # pre-cluster journals lack it
        priority=d.get("priority", 1),  # pre-overload journals lack both
        deadline_s=d.get("deadline_s"))


class RunJournal:
    """Append-only, fsync'd, crash-tolerant journal of service mutations.

    Opening an existing journal first drops any torn tail (atomic
    truncate), so appends always start at a record boundary — a restarted
    service can keep writing to the same file it recovered from.
    """

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self.appended = 0           # records appended by THIS process
        self.bytes_written = 0
        if os.path.exists(path):
            _, clean_end = wal.scan_wal(path, truncate_partial=True)
            log.debug("journal %s opened at clean offset %d", path,
                      clean_end)
        self._f = open(path, "ab")

    def append(self, kind: str, **fields: Any) -> None:
        """Durably append one record; returns only after the fsync (when
        enabled), so an acknowledged mutation survives a process kill."""
        rec = dict(fields)
        rec["kind"] = kind
        payload = json.dumps(rec, sort_keys=True,
                             separators=(",", ":")).encode("utf-8")
        with obs_trace.span("serve.journal.append", cat="serve", kind=kind,
                            bytes=len(payload)):
            n = wal.append_record(self._f, payload, fsync=self.fsync)
        self.appended += 1
        self.bytes_written += n
        METRICS.inc("serve.journal_records")
        METRICS.inc("serve.journal_bytes", n)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_journal(path: str, truncate_partial: bool = False
                 ) -> Tuple[List[Dict[str, Any]], int]:
    """Decode every intact record; returns ``(records, clean_end)``.

    A record that fails JSON decoding despite a valid checksum indicates a
    writer bug, not a crash artifact — that fails loudly instead of being
    silently skipped (skipping a mutation would corrupt every replayed
    record after it)."""
    payloads, clean_end = wal.scan_wal(path, truncate_partial=truncate_partial)
    records = [json.loads(p.decode("utf-8")) for p in payloads]
    return records, clean_end
