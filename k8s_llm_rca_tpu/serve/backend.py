"""LM backends for the assistants service.

``EngineBackend`` is the real path: requests stream through the
continuous-batching InferenceEngine, so concurrent runs (e.g. stage 3's
per-entity audits, SURVEY §3.4) share decode steps in one batch.

``EchoBackend`` is a trivial deterministic backend for serve-layer tests.
The RCA-aware scripted oracle lives in rca/oracle.py (it needs the stage
prompt contracts, which belong to the rca layer).

Forced prefixes implement the fenced-output contracts on the engine side:
the fence opener (e.g. "```json\\n") is prefilled as forced tokens and the
closing fence is a stop string, so the model cannot emit an unfenced reply —
this kills the JSONDecodeError retry loop the reference needs
(test_all.py:70-76).
"""

from __future__ import annotations

import base64
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence, Set, Tuple

from k8s_llm_rca_tpu.engine.constrain import make_grammar
from k8s_llm_rca_tpu.engine.engine import InferenceEngine
from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.utils import pages, wal
from k8s_llm_rca_tpu.utils.tokenizer import Tokenizer


class Priority:
    """Request priority classes (small ints: LOWER value = MORE urgent,
    so ``sorted()`` over (priority, seq_id) is the scheduling order).
    The engine buckets anything <= CRITICAL as critical and anything
    >= BATCH as batch for the per-priority queue gauges."""

    CRITICAL = 0      # interactive / SLO-bound: never shed by the router
    NORMAL = 1        # default
    BATCH = 2         # offline sweeps: first shed under backpressure


@dataclass(frozen=True)
class GenOptions:
    max_new_tokens: int = 256
    stop: Tuple[str, ...] = ()
    forced_prefix: str = ""     # emitted verbatim, prefilled as forced tokens
    suffix: str = ""            # appended verbatim after generation stops
    # grammar-constrained decode of the BODY (engine/constrain.py): "json"
    # guarantees the generated text parses; a schema dict
    # (constrain.SchemaGrammar) additionally forces the exact shape
    # (structured outputs).  Composes with forced_prefix / suffix carrying
    # the fences.  None = unconstrained.
    grammar: Optional[object] = None
    # routing metadata: the name of the assistant the run belongs to,
    # populated by AssistantService.create_run.  Engine backends ignore it;
    # the scripted oracle routes on it (prompt-substring routing is brittle
    # to harmless rewordings and kept only as its fallback).
    assistant_name: str = ""
    # cluster routing metadata: the session key (the thread id) the run
    # belongs to, populated by AssistantService.create_run.  Single-engine
    # backends ignore it; the cluster router pins a session to one replica
    # (cluster/router.py affinity) so a thread's monotonically growing
    # prompt keeps hitting the replica whose prefix cache already holds
    # its history.
    session: str = ""
    # overload scheduling (docs/serving.md "overload & priorities"):
    # ``priority`` orders engine admission and preemption-victim selection
    # (Priority.CRITICAL/NORMAL/BATCH; lower = more urgent) and tiers the
    # cluster router's backpressure (BATCH sheds before NORMAL, CRITICAL
    # never sheds).  ``deadline_s`` is a per-run budget in seconds on the
    # injectable clock (faults.plan.VirtualClock under chaos); the engine
    # reaps an expired sequence inside its own tick — pages freed
    # immediately, finish_reason "expired" — instead of waiting for the
    # serve-layer poll.  None = serve default (RCAConfig.run_timeout_s).
    priority: int = Priority.NORMAL
    deadline_s: Optional[float] = None


class BudgetError(ValueError):
    """The effective token budget cannot hold the grammar's minimal
    document — no valid output exists, so retrying the SAME request is
    futile by construction (callers should fall back, not retry)."""


@dataclass
class BackendResult:
    text: str
    completion_tokens: int
    prompt_tokens: Optional[int] = None   # actual prefilled tokens if known
    error: Optional[str] = None
    # the engine reaped the sequence past its deadline (finish_reason
    # "expired"): the service settles the run as EXPIRED, not FAILED
    expired: bool = False


class LMBackend(Protocol):
    def start(self, prompt: str, opts: GenOptions) -> int: ...
    def pump(self) -> Dict[int, BackendResult]: ...
    def busy(self, handle: int) -> bool: ...
    def cancel(self, handle: int) -> None: ...
    def count_tokens(self, text: str) -> int: ...


def _assert_fully_addressable(engine) -> None:
    """The engine's threaded serving driver (EngineBackend under worker
    threads, e.g. bench_rca_p50_engine) has nondeterministic tick
    interleaving, while ``host_np``'s process_allgather path requires every
    process to issue identical host syncs in identical order — driving a
    process-spanning mesh through this backend would misalign the
    collective and hang/corrupt all processes.  Multi-process meshes must
    use a deterministic single-threaded SPMD driver instead
    (tests/test_distributed.py); fail loudly at construction."""
    import jax

    leaves = list(jax.tree.leaves(engine.params))
    cache = getattr(engine, "cache", None)
    if cache is None:
        cache = getattr(engine, "pool", None)
    if cache is not None:
        leaves += jax.tree.leaves(cache)
    for leaf in leaves:
        if not getattr(leaf, "is_fully_addressable", True):
            raise ValueError(
                "EngineBackend requires a fully-addressable engine mesh: "
                "an array spans non-addressable devices (multi-process "
                "mesh), and this backend's threaded drivers tick the "
                "engine in nondeterministic order, which would misalign "
                "host_np's process_allgather across the cluster.  Drive "
                "multi-process meshes with a deterministic single-"
                "threaded SPMD loop instead (see engine.host_np and "
                "tests/test_distributed.py).")


class EngineBackend:
    """Continuous-batching engine behind the assistants API.

    Fault injection (faults/inject.py): when a plan is armed, every
    ``start`` polls ``SITE_BACKEND`` — "error" fails the run at the next
    pump, "budget" raises BudgetError at submission, "stall" accepts the
    run but never progresses it (a hung engine), so only the serve-layer
    deadline ends it; ``cancel`` then reaps it.  Cancelling any live run
    retires its engine sequence immediately (``EngineBase.cancel_seq``),
    freeing its batch slot and — on the paged engine — its pages.
    """

    def __init__(self, engine: InferenceEngine):
        _assert_fully_addressable(engine)
        self.engine = engine
        self.tokenizer = engine.tokenizer
        self._handles = itertools.count()
        self._seq_to_handle: Dict[int, int] = {}
        self._handle_seq: Dict[int, int] = {}
        self._opts: Dict[int, GenOptions] = {}
        self._live: Dict[int, bool] = {}
        self._failed: Dict[int, str] = {}    # injected run failures
        self._stalled: Set[int] = set()      # injected stalls (no result)

    def start(self, prompt: str, opts: GenOptions) -> int:
        fault = None
        if inject._ARMED is not None:
            fault = inject._ARMED.poll(inject.SITE_BACKEND)
        if fault is not None and fault.kind == "budget":
            raise BudgetError(
                f"injected budget fault at {fault.site}[{fault.index}]: "
                f"no valid output exists under this budget")
        if fault is not None and fault.kind == "error":
            # the run "fails" engine-side: surfaces as BackendResult.error
            # at the next pump, which the service maps to status=failed
            handle = next(self._handles)
            self._failed[handle] = (
                f"injected engine-run failure at "
                f"{fault.site}[{fault.index}]")
            self._live[handle] = True
            return handle
        if fault is not None and fault.kind == "stall":
            # a hung run: accepted, never progressed — stays busy until
            # the serve-layer deadline cancels it.  Nothing is submitted
            # to the engine, so the stall cannot perturb tick counts (the
            # soak's byte-identity depends on that)
            handle = next(self._handles)
            self._stalled.add(handle)
            self._live[handle] = True
            return handle
        handle = next(self._handles)
        ids = self.tokenizer.encode(prompt + opts.forced_prefix, add_bos=True)
        grammar = make_grammar(opts.grammar, self.tokenizer,
                               prefer_native=self.engine.engine_cfg.native)
        min_budget = getattr(grammar, "min_budget", None)
        if min_budget is not None:
            # check the budget AFTER engine clamping: a long prompt shrinks
            # max_new below the request (engine._clamp_prompt), and a
            # sub-minimal effective budget can only produce truncated,
            # unparseable output — fail loudly instead
            _, effective = self.engine._clamp_prompt(ids,
                                                     opts.max_new_tokens)
            if effective < min_budget():
                raise BudgetError(
                    f"effective token budget {effective} (requested "
                    f"{opts.max_new_tokens}, clamped by prompt length "
                    f"{len(ids)} vs cache cap "
                    f"{self.engine.engine_cfg.max_seq_len}) cannot hold "
                    f"the schema's minimal document ({min_budget()} tokens "
                    f"worst case); no valid output exists under this "
                    f"budget")
        # a grammar owns termination (forced EOS when the value closes);
        # stop strings must not also apply — e.g. "```" is a legal substring
        # INSIDE a JSON string, and a stop match there would truncate the
        # body mid-string and break the parse guarantee
        stop = () if grammar is not None else opts.stop
        seq_id = self.engine.submit(
            ids, max_new_tokens=opts.max_new_tokens, stop_strings=stop,
            grammar=grammar, priority=opts.priority,
            deadline_s=opts.deadline_s)
        self._seq_to_handle[seq_id] = handle
        self._handle_seq[handle] = seq_id
        self._opts[handle] = opts
        self._live[handle] = True
        return handle

    def pump(self) -> Dict[int, BackendResult]:
        results: Dict[int, BackendResult] = {}
        for handle in list(self._failed):
            msg = self._failed.pop(handle)
            if self._live.pop(handle, False):
                results[handle] = BackendResult("", 0, error=msg)
        if self._stalled and inject._ARMED is not None:
            # a stalled run only ends via the serve deadline; advance the
            # plan's virtual clock so that deadline arrives after a
            # DETERMINISTIC number of pumps instead of wall seconds
            inject._ARMED.clock.sleep(0.05)
        if not self.engine.has_work:
            if self._live:
                # pumped with live handles but nothing decodable: every
                # live run is stalled (injected fault) or orphaned.  Count
                # it so sweep timelines show WAITED ticks, not just busy
                # ones (registered obs site; TickSample.idle_ticks picks
                # the counter up on the next real tick).
                self.engine._count("engine.idle_ticks")
                obs_trace.event("engine.idle_ticks", live=len(self._live))
            return results
        for res in self.engine.step():
            handle = self._seq_to_handle.pop(res.seq_id, None)
            if handle is None:
                continue
            self._handle_seq.pop(handle, None)
            opts = self._opts.pop(handle, GenOptions())
            live = self._live.pop(handle, False)
            if not live:                   # cancelled: drop, don't leak
                continue
            text = opts.forced_prefix + res.text + opts.suffix
            if res.finish_reason == "expired":
                results[handle] = BackendResult(
                    text=text,
                    completion_tokens=res.completion_tokens,
                    prompt_tokens=res.prompt_tokens,
                    error="deadline exceeded (engine deadline reap)",
                    expired=True)
                continue
            results[handle] = BackendResult(
                text=text,
                completion_tokens=res.completion_tokens,
                prompt_tokens=res.prompt_tokens)
        if results:
            obs_trace.event("backend.settled", n=len(results))
        return results

    def busy(self, handle: int) -> bool:
        return self._live.get(handle, False)

    def cancel(self, handle: int) -> None:
        # abort for real: the engine sequence retires NOW (the paged
        # engine frees its pages through the normal _retire path), so an
        # expired/cancelled run cannot leak allocator blocks or keep
        # occupying a batch slot
        if handle not in self._live and handle not in self._failed:
            return
        self._failed.pop(handle, None)
        self._stalled.discard(handle)
        self._live.pop(handle, None)
        self._opts.pop(handle, None)
        seq_id = self._handle_seq.pop(handle, None)
        if seq_id is not None:
            self._seq_to_handle.pop(seq_id, None)
            self.engine.cancel_seq(seq_id)

    def count_tokens(self, text: str) -> int:
        return self.tokenizer.count(text)

    def queue_depth(self) -> int:
        """Live runs on this backend — the router's load-balancing
        signal (cluster/router.py picks the alive replica with the
        smallest depth for a session it has not seen)."""
        return len(self._live)

    def occupancy(self) -> float:
        """Fraction of the engine's batch slots occupied (0..1) — the
        per-replica gauge the router mirrors into the tick timeline and
        Prometheus ``cluster_replica_occupancy``."""
        return (len(self.engine._active)
                / max(1, self.engine.engine_cfg.max_batch))

    def adopt_sequences(self, snap: Dict[str, object],
                        opts: Sequence[GenOptions]) -> List[int]:
        """Adopt another engine's ``snapshot_sequences`` export into THIS
        backend: the cluster failover path (cluster/router.py
        ``drain_replica``).  Three things make adoption different from a
        raw ``restore_sequences`` on the target engine:

        - seq ids are REMAPPED into the target engine's namespace (the
          replicas' independent ``_seq_counter``s collide, and
          ``restore_sequences`` raises loudly on collision by design);
        - grammars are recompiled from each run's GenOptions SPEC and
          rebuilt by advancing over the generated tokens (compiled FSMs
          are host objects owned by the dead replica);
        - the source RNG key is dropped (``rng_key: None``): migration
          must never clobber the target replica's key mid-decode —
          greedy parity holds regardless, by the snapshot contract.

        Fresh backend handles are registered per sequence so ``pump``
        settles the migrated runs exactly like native ones (results for
        unknown seq_ids are dropped there — adoption must come through
        here, never through the engine directly).  Returns the new
        handles in snapshot order."""
        seqs = list(snap.get("sequences", []))
        if len(opts) != len(seqs):
            raise ValueError(
                f"adopt_sequences needs one GenOptions per snapshotted "
                f"sequence: got {len(opts)} for {len(seqs)}")
        remapped = []
        grammars: Dict[int, object] = {}
        for s, o in zip(seqs, opts):
            new_id = next(self.engine._seq_counter)
            s2 = dict(s)
            s2["seq_id"] = new_id
            if s.get("grammar"):
                if o.grammar is None:
                    raise ValueError(
                        f"seq {s['seq_id']} was grammar-constrained but "
                        f"its GenOptions carries no grammar spec; the "
                        f"FSM is rebuilt from the spec at adoption")
                grammars[new_id] = make_grammar(
                    o.grammar, self.tokenizer,
                    prefer_native=self.engine.engine_cfg.native)
            remapped.append(s2)
        self.engine.restore_sequences(
            {"rng_key": None, "sequences": remapped}, grammars=grammars)
        handles: List[int] = []
        for s2, o in zip(remapped, opts):
            handle = next(self._handles)
            seq_id = s2["seq_id"]
            self._seq_to_handle[seq_id] = handle
            self._handle_seq[handle] = seq_id
            self._opts[handle] = o
            self._live[handle] = True
            handles.append(handle)
        return handles

    def snapshot_sequences(self) -> Tuple[Dict[str, object], List[int]]:
        """Snapshot every live engine sequence for migration, returning
        ``(snapshot, handles)`` — the JSON-safe engine export plus THIS
        backend's handle for each snapshotted sequence, in snapshot
        order.  The backend-level seam ``ClusterRouter.drain_replica``
        works through (proc replicas answer it over the wire — the
        router must not reach for ``engine._seq_to_handle`` internals
        that live in another process).  Resident prefix pages are
        published to the shared PrefixStore FIRST, so the adopter's
        re-prefill promotes them by h2d page writes (the warm-start
        contract, docs/cluster.md)."""
        if hasattr(self.engine, "flush_prefix_store"):
            self.engine.flush_prefix_store()
        snap = self.engine.snapshot_sequences()
        handles = [self._seq_to_handle[s["seq_id"]]
                   for s in snap.get("sequences", [])]
        return snap, handles

    def export_run(self, handle: int) -> Optional[Dict[str, object]]:
        """Per-run EXPORT for the disaggregated handoff
        (cluster/disagg.py): freeze ONE live run and return its wire
        frame ``{"seq": <snapshot entry>, "kv": None | {"b64", "length",
        "cur_token"}}`` — the entry is the durable token state, the kv
        block (when the paged engine could spill it) is the CRC-framed
        ``utils/pages.py`` disk codec, base64'd so the frame stays
        JSON-safe over the proc transports.  The run STAYS live here
        until the adopter acks and the caller cancels this handle
        (RELEASE).  None = nothing to export right now: unknown/settled
        handle (the run raced to completion — not a retry), an injected
        stall/failure, or an engine state that cannot freeze this pump
        (chunked prefill in flight).  Never raises for a missing run:
        the handoff queue self-cleans on the next pump."""
        seq_id = self._handle_seq.get(handle)
        if seq_id is None or not self._live.get(handle, False):
            return None
        if hasattr(self.engine, "flush_prefix_store"):
            # publish resident prefix pages first so a re-prefill after
            # a failed handoff is a mostly-HIT path on any replica
            self.engine.flush_prefix_store()
        exported = self.engine.export_run(seq_id)
        if exported is None:
            return None
        entry, kv = exported
        frame: Dict[str, object] = {"seq": entry, "kv": None}
        if kv is not None:
            try:
                blob = pages.encode_page_record(
                    {k: kv[k] for k in
                     ("n_pages",) + pages.record_fields(kv)})
            except ValueError:
                blob = None     # record too large to frame: entry-only
            if blob is not None:
                b64 = base64.b64encode(blob).decode("ascii")
                if len(b64) + 4096 <= wal.MAX_RECORD_SIZE:
                    frame["kv"] = {"b64": b64,
                                   "length": int(kv["length"]),
                                   "cur_token": int(kv["cur_token"])}
        return frame

    def adopt_run(self, frame: Dict[str, object],
                  opts: GenOptions) -> int:
        """Per-run ADOPT: validate the ENTIRE frame before any engine
        state moves, then re-admit the run under a fresh seq id/handle.
        A malformed entry or a torn/corrupt kv blob raises ValueError —
        the transfer is discarded whole and the caller retries from the
        still-pinned source; this backend is left untouched.  A kv
        record that decodes but was gathered under a different PAGE
        SIZE is re-chunked deterministically by the engine's adopt
        (``engine.handoff_kv_relayout``); one whose dtype/kv_dim/layer
        geometry differs raises ValueError (a misconfigured tier pair —
        TierRouter refuses to build one); torn frames (length mismatch,
        page overflow) drop to a counted re-prefill, byte-identical
        output."""
        entry = frame.get("seq") if isinstance(frame, dict) else None
        if (not isinstance(entry, dict)
                or not {"seq_id", "prompt_ids", "generated",
                        "remaining_new_tokens",
                        "stop_strings"} <= set(entry)):
            raise ValueError(
                "torn handoff frame: malformed sequence entry")
        rec = None
        kv = frame.get("kv")
        if kv is not None:
            try:
                blob = base64.b64decode(kv["b64"], validate=True)
                rec = pages.decode_page_record(blob)
            except Exception:
                raise ValueError(
                    "torn handoff frame: kv blob failed base64/frame "
                    "decoding; transfer discarded whole")
            if rec is None:
                raise ValueError(
                    "torn handoff frame: kv page record failed CRC/"
                    "layout checks; transfer discarded whole")
            rec["n_shared"] = 0
            rec["shared_pages"] = []
            rec["length"] = int(kv["length"])
            rec["cur_token"] = int(kv["cur_token"])
        new_id = next(self.engine._seq_counter)
        grammar = None
        if entry.get("grammar"):
            if opts.grammar is None:
                raise ValueError(
                    f"seq {entry['seq_id']} was grammar-constrained but "
                    f"its GenOptions carries no grammar spec; the FSM "
                    f"is rebuilt from the spec at adoption")
            grammar = make_grammar(
                opts.grammar, self.tokenizer,
                prefer_native=self.engine.engine_cfg.native)
        self.engine.adopt_run(dict(entry, seq_id=new_id), kv=rec,
                              grammar=grammar)
        handle = next(self._handles)
        self._seq_to_handle[new_id] = handle
        self._handle_seq[handle] = new_id
        self._opts[handle] = opts
        self._live[handle] = True
        return handle

    def host_counters(self) -> Dict[str, float]:
        """Cumulative host<->device traffic counters of the backing
        engine (engine.h2d_uploads / d2h_syncs / dispatches /
        decode_tokens — docs/performance.md).  The serve layer exposes
        them so bench/ops can compute syncs-per-decoded-token without
        reaching into engine internals.  With ``host_overlap`` engines
        note the counters run one flush behind the last committed token
        (lagged commit); read after drain (``has_work`` False) for exact
        totals."""
        counts = getattr(self.engine, "_counts", None) or {}
        return {key: float(counts.get(key, 0.0))
                for key in ("engine.h2d_uploads", "engine.d2h_syncs",
                            "engine.dispatches", "engine.decode_tokens")}


class EchoBackend:
    """Deterministic test backend: replies with a fixed or prompt-derived
    string after ``delay_pumps`` pump calls (to exercise the run-state
    machine's in_progress window)."""

    def __init__(self, tokenizer: Tokenizer, reply: Optional[str] = None,
                 delay_pumps: int = 0, fail: bool = False):
        self.tokenizer = tokenizer
        self.reply = reply
        self.fail = fail
        self.delay_pumps = delay_pumps
        self._handles = itertools.count()
        self._inflight: Dict[int, Tuple[str, GenOptions, int]] = {}

    def start(self, prompt: str, opts: GenOptions) -> int:
        handle = next(self._handles)
        self._inflight[handle] = (prompt, opts, self.delay_pumps)
        return handle

    def pump(self) -> Dict[int, BackendResult]:
        results: Dict[int, BackendResult] = {}
        for handle in list(self._inflight):
            prompt, opts, remaining = self._inflight[handle]
            if remaining > 0:
                self._inflight[handle] = (prompt, opts, remaining - 1)
                continue
            del self._inflight[handle]
            if self.fail:
                results[handle] = BackendResult("", 0, error="echo backend failure")
                continue
            text = self.reply if self.reply is not None else f"echo: {prompt[-64:]}"
            text = opts.forced_prefix + text + opts.suffix
            results[handle] = BackendResult(
                text=text, completion_tokens=self.tokenizer.count(text))
        return results

    def busy(self, handle: int) -> bool:
        return handle in self._inflight

    def cancel(self, handle: int) -> None:
        self._inflight.pop(handle, None)

    def count_tokens(self, text: str) -> int:
        return self.tokenizer.count(text)

    def queue_depth(self) -> int:
        # same load signal EngineBackend exposes, so the cluster router's
        # capacity tiering is testable without a real engine
        return len(self._inflight)
