"""Local assistants API: Assistant / Thread / Message / Run on a local backend.

This is the drop-in replacement surface for the reference's
``OpenAIGenericAssistant`` (common/openai_generic_assistant.py) — the same
object model and the same 13 client methods — except the compute behind it is
the in-tree TPU engine instead of HTTPS to api.openai.com:

- the run-state machine is preserved exactly: ``queued | in_progress |
  completed | cancelled | failed | expired`` (reference :100-112 branches on
  these);
- ``get_token_usage(tmin, tmax, limit)`` keeps the reference's window
  semantics (:117-135): sum usage over runs whose created_at AND completed_at
  both fall in ``[tmin, tmax)``, newest-first, capped at ``limit``;
- ``wait_get_last_k_message`` keeps the blocking contract but pumps the
  scheduler instead of sleeping 5·i seconds per poll (:92-115) — the 5 s
  polling floor per LLM call simply disappears;
- message listings are newest-first and messages expose
  ``.content[0].text.value`` so stage code written against the OpenAI shapes
  ports without edits (reference usage: find_srckind_metapath_neo4j.py:189).

Threads support concurrent runs from one thread (the reference serializes
per-thread; SURVEY §3.4 notes stage 3 issues independent per-entity audits on
a shared thread — here they can overlap in the batch).

The service is thread-safe: one coarse re-entrant lock serializes every
public method and the backend pump, so N sweep workers can drive their own
pipelines against ONE shared service/engine and the continuous batcher
merges their runs into shared decode ticks (the configs[2] sweep shape —
see sweeps/run_file.py --workers).  A worker blocked on the lock while
another worker's pump ticks the engine is not wasted time: that tick
decodes every in-flight run, including the blocked worker's.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.serve.backend import GenOptions, LMBackend
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)


class RunStatus:
    QUEUED = "queued"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"
    CANCELLED = "cancelled"
    FAILED = "failed"
    EXPIRED = "expired"

    TERMINAL = (COMPLETED, CANCELLED, FAILED, EXPIRED)


# --- OpenAI-shaped message content (stage code reads .content[0].text.value)


@dataclass
class _Text:
    value: str


@dataclass
class _ContentPart:
    text: _Text
    type: str = "text"


@dataclass
class Message:
    id: str
    role: str
    raw_content: str
    created_at: float
    content: List[_ContentPart] = field(default_factory=list)

    def __post_init__(self):
        if not self.content:
            self.content = [_ContentPart(text=_Text(value=self.raw_content))]


@dataclass
class MessageList:
    data: List[Message]        # newest first, like the OpenAI listing


@dataclass
class Assistant:
    id: str
    name: str
    instructions: str
    model: str
    gen: GenOptions = field(default_factory=GenOptions)


@dataclass
class Thread:
    id: str
    messages: List[Message] = field(default_factory=list)  # oldest first


@dataclass
class Run:
    id: str
    thread_id: str
    assistant_id: str
    status: str = RunStatus.QUEUED
    created_at: Optional[int] = None
    completed_at: Optional[int] = None
    usage: Dict[str, int] = field(default_factory=lambda: {
        "prompt_tokens": 0, "completion_tokens": 0, "total_tokens": 0})
    error: Optional[str] = None
    # book-keeping
    instructions_override: Optional[str] = None
    backend_handle: Optional[int] = None
    deadline: Optional[float] = None
    response_message_id: Optional[str] = None
    # precise (float) start time on the service clock, for the flight
    # recorder's "serve.run" span (created_at is int seconds for the
    # reference's window semantics and too coarse for span durations)
    t_started: Optional[float] = None


def render_prompt(assistant: Assistant, thread: Thread,
                  instructions_override: Optional[str] = None) -> str:
    """Chat-template rendering of instructions + thread history.

    The whole thread is replayed every run, matching the reference's
    monotonically growing assistant threads (SURVEY §5 long-context note) —
    this is precisely what makes CP/ring-attention prefill worth having.
    """
    instructions = instructions_override or assistant.instructions
    parts = [f"<|system|>\n{instructions}\n"]
    for m in thread.messages:
        parts.append(f"<|{m.role}|>\n{m.raw_content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


def _locked(fn):
    """Serialize a service method on the instance's re-entrant lock."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class AssistantService:
    """The 'server': owns assistants/threads/runs and drives an LMBackend."""

    def __init__(self, backend: LMBackend, run_timeout_s: float = 600.0,
                 clock=None, journal=None):
        # ``clock``: injectable time source (time()/sleep()) for run
        # timestamps and deadlines — the real ``time`` module by default,
        # a faults.plan.VirtualClock under chaos runs so deadline expiry
        # happens after a deterministic number of pumps, not wall seconds
        # ``journal``: optional serve.journal.RunJournal.  Every mutation
        # hook below is guarded by a single ``is None`` check (same
        # discipline as faults/inject.py) — the default path does zero
        # journal work, builds zero records, touches zero files.
        self.backend = backend
        self.run_timeout_s = run_timeout_s
        self._clock = clock if clock is not None else time
        self._journal = journal
        self.assistants: Dict[str, Assistant] = {}
        self.threads: Dict[str, Thread] = {}
        self.runs: Dict[str, Run] = {}
        self._thread_runs: Dict[str, List[str]] = {}
        self._inflight: Dict[int, str] = {}   # backend handle -> run id
        self._ids = itertools.count()
        self._lock = threading.RLock()
        self._waiters = 0       # concurrent wait_run count (handoff sleep)

    @_locked
    def _next_id(self, prefix: str) -> str:
        return f"{prefix}_{next(self._ids):08d}"

    # ------------------------------------------------------------ lifecycle

    @_locked
    def create_assistant(self, instructions: str, name: str,
                         model: str = "local",
                         gen: Optional[GenOptions] = None) -> Assistant:
        a = Assistant(self._next_id("asst"), name, instructions, model,
                      gen or GenOptions())
        self.assistants[a.id] = a
        if self._journal is not None:
            from k8s_llm_rca_tpu.serve.journal import encode_gen
            self._journal.append("create_assistant", id=a.id, name=a.name,
                                 instructions=a.instructions, model=a.model,
                                 gen=encode_gen(a.gen))
        return a

    @_locked
    def retrieve_assistant(self, assistant_id: str) -> Assistant:
        return self.assistants[assistant_id]

    @_locked
    def create_thread(self) -> Thread:
        t = Thread(self._next_id("thread"))
        self.threads[t.id] = t
        self._thread_runs[t.id] = []
        if self._journal is not None:
            self._journal.append("create_thread", id=t.id)
        return t

    @_locked
    def retrieve_thread(self, thread_id: str) -> Thread:
        return self.threads[thread_id]

    @_locked
    def add_message(self, thread_id: str, content: str,
                    role: str = "user") -> Message:
        m = Message(self._next_id("msg"), role, content, time.time())
        self.threads[thread_id].messages.append(m)
        if self._journal is not None:
            self._journal.append("add_message", thread_id=thread_id,
                                 id=m.id, role=m.role, content=m.raw_content,
                                 created_at=m.created_at)
        return m

    @_locked
    def create_run(self, thread_id: str, assistant_id: str,
                   instructions: Optional[str] = None,
                   gen: Optional[GenOptions] = None) -> Run:
        assistant = self.assistants[assistant_id]
        run = Run(self._next_id("run"), thread_id, assistant_id,
                  created_at=int(self._clock.time()),
                  instructions_override=instructions)
        run.t_started = self._clock.time()
        self.runs[run.id] = run
        self._thread_runs[thread_id].append(run.id)

        prompt = render_prompt(assistant, self.threads[thread_id], instructions)
        # session = thread id: the cluster router's affinity key, so every
        # run of a thread lands on the replica already holding its prefix.
        # Every run carries a concrete deadline into the ENGINE (eager
        # in-tick reaping frees pages the moment it passes): the caller's
        # GenOptions.deadline_s when set, else run_timeout_s — the serve-
        # level poll expiry stays as a backstop at the tighter of the two.
        base = gen or assistant.gen
        deadline_s = (base.deadline_s if base.deadline_s is not None
                      else self.run_timeout_s)
        run.deadline = self._clock.time() + min(self.run_timeout_s,
                                                deadline_s)
        opts = dataclasses.replace(base,
                                   assistant_name=assistant.name,
                                   session=thread_id,
                                   deadline_s=deadline_s)
        run.usage["prompt_tokens"] = self.backend.count_tokens(prompt)
        run.backend_handle = self.backend.start(prompt, opts)
        run.status = RunStatus.IN_PROGRESS
        self._inflight[run.backend_handle] = run.id
        if self._journal is not None:
            # journaled AFTER backend.start: a submission the backend
            # rejected (BudgetError) never reaches the journal, so replay
            # cannot resurrect a run that was never accepted
            from k8s_llm_rca_tpu.serve.journal import encode_gen
            self._journal.append(
                "run_submit", id=run.id, thread_id=thread_id,
                assistant_id=assistant_id, created_at=run.created_at,
                instructions=instructions, gen=encode_gen(gen),
                prompt=prompt)
        METRICS.inc("serve.runs_started")
        obs_trace.event("serve.run_started", run=run.id,
                        assistant=assistant.name)
        return run

    @_locked
    def retrieve_run(self, run_id: str) -> Run:
        self._pump()
        return self.runs[run_id]

    @_locked
    def poll_run(self, run_id: str) -> Run:
        """Non-blocking probe: advance the backend by ONE pump and return
        the run, terminal or not.  This is the future-style half of the
        run API — ``wait_run`` spins this in a loop; a sweep scheduler
        calls it once per slot visit and interleaves other incidents'
        stages while the run decodes (the reference's 5 s ``sleep`` poll,
        common/openai_generic_assistant.py:92-115, with the sleep deleted
        and the wait externalized)."""
        self._pump()
        return self.runs[run_id]

    @_locked
    def pump_once(self) -> None:
        """Public single pump: advance the backend one tick and settle any
        finished runs, without reference to a particular run.  The sweep
        scheduler's shared pump loop calls this when every in-flight
        incident is blocked on an unsettled run — one tick decodes ALL of
        them (the continuous batcher doesn't care which caller pumps)."""
        self._pump()

    @_locked
    def reap_dropped_run(self, run_id: str) -> Run:
        """Settle a non-terminal run whose backend no longer tracks its
        handle — the ``_wait_run_loop`` 'backend dropped the run' path,
        exposed for non-blocking pollers: the sweep scheduler cannot sit
        inside ``wait_run`` (it has other incidents to advance), so it
        applies the same liveness check between pumps.  Unlike the wait
        loop this also drops the handle from ``_inflight``, so a later
        deadline sweep in ``_pump`` cannot flip the FAILED run to
        EXPIRED."""
        run = self.runs[run_id]
        if (run.status not in RunStatus.TERMINAL
                and not self.backend.busy(run.backend_handle)):
            run.status = RunStatus.FAILED
            run.error = "backend dropped the run"
            self._inflight.pop(run.backend_handle, None)
            if self._journal is not None:
                self._journal_settle(run)
        return run

    @_locked
    def cancel_run(self, run_id: str) -> Run:
        run = self.runs[run_id]
        if run.status not in RunStatus.TERMINAL:
            self.backend.cancel(run.backend_handle)
            run.status = RunStatus.CANCELLED
            run.completed_at = int(self._clock.time())
            self._inflight.pop(run.backend_handle, None)
            if self._journal is not None:
                self._journal_settle(run)
            self._trace_run_settled(run)
        return run

    def _journal_settle(self, run: Run) -> None:
        """Append the run's terminal transition.  Only ever called behind
        ``self._journal is not None`` — never on the default path."""
        response = None
        if run.response_message_id is not None:
            for m in self.threads[run.thread_id].messages:
                if m.id == run.response_message_id:
                    response = {"id": m.id, "role": m.role,
                                "content": m.raw_content,
                                "created_at": m.created_at}
                    break
        self._journal.append(
            "run_settle", id=run.id, status=run.status,
            completed_at=run.completed_at, usage=dict(run.usage),
            error=run.error, response=response)

    def _trace_run_settled(self, run: Run) -> None:
        """Record the run's whole lifetime as one explicit-times
        'serve.run' span (start = create_run, end = settle — the two are
        separate pump calls, so the context-manager span API cannot
        bracket them).  No-op without an active tracer."""
        tr = obs_trace._ACTIVE
        if tr is None:
            return
        assistant = self.assistants.get(run.assistant_id)
        now = self._clock.time()
        t0 = run.t_started if run.t_started is not None else now
        tr.add_span("serve.run", t0, now, cat="serve",
                    args={"run": run.id, "status": run.status,
                          "assistant": assistant.name if assistant else "",
                          "completion_tokens":
                          run.usage["completion_tokens"]})

    @_locked
    def list_runs(self, thread_id: str, limit: int = 20,
                  order: str = "desc") -> List[Run]:
        ids = self._thread_runs.get(thread_id, [])
        runs = [self.runs[i] for i in ids]
        if order == "desc":
            runs = runs[::-1]
        return runs[:limit]

    @_locked
    def assistant_token_usage(self, assistant_id: str, tmin: int, tmax: int,
                              limit: int = 20) -> Dict[str, int]:
        """Windowed usage over ALL of an assistant's runs (any thread) —
        the reference's window semantics (created_at AND completed_at in
        [tmin, tmax), newest-first, capped) applied assistant-wide, so
        runs on audit sub-threads stay counted."""
        usage = {"prompt_tokens": 0, "completion_tokens": 0,
                 "total_tokens": 0}
        # newest `limit` runs FIRST, then window-filter — the reference's
        # order of operations (list_runs(limit) then the window test,
        # reference common/openai_generic_assistant.py:117-135)
        newest = sorted(
            (r for r in self.runs.values()
             if r.assistant_id == assistant_id and r.created_at is not None),
            key=lambda r: r.created_at, reverse=True)[:limit]
        for run in newest:
            if (run.completed_at is not None
                    and tmin <= run.created_at < tmax
                    and tmin <= run.completed_at < tmax):
                for k in usage:
                    usage[k] += run.usage[k]
        return usage

    @_locked
    def usage_for_runs(self, run_ids: Sequence[str],
                       critical_path: bool = False) -> Dict[str, Any]:
        """Exact usage attribution: sum the usage of precisely the named
        runs (terminal only — in-flight usage is still moving).  The
        wall-clock window of ``assistant_token_usage`` double-counts when
        incidents overlap in time (pipelined sweeps); summing by the run
        ids an incident actually created cannot.  Same 3-key schema as the
        reference's windowed accounting.

        ``critical_path=True`` additionally attaches the per-run latency
        decomposition (obs/critical_path.py over the ACTIVE tracer's
        merged fleet tree) under a ``"critical_path"`` key.  Strictly
        opt-in: the default 3-key schema is embedded in the pipelined
        sweep's byte-compared ``report_bytes`` and must never change
        shape."""
        usage: Dict[str, Any] = {"prompt_tokens": 0,
                                 "completion_tokens": 0,
                                 "total_tokens": 0}
        for rid in run_ids:
            run = self.runs.get(rid)
            if run is not None and run.status in RunStatus.TERMINAL:
                for k in ("prompt_tokens", "completion_tokens",
                          "total_tokens"):
                    usage[k] += run.usage[k]
        if critical_path:
            from k8s_llm_rca_tpu.obs.critical_path import (
                critical_path as _decompose)

            tr = obs_trace._ACTIVE
            usage["critical_path"] = (
                _decompose(tr, runs=set(run_ids)) if tr is not None
                else {})
        return usage

    @_locked
    def list_messages(self, thread_id: str, limit: Optional[int] = None
                      ) -> MessageList:
        msgs = self.threads[thread_id].messages[::-1]  # newest first
        if limit is not None:
            msgs = msgs[:limit]
        return MessageList(data=msgs)

    # -------------------------------------------------------- observability

    @_locked
    def prometheus_metrics(self) -> str:
        """Prometheus text exposition for this service: the global METRICS
        store (serve/engine/rca counters + phase-latency summaries) plus
        live engine gauges (running/queued seqs, free/evictable pages,
        prefix-hit tokens) when the backend carries an engine.  This is
        the serve API's scrape surface — an HTTP wrapper only needs to
        return this string with content type text/plain; version=0.0.4.
        A cluster backend (cluster.ClusterRouter — duck-typed on its
        ``queue_depths`` accessor) additionally yields ``cluster_*``
        gauges: replicas alive, per-replica queue depth and occupancy.
        Under an active tracer, worker counters shipped over the fleet
        telemetry seam render into the same families with ``{replica=}``
        labels."""
        from k8s_llm_rca_tpu.obs.export import prometheus_text

        router = (self.backend
                  if hasattr(self.backend, "queue_depths") else None)
        return prometheus_text(METRICS,
                               engine=getattr(self.backend, "engine", None),
                               router=router,
                               tracer=obs_trace._ACTIVE)

    # ------------------------------------------------------------ execution

    @_locked
    def _pump(self) -> None:
        """Advance the backend and settle any finished runs.  O(in-flight
        runs), not O(all runs ever created)."""
        results = self.backend.pump()
        now = self._clock.time()
        for handle, run_id in list(self._inflight.items()):
            run = self.runs[run_id]
            if handle in results:
                res = results[handle]
                if res.error is not None:
                    # engine-reaped deadline expiry surfaces as its own
                    # terminal status (pages already freed in-tick);
                    # journal/recovery replay it verbatim
                    run.status = (RunStatus.EXPIRED
                                  if getattr(res, "expired", False)
                                  else RunStatus.FAILED)
                    run.error = res.error
                else:
                    run.status = RunStatus.COMPLETED
                    msg = Message(self._next_id("msg"), "assistant",
                                  res.text, now)
                    self.threads[run.thread_id].messages.append(msg)
                    run.response_message_id = msg.id
                if res.prompt_tokens is not None:
                    # prefer the engine's ground truth (includes BOS, forced
                    # prefix, and any overflow truncation)
                    run.usage["prompt_tokens"] = res.prompt_tokens
                run.usage["completion_tokens"] = res.completion_tokens
                run.usage["total_tokens"] = (
                    run.usage["prompt_tokens"] + res.completion_tokens)
                run.completed_at = int(self._clock.time())
                del self._inflight[handle]
                if self._journal is not None:
                    self._journal_settle(run)
                self._trace_run_settled(run)
            elif run.deadline is not None and now > run.deadline:
                self.backend.cancel(run.backend_handle)
                run.status = RunStatus.EXPIRED
                run.completed_at = int(self._clock.time())
                del self._inflight[handle]
                if self._journal is not None:
                    self._journal_settle(run)
                self._trace_run_settled(run)
        if results:
            obs_trace.event("serve.settled", n=len(results))

    def wait_run(self, run_id: str, timeout_s: Optional[float] = None) -> Run:
        # NOT @_locked: the lock is taken per pump iteration, never for the
        # whole wait, so concurrent waiters interleave — each tick one of
        # them drives decodes EVERY in-flight run forward
        run = self.runs[run_id]
        t0 = self._clock.time()
        with self._lock:               # += is not atomic across threads
            self._waiters += 1
        try:
            return self._wait_run_loop(run, t0, timeout_s)
        finally:
            with self._lock:
                self._waiters -= 1

    def _wait_run_loop(self, run: Run, t0: float,
                       timeout_s: Optional[float]) -> Run:
        while run.status not in RunStatus.TERMINAL:
            with self._lock:
                if run.status in RunStatus.TERMINAL:
                    break
                self._pump()
                if run.status in RunStatus.TERMINAL:
                    break
                if not self.backend.busy(run.backend_handle):
                    # backend lost the handle without a result
                    run.status = RunStatus.FAILED
                    run.error = "backend dropped the run"
                    if self._journal is not None:
                        self._journal_settle(run)
                    break
                if timeout_s is not None and self._clock.time() - t0 > timeout_s:
                    # mirror _pump's deadline path: cancel the backend run
                    # and drop it from _inflight, else the abandoned run
                    # keeps occupying a batch slot and a peer worker's
                    # later _pump would flip this EXPIRED run to COMPLETED
                    self.backend.cancel(run.backend_handle)
                    self._inflight.pop(run.backend_handle, None)
                    run.status = RunStatus.EXPIRED
                    run.completed_at = int(self._clock.time())
                    if self._journal is not None:
                        self._journal_settle(run)
                    self._trace_run_settled(run)
                    break
            # with PEER waiters, a REAL sleep (not sleep(0)): lock release
            # does not hand off — this thread would re-acquire before a
            # peer blocked on create_run/add_message gets scheduled,
            # serializing the whole sweep onto one worker's runs.  1 ms
            # against multi-ms pump ticks guarantees handoff; the
            # single-waiter case skips the sleep entirely (no contention
            # to break, and +1 ms per tick would tax fast backends).
            if self._waiters > 1:
                time.sleep(0.001)
        return run


def drive_steps(gen, service: AssistantService):
    """Run a step generator (rca/pipeline.py::incident_steps and friends)
    to completion by BLOCKING on each yielded run — the sequential
    scheduling of the exact code the sweep scheduler (rca/scheduler.py)
    interleaves.  ``StopIteration.value`` is the generator's result.
    Exceptions raised inside the generator (failed runs are detected at
    the parse halves) propagate unchanged."""
    try:
        pending = next(gen)
        while True:
            service.wait_run(pending.id)
            pending = gen.send(None)
    except StopIteration as stop:
        return stop.value


def run_reply_text(service: AssistantService, run: Run) -> str:
    """Reply text of a COMPLETED run, located by its response_message_id
    (robust to concurrent runs settling interleaved on a shared thread —
    the same disambiguation ``wait_get_last_k_message`` applies).  The
    parse halves of the split stage functions (rca/locator.py,
    rca/cyphergen.py) read their settled runs through this."""
    for m in service.list_messages(run.thread_id).data:
        if m.id == run.response_message_id:
            return m.content[0].text.value
    raise RuntimeError(f"reply message for run {run.id} not found")


class GenericAssistant:
    """Reference-compatible client: the 13 methods of
    common/openai_generic_assistant.py:10-135, same names, same shapes."""

    def __init__(self, service: AssistantService):
        self.service = service
        self.assistant: Optional[Assistant] = None
        self.thread: Optional[Thread] = None
        self.message: Optional[Message] = None
        self.run: Optional[Run] = None

    # --- lifecycle (reference :16-35)

    def create_assistant(self, instructions: str, name: str,
                         model: str = "local",
                         gen: Optional[GenOptions] = None) -> None:
        self.assistant = self.service.create_assistant(
            instructions, name, model, gen)

    def retrieve_assistant(self, assistant_id: str) -> None:
        self.assistant = self.service.retrieve_assistant(assistant_id)

    def create_thread(self) -> None:
        self.thread = self.service.create_thread()

    def retrieve_thread(self, thread_id: str) -> None:
        self.thread = self.service.retrieve_thread(thread_id)

    # --- messages & runs (reference :37-58)

    def add_message(self, content: str) -> None:
        self.message = self.service.add_message(self.thread.id, content)

    def run_assistant(self, instructions: Optional[str] = None,
                      gen: Optional[GenOptions] = None) -> None:
        """``gen``: per-run GenOptions override (e.g. a request-specific
        grammar — the cypher skeleton grammar differs per metapath)."""
        self.run = self.service.create_run(
            self.thread.id, self.assistant.id, instructions, gen)

    def get_run_status(self) -> Run:
        return self.service.retrieve_run(self.run.id)

    # --- listings (reference :60-90)

    def display_response(self) -> None:
        print(self.get_last_message().data[0])

    def get_last_message(self) -> MessageList:
        return self.service.list_messages(self.thread.id, limit=1)

    def get_all_message(self) -> MessageList:
        return self.service.list_messages(self.thread.id, limit=20)

    def get_last_k_message(self, num: int) -> MessageList:
        return self.service.list_messages(self.thread.id, limit=num)

    # --- blocking wait (reference :92-115; polling becomes a pumped future)

    def wait_get_last_k_message(self, num: int = 1) -> Optional[MessageList]:
        run = self.service.wait_run(self.run.id)
        if run.status == RunStatus.COMPLETED:
            msgs = self.get_last_k_message(num)
            # Concurrent runs on a shared thread may have settled in the same
            # pump; make sure data[0] is THIS run's reply (stage code reads
            # data[0].content[0].text.value as the awaited answer).
            if run.response_message_id is not None and (
                    not msgs.data or msgs.data[0].id != run.response_message_id):
                all_msgs = self.service.list_messages(self.thread.id)
                mine = [m for m in all_msgs.data
                        if m.id == run.response_message_id]
                rest = [m for m in all_msgs.data
                        if m.id != run.response_message_id]
                msgs = MessageList(data=(mine + rest)[:num])
            return msgs
        log.warning("run %s terminated with status=%s error=%s",
                    run.id, run.status, run.error)
        return None

    # --- token accounting (reference :117-135, same window semantics)

    def get_token_usage(self, tmin: int, tmax: int, limit: int = 20
                        ) -> Dict[str, int]:
        usage = {"prompt_tokens": 0, "completion_tokens": 0, "total_tokens": 0}
        for run in self.service.list_runs(self.thread.id, limit=limit,
                                          order="desc"):
            if (run.created_at is not None and run.completed_at is not None
                    and tmin <= run.created_at < tmax
                    and tmin <= run.completed_at < tmax):
                for k in usage:
                    usage[k] += run.usage[k]
        return usage


# ---------------------------------------------------------------------------
# persistence (session checkpoint/resume)
# ---------------------------------------------------------------------------


def save_service_state(service: AssistantService, path: str) -> None:
    """Persist assistants, threads (full message history) and TERMINAL runs
    to a JSON file.

    The reference kept OpenAI thread/assistant ids in comments so sessions
    could be resumed by ``retrieve_*`` (reference
    find_srckind_metapath_neo4j.py:52-53, generate_query.py:25-29, live use
    bkp_find...:190-192); here the whole store round-trips instead.
    In-flight runs are not persisted (their engine state is not
    serializable mid-decode); callers should drain first.
    """
    import json

    # peek the id counter without consuming (itertools.count can only be
    # advanced, so re-seed it with the observed value)
    next_id = next(service._ids)
    service._ids = itertools.count(next_id)
    state = {
        "next_id": next_id,              # keeps restored ids collision-free
        "assistants": [
            {"id": a.id, "name": a.name, "instructions": a.instructions,
             "model": a.model,
             "gen": {"max_new_tokens": a.gen.max_new_tokens,
                     "stop": list(a.gen.stop),
                     "forced_prefix": a.gen.forced_prefix,
                     "suffix": a.gen.suffix,
                     "grammar": a.gen.grammar}}
            for a in service.assistants.values()
        ],
        "threads": [
            {"id": t.id,
             "messages": [
                 {"id": m.id, "role": m.role, "content": m.raw_content,
                  "created_at": m.created_at}
                 for m in t.messages
             ]}
            for t in service.threads.values()
        ],
        "runs": [
            {"id": r.id, "thread_id": r.thread_id,
             "assistant_id": r.assistant_id, "status": r.status,
             "created_at": r.created_at, "completed_at": r.completed_at,
             "usage": r.usage, "error": r.error,
             "response_message_id": r.response_message_id}
            for r in service.runs.values() if r.status in RunStatus.TERMINAL
        ],
        "thread_runs": service._thread_runs,
    }
    with open(path, "w") as f:
        json.dump(state, f)


def load_service_state(path: str, backend: LMBackend,
                       run_timeout_s: float = 600.0) -> AssistantService:
    """Rebuild an AssistantService from ``save_service_state`` output.

    Restored threads keep their ids, so stage code holding thread/assistant
    ids across a process restart resumes transparently (and
    ``get_token_usage`` windows over past runs still answer correctly).
    """
    import json

    with open(path) as f:
        state = json.load(f)

    service = AssistantService(backend, run_timeout_s=run_timeout_s)
    service._ids = itertools.count(state["next_id"])
    for a in state["assistants"]:
        g = a.get("gen", {})
        gen = GenOptions(
            max_new_tokens=g.get("max_new_tokens", 256),
            stop=tuple(g.get("stop", ())),
            forced_prefix=g.get("forced_prefix", ""),
            suffix=g.get("suffix", ""),
            grammar=g.get("grammar"))
        service.assistants[a["id"]] = Assistant(
            a["id"], a["name"], a["instructions"], a["model"], gen)
    for t in state["threads"]:
        thread = Thread(t["id"], [
            Message(m["id"], m["role"], m["content"], m["created_at"])
            for m in t["messages"]
        ])
        service.threads[thread.id] = thread
    for r in state["runs"]:
        run = Run(r["id"], r["thread_id"], r["assistant_id"],
                  status=r["status"], created_at=r["created_at"],
                  completed_at=r["completed_at"], usage=r["usage"],
                  error=r["error"])
        run.response_message_id = r["response_message_id"]
        service.runs[run.id] = run
    terminal = set(service.runs)
    service._thread_runs = {
        tid: [rid for rid in rids if rid in terminal]
        for tid, rids in state["thread_runs"].items()
    }
    for tid in service.threads:
        service._thread_runs.setdefault(tid, [])
    return service
