"""Crash recovery: rebuild an ``AssistantService`` from its run journal.

Replay contract (the invariants docs/durability.md spells out):

- Every mutation the crashed service ACKNOWLEDGED is in the journal
  (RunJournal.append fsyncs before the mutating method returns), so replay
  reconstructs assistants, threads, messages, and run outcomes exactly.
- A run with a ``run_settle`` record is terminal and is restored AS
  SETTLED — completed, failed, cancelled, and expired runs are never
  re-executed.  In particular a run cancelled before the crash stays
  cancelled; replay cannot resurrect it.
- A run with a ``run_submit`` record but no settle record was in flight
  when the process died.  Its engine state (KV pages, decode position) is
  gone with the process; recovery re-queues it through ``backend.start``
  with the journaled prompt and options — a fresh prefill that the paged
  engine's prefix cache turns into a mostly-HIT path when enabled
  (engine/prefix.py).  Generated-but-unsettled tokens are NOT recovered:
  the run never settled, so nothing was acknowledged to the caller.
- Reconciliation: the sweep output file is the layer of record ABOVE the
  journal (sweeps/run_file.py).  An interrupted run whose thread carries
  an incident already durable in the sweep output is not resubmitted —
  its result exists on disk; re-running it would burn compute to produce
  a record the resumed sweep will skip anyway.  Such runs are marked
  cancelled with an explanatory error.
- The id counter resumes past the highest journaled id, so post-recovery
  ids never collide with pre-crash ids.

What is NOT replayed: engine/backend internals (handles, KV pages — those
die with the process and are rebuilt by resubmission), METRICS counters,
tracer state, and runs whose submission was rejected by the backend
(BudgetError fires before the submit record is written).
"""

from __future__ import annotations

import dataclasses
import itertools
import re
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from k8s_llm_rca_tpu.obs import trace as obs_trace
from k8s_llm_rca_tpu.serve.api import (Assistant, AssistantService, Message,
                                       Run, RunStatus, Thread)
from k8s_llm_rca_tpu.serve.backend import BudgetError, GenOptions
from k8s_llm_rca_tpu.serve.journal import decode_gen, read_journal
from k8s_llm_rca_tpu.utils.logging import METRICS, get_logger

log = get_logger(__name__)

_ID_SUFFIX = re.compile(r"_(\d+)$")


def _id_number(s: Optional[str]) -> int:
    if not s:
        return -1
    m = _ID_SUFFIX.search(s)
    return int(m.group(1)) if m else -1


def recover_service(journal_path: str, backend, run_timeout_s: float = 600.0,
                    clock=None, journal=None,
                    sweep_output: Optional[str] = None,
                    resubmit: bool = True
                    ) -> Tuple[AssistantService, Dict[str, Any]]:
    """Rebuild a service from ``journal_path`` onto a fresh ``backend``.

    ``journal``: the RunJournal the RECOVERED service should keep writing
    to (typically opened on the same path — RunJournal's open already
    dropped any torn tail).  It is attached only after replay, so replayed
    mutations and resubmissions are never journaled twice.

    Returns ``(service, report)`` where report counts what replay saw and
    what was re-queued.
    """
    records, clean_end = read_journal(journal_path)
    svc = AssistantService(backend, run_timeout_s=run_timeout_s, clock=clock)
    interrupted: Dict[str, Dict[str, Any]] = {}   # run id -> submit record
    max_id = -1
    n_messages = 0

    with obs_trace.span("serve.recover.replay", cat="serve",
                        records=len(records)):
        for rec in records:
            kind = rec["kind"]
            max_id = max(max_id, _id_number(rec.get("id")))
            if kind == "create_assistant":
                a = Assistant(rec["id"], rec["name"], rec["instructions"],
                              rec["model"],
                              decode_gen(rec["gen"]) or GenOptions())
                svc.assistants[a.id] = a
            elif kind == "create_thread":
                t = Thread(rec["id"])
                svc.threads[t.id] = t
                svc._thread_runs[t.id] = []
            elif kind == "add_message":
                m = Message(rec["id"], rec["role"], rec["content"],
                            rec["created_at"])
                svc.threads[rec["thread_id"]].messages.append(m)
                n_messages += 1
            elif kind == "run_submit":
                run = Run(rec["id"], rec["thread_id"], rec["assistant_id"],
                          created_at=rec["created_at"],
                          instructions_override=rec["instructions"])
                svc.runs[run.id] = run
                svc._thread_runs[run.thread_id].append(run.id)
                interrupted[run.id] = rec
            elif kind == "run_settle":
                run = svc.runs[rec["id"]]
                run.status = rec["status"]
                run.completed_at = rec["completed_at"]
                run.usage = dict(rec["usage"])
                run.error = rec["error"]
                resp = rec["response"]
                if resp is not None:
                    m = Message(resp["id"], resp["role"], resp["content"],
                                resp["created_at"])
                    svc.threads[run.thread_id].messages.append(m)
                    run.response_message_id = m.id
                    max_id = max(max_id, _id_number(m.id))
                    n_messages += 1
                interrupted.pop(rec["id"], None)
            else:
                raise ValueError(
                    f"unknown journal record kind {kind!r} — refusing to "
                    f"skip a mutation (every replayed record after it "
                    f"would be built on corrupt state)")

        svc._ids = itertools.count(max_id + 1)

        # ---- reconcile interrupted runs against the sweep output file
        reconciled: List[str] = []
        if sweep_output is not None and interrupted:
            from k8s_llm_rca_tpu.sweeps.run_file import scan_output

            durable = set(scan_output(sweep_output)[0])
            for run_id in list(interrupted):
                run = svc.runs[run_id]
                thread = svc.threads[run.thread_id]
                if any(m.raw_content in durable for m in thread.messages
                       if m.role == "user"):
                    run.status = RunStatus.CANCELLED
                    run.completed_at = int((clock or _time).time())
                    run.error = ("reconciled: incident already durable in "
                                 "sweep output")
                    del interrupted[run_id]
                    reconciled.append(run_id)

        # ---- re-queue the runs that never settled (journal order)
        resubmitted: List[str] = []
        failed_resubmit: List[str] = []
        if resubmit:
            now = (clock or _time).time
            for run_id, rec in interrupted.items():
                run = svc.runs[run_id]
                assistant = svc.assistants[run.assistant_id]
                # session = thread id, re-stamped exactly as create_run
                # does: a cluster router recovering the journal re-pins
                # the thread's affinity instead of scattering its runs
                base = decode_gen(rec["gen"]) or assistant.gen
                # deadline re-stamped exactly as create_run does: the
                # resubmitted run carries its priority AND a fresh engine
                # deadline (the journal keeps deadline_s, not the absolute
                # instant — a crash-restart grants the full window again)
                deadline_s = (base.deadline_s if base.deadline_s is not None
                              else run_timeout_s)
                opts = dataclasses.replace(
                    base,
                    assistant_name=assistant.name,
                    session=rec["thread_id"],
                    deadline_s=deadline_s)
                prompt = rec["prompt"]
                run.usage["prompt_tokens"] = backend.count_tokens(prompt)
                run.t_started = now()
                run.deadline = now() + min(run_timeout_s, deadline_s)
                try:
                    run.backend_handle = backend.start(prompt, opts)
                except BudgetError as e:
                    # the REPLAYED budget can shrink (e.g. a smaller
                    # recovery engine); surface it as a failed run rather
                    # than aborting the whole recovery
                    run.status = RunStatus.FAILED
                    run.error = f"resubmit rejected: {e}"
                    run.completed_at = int(now())
                    failed_resubmit.append(run_id)
                    continue
                run.status = RunStatus.IN_PROGRESS
                svc._inflight[run.backend_handle] = run.id
                resubmitted.append(run_id)

    svc._journal = journal
    report = {
        "records": len(records),
        "clean_end": clean_end,
        "assistants": len(svc.assistants),
        "threads": len(svc.threads),
        "messages": n_messages,
        "runs": len(svc.runs),
        "interrupted": len(interrupted) + len(reconciled),
        "resubmitted": resubmitted,
        "reconciled": reconciled,
        "failed_resubmit": failed_resubmit,
    }
    METRICS.inc("serve.recoveries")
    log.info("recovered service from %s: %d records, %d runs, "
             "%d resubmitted, %d reconciled", journal_path, len(records),
             len(svc.runs), len(resubmitted), len(reconciled))
    return svc, report
