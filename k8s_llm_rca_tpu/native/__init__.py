"""ctypes bindings for the native (C++) runtime components in csrc/.

The library builds on demand with the in-image g++ (``ensure_built``); every
consumer degrades gracefully to the pure-Python implementation when no
toolchain is available, so the hermetic test path never hard-requires a
compile.  ``NativePageAllocator`` and ``NativeJsonGrammar`` are drop-in
behind the same interfaces as engine/paged.PageAllocator and
engine/constrain.JsonGrammar; parity is asserted by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from k8s_llm_rca_tpu.utils.logging import get_logger

log = get_logger(__name__)

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, "libk8s_rca_native.so")
_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "csrc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

# status codes (csrc/native.cpp)
OK = 0
ERR_OUT_OF_PAGES = 1
ERR_DOUBLE_FREE = 2
ERR_FOREIGN_PAGE = 3
ERR_TRASH_PAGE = 4
ERR_LEAK = 5
ERR_BAD_ARG = 6
ERR_GRAMMAR_VIOLATION = 7


def _stale() -> bool:
    """True when the .so is missing or older than any csrc/ source."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    try:
        sources = os.listdir(_CSRC_DIR)
    except OSError:
        return False                 # installed without sources: use as-is
    return any(os.path.getmtime(os.path.join(_CSRC_DIR, f)) > lib_mtime
               for f in sources)


def ensure_built() -> bool:
    """Build csrc/ into the package tree if missing or stale; True when a
    current .so is present.  The library is compiled to a process-unique
    temp path and atomically renamed, so concurrent first-builds from
    several processes can't hand each other a half-written file."""
    global _build_failed
    if _build_failed:
        return False
    if not _stale():
        return True
    with _lock:
        if not _stale():
            return True
        tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
        try:
            subprocess.run(["make", "-C", _CSRC_DIR, "-B", f"OUT={tmp}"],
                           check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB_PATH)
        except (OSError, subprocess.SubprocessError) as e:
            log.warning("native build failed, using Python fallbacks: %s", e)
            _build_failed = True
            return False
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    return os.path.exists(_LIB_PATH)


def load_library() -> Optional[ctypes.CDLL]:
    """The loaded library, building it first if necessary; None when
    unavailable (callers fall back to Python)."""
    global _lib
    if _lib is not None:
        return _lib
    if not ensure_built():
        return None
    global _build_failed
    with _lock:
        if _lib is None:
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _configure(lib)
            except OSError as e:     # corrupt/incompatible .so: fall back
                log.warning("native library failed to load: %s", e)
                _build_failed = True
                return None
            _lib = lib
    return _lib


def _configure(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.pagealloc_create.restype = c.c_void_p
    lib.pagealloc_create.argtypes = [c.c_int32]
    lib.pagealloc_destroy.argtypes = [c.c_void_p]
    lib.pagealloc_n_free.restype = c.c_int32
    lib.pagealloc_n_free.argtypes = [c.c_void_p]
    lib.pagealloc_alloc.restype = c.c_int32
    lib.pagealloc_alloc.argtypes = [c.c_void_p, c.c_int32, c.c_int64,
                                    c.POINTER(c.c_int32)]
    lib.pagealloc_free.restype = c.c_int32
    lib.pagealloc_free.argtypes = [c.c_void_p, c.POINTER(c.c_int32),
                                   c.c_int32, c.c_int64]
    lib.pagealloc_transfer.restype = c.c_int32
    lib.pagealloc_transfer.argtypes = [c.c_void_p, c.POINTER(c.c_int32),
                                       c.c_int32, c.c_int64, c.c_int64]
    lib.pagealloc_pages_of.restype = c.c_int32
    lib.pagealloc_pages_of.argtypes = [c.c_void_p, c.c_int64,
                                       c.POINTER(c.c_int32), c.c_int32]
    lib.pagealloc_check.restype = c.c_int32
    lib.pagealloc_check.argtypes = [c.c_void_p]

    lib.jsongram_create.restype = c.c_void_p
    lib.jsongram_destroy.argtypes = [c.c_void_p]
    lib.jsongram_set_vocab.restype = c.c_int32
    lib.jsongram_set_vocab.argtypes = [c.c_void_p, c.c_char_p,
                                       c.POINTER(c.c_int32), c.c_int32]
    lib.jsongram_complete.restype = c.c_int32
    lib.jsongram_complete.argtypes = [c.c_void_p]
    lib.jsongram_can_terminate.restype = c.c_int32
    lib.jsongram_can_terminate.argtypes = [c.c_void_p]
    lib.jsongram_mask.restype = c.c_int32
    lib.jsongram_mask.argtypes = [c.c_void_p, c.POINTER(c.c_uint8)]
    lib.jsongram_advance_token.restype = c.c_int32
    lib.jsongram_advance_token.argtypes = [c.c_void_p, c.c_int32]
    lib.jsongram_accept_char.restype = c.c_int32
    lib.jsongram_accept_char.argtypes = [c.c_void_p, c.c_char]
    lib.jsongram_minimal_completion.restype = c.c_int32
    lib.jsongram_minimal_completion.argtypes = [c.c_void_p, c.c_char_p,
                                                c.c_int32]


def available() -> bool:
    return load_library() is not None


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------


class NativePageAllocator:
    """Drop-in for engine/paged.PageAllocator backed by csrc/native.cpp.
    Raises the same exception types on the same violations."""

    def __init__(self, n_pages: int):
        from k8s_llm_rca_tpu.engine.paged import AllocatorError

        if n_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        lib = load_library()
        if lib is None:
            raise AllocatorError("native library unavailable")
        self._lib = lib
        self.n_pages = n_pages
        self._h = lib.pagealloc_create(np.int32(n_pages))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pagealloc_destroy(h)
            self._h = None

    def _raise(self, status: int) -> None:
        from k8s_llm_rca_tpu.engine.paged import AllocatorError, OutOfPages

        if status == ERR_OUT_OF_PAGES:
            raise OutOfPages(f"pool exhausted ({self.n_free} free)")
        messages = {
            ERR_DOUBLE_FREE: "double free",
            ERR_FOREIGN_PAGE: "page owned by another sequence",
            ERR_TRASH_PAGE: "attempt to free the trash page",
            ERR_LEAK: "leaked or aliased pages",
            ERR_BAD_ARG: "bad argument",
        }
        raise AllocatorError(messages.get(status, f"status {status}"))

    @property
    def n_free(self) -> int:
        return int(self._lib.pagealloc_n_free(self._h))

    def pages_of(self, owner: int) -> List[int]:
        cap = self.n_pages
        out = (ctypes.c_int32 * cap)()
        n = self._lib.pagealloc_pages_of(self._h, np.int64(owner), out, cap)
        return sorted(out[i] for i in range(min(n, cap)))

    def alloc(self, n: int, owner: int) -> List[int]:
        out = (ctypes.c_int32 * max(n, 1))()
        status = self._lib.pagealloc_alloc(self._h, np.int32(n),
                                           np.int64(owner), out)
        if status != OK:
            self._raise(status)
        return [out[i] for i in range(n)]

    def free(self, pages: Sequence[int], owner: int) -> None:
        arr = (ctypes.c_int32 * max(len(pages), 1))(*pages)
        status = self._lib.pagealloc_free(self._h, arr,
                                          np.int32(len(pages)),
                                          np.int64(owner))
        if status != OK:
            self._raise(status)

    def transfer(self, pages: Sequence[int], from_owner: int,
                 to_owner: int) -> None:
        arr = (ctypes.c_int32 * max(len(pages), 1))(*pages)
        status = self._lib.pagealloc_transfer(
            self._h, arr, np.int32(len(pages)), np.int64(from_owner),
            np.int64(to_owner))
        if status != OK:
            self._raise(status)

    def check(self) -> None:
        status = self._lib.pagealloc_check(self._h)
        if status != OK:
            self._raise(status)


class NativeJsonGrammar:
    """Drop-in for engine/constrain.JsonGrammar with the automaton, mask
    computation and minimal-completion logic in C++."""

    def __init__(self, tokenizer):
        from k8s_llm_rca_tpu.engine import constrain

        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.tokenizer = tokenizer
        self.eos_id = tokenizer.eos_id
        self._h = lib.jsongram_create()
        strings = constrain._token_strings(tokenizer)
        # flattened vocab buffer, cached on the tokenizer: grammars are
        # built once per serve request, so the O(V) encode must not repeat
        cached = getattr(tokenizer, "_native_vocab_cache", None)
        if cached is None:
            encoded = [s.encode("utf-8", errors="replace") for s in strings]
            buf = b"".join(encoded)
            offsets = np.zeros((len(strings) + 1,), np.int32)
            np.cumsum([len(e) for e in encoded], out=offsets[1:])
            cached = (buf, offsets)
            tokenizer._native_vocab_cache = cached
        buf, offsets = cached
        self._offsets = offsets            # keep alive for the C side setup
        status = lib.jsongram_set_vocab(
            self._h, buf, offsets.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)), np.int32(len(strings)))
        if status != OK:
            raise RuntimeError(f"set_vocab failed: {status}")
        self._strings = strings
        self._mask_buf = np.zeros((len(strings),), np.uint8)
        # force-close bookkeeping mirrors the Python grammar
        self._char_token: Dict[str, int] = {}
        max_chars = 1
        for t, s in enumerate(strings):
            if len(s) == 1 and s not in self._char_token:
                self._char_token[s] = t
            max_chars = max(max_chars, len(s))
        self._close_margin = 2 + 4 * (max_chars - 1)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.jsongram_destroy(h)
            self._h = None

    @property
    def done(self) -> bool:
        return bool(self._lib.jsongram_complete(self._h))

    def minimal_completion(self) -> str:
        out = ctypes.create_string_buffer(4096)
        n = self._lib.jsongram_minimal_completion(self._h, out, 4096)
        if n < 0:
            raise RuntimeError("minimal completion overflow")
        return out.raw[:n].decode()

    def constraint(self, remaining: Optional[int] = None):
        from k8s_llm_rca_tpu.engine.constrain import Constraint

        if self.done:
            return Constraint(force=self.eos_id)
        if remaining is not None:
            completion = self.minimal_completion()
            if remaining <= len(completion) + self._close_margin:
                if not completion:
                    return Constraint(force=self.eos_id)
                forced = self._char_token.get(completion[0])
                if forced is None:
                    if bool(self._lib.jsongram_can_terminate(self._h)):
                        return Constraint(force=self.eos_id)
                    forced = self.tokenizer.encode(completion[0])[0]
                return Constraint(force=forced)
        n_allowed = self._lib.jsongram_mask(
            self._h, self._mask_buf.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)))
        allow = self._mask_buf.astype(bool)   # fresh array each call
        if bool(self._lib.jsongram_can_terminate(self._h)):
            allow[self.eos_id] = True
            n_allowed += 1
        if n_allowed == 0:
            return Constraint(force=self.eos_id)
        return Constraint(allow=allow)

    def advance(self, token: int) -> None:
        if token == self.eos_id:
            return
        status = self._lib.jsongram_advance_token(self._h, np.int32(token))
        if status != OK:
            raise ValueError(
                f"token {token} ({self._strings[token]!r}) violates the "
                f"JSON grammar")
