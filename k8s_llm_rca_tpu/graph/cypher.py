"""Mini-Cypher interpreter over the in-memory property graph.

Covers the Cypher surface the RCA pipeline actually emits — both the
hand-written queries and the shapes the LLM/deterministic compiler generate
(reference query inventory, SURVEY §2 #3,#4,#6,#10,#11):

- multiple MATCH clauses with shared bindings, comma-separated patterns,
  path assignment ``p = (a)-[*1..3]->(b)``, variable-length and undirected
  relationships, label constraints on nodes and types on relationships;
- WHERE with comparisons, CONTAINS, IN, IS [NOT] NULL, AND/OR/NOT, parens,
  list literals, parameters ($x), property access, list slicing
  ``nodes(path)[1..-1]``, and the quantifiers all/any/single/none
  ``(x IN list WHERE pred)``;
- WITH projection with LIMIT (``WITH evt LIMIT 1``);
- RETURN [DISTINCT] items [AS alias] [ORDER BY ...] [LIMIT n].

Result rows come back as store.Record with the neo4j access styles.
Keywords are case-insensitive (the reference mixes ``MATCH``/``match``,
``CONTAINS``/``contains``); identifiers and labels are case-sensitive
(``Event`` entity vs ``EVENT`` state labels are distinct — reference data
model, SURVEY §1).

Relationship uniqueness follows Cypher trail semantics: a relationship
instance is used at most once per pattern match (this is what makes the
reference's ``*1..3`` ladder terminate on cyclic metagraphs).

Errors raise CypherSyntaxError so the pipeline's retry-with-feedback loop
(test_all.py:109-115) sees the same exception category the neo4j driver
would raise.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from k8s_llm_rca_tpu.graph.store import Graph, Node, Path, Record, Relationship


class CypherSyntaxError(ValueError):
    """Mirror of neo4j.exceptions.CypherSyntaxError for the retry loops."""


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>//[^\n]*)
  | (?P<string>'(?:\\.|[^'\\])*'|"(?:\\.|[^"\\])*")
  | (?P<number>\d+(?:\.\d+)?)
  | (?P<param>\$[A-Za-z_][A-Za-z0-9_]*)
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|<=|>=|<-|->|\.\.|[()\[\]{},;:.\-<>=*+|])
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "match", "where", "with", "return", "limit", "distinct", "and", "or",
    "not", "in", "contains", "as", "order", "by", "is", "null", "asc",
    "desc", "all", "any", "single", "none", "size", "nodes",
    "relationships", "true", "false", "starts", "ends", "optional",
}

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "'": "'", '"': '"', "\\": "\\"}


def _unescape(body: str) -> str:
    out, i = [], 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


@dataclass
class Token:
    kind: str       # 'string' | 'number' | 'param' | 'name' | 'kw' | 'op' | 'eof'
    value: Any
    pos: int


def tokenize(text: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise CypherSyntaxError(
                f"unexpected character {text[pos]!r} at offset {pos}")
        kind = m.lastgroup
        val = m.group()
        if kind not in ("ws", "comment"):
            if kind == "string":
                tokens.append(Token("string", _unescape(val[1:-1]), pos))
            elif kind == "number":
                num = float(val) if "." in val else int(val)
                tokens.append(Token("number", num, pos))
            elif kind == "param":
                tokens.append(Token("param", val[1:], pos))
            elif kind == "name":
                if val.lower() in _KEYWORDS:
                    tokens.append(Token("kw", val.lower(), pos))
                else:
                    tokens.append(Token("name", val, pos))
            else:
                tokens.append(Token("op", val, pos))
        pos = m.end()
    tokens.append(Token("eof", None, pos))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

Expr = Callable[["Ctx"], Any]   # compiled expressions are closures over Ctx


@dataclass
class NodePat:
    var: Optional[str]
    label: Optional[str]


@dataclass
class RelPat:
    var: Optional[str]
    type: Optional[str]
    direction: str              # 'out' | 'in' | 'both'
    min_hops: int = 1
    max_hops: int = 1
    var_length: bool = False


@dataclass
class Pattern:
    path_var: Optional[str]
    nodes: List[NodePat]
    rels: List[RelPat]


@dataclass
class MatchClause:
    patterns: List[Pattern]
    where: Optional[Expr]
    refs: set = field(default_factory=set)    # variables read by WHERE


@dataclass
class WithClause:
    items: List[Tuple[str, Expr]]        # (output name, expr)
    limit: Optional[int]
    refs: set = field(default_factory=set)


@dataclass
class ReturnClause:
    items: List[Tuple[str, Expr]]
    distinct: bool
    order_by: List[Tuple[Expr, bool]]    # (expr, descending)
    limit: Optional[int]
    refs: set = field(default_factory=set)


@dataclass
class Ctx:
    row: Dict[str, Any]
    params: Dict[str, Any]


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, tokens: List[Token], source: str):
        self.toks = tokens
        self.i = 0
        self.source = source
        self._refs: List[set] = [set()]   # variable-reference scope stack

    # -- token helpers

    def peek(self, offset: int = 0) -> Token:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            raise CypherSyntaxError(
                f"expected {kw.upper()} at offset {self.peek().pos}, "
                f"got {self.peek().value!r}")
        return self.next()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise CypherSyntaxError(
                f"expected {op!r} at offset {self.peek().pos}, "
                f"got {self.peek().value!r}")
        return self.next()

    def expect_name(self) -> str:
        t = self.peek()
        if t.kind != "name":
            raise CypherSyntaxError(
                f"expected identifier at offset {t.pos}, got {t.value!r}")
        return self.next().value

    def expect_int(self) -> int:
        t = self.peek()
        if t.kind != "number" or not isinstance(t.value, int):
            raise CypherSyntaxError(
                f"expected integer literal at offset {t.pos}, "
                f"got {t.value!r}")
        return self.next().value

    def slice_text(self, start_tok: Token, end_tok: Token) -> str:
        return self.source[start_tok.pos:end_tok.pos].strip()

    # -- top level

    def parse(self) -> List[Any]:
        clauses: List[Any] = []
        while not self.peek().kind == "eof":
            if self.at_op(";"):
                self.next()
                continue
            if self.at_kw("optional"):
                raise CypherSyntaxError("OPTIONAL MATCH is not supported")
            if self.at_kw("match"):
                clauses.append(self.parse_match())
            elif self.at_kw("with"):
                clauses.append(self.parse_with())
            elif self.at_kw("return"):
                clauses.append(self.parse_return())
            else:
                raise CypherSyntaxError(
                    f"expected MATCH/WITH/RETURN at offset {self.peek().pos}, "
                    f"got {self.peek().value!r}")
        if not clauses or not isinstance(clauses[-1], ReturnClause):
            raise CypherSyntaxError("query must end with a RETURN clause")
        self._check_scopes(clauses)
        return clauses

    def _check_scopes(self, clauses: List[Any]) -> None:
        """Plan-time variable scoping: undefined names fail even on queries
        that would match zero rows (the neo4j behavior the retry loop needs)."""
        defined: set = set()
        for clause in clauses:
            if isinstance(clause, MatchClause):
                for p in clause.patterns:
                    if p.path_var:
                        defined.add(p.path_var)
                    defined.update(n.var for n in p.nodes if n.var)
                    defined.update(r.var for r in p.rels if r.var)
                missing = clause.refs - defined
            elif isinstance(clause, WithClause):
                missing = clause.refs - defined
                defined = {name for name, _ in clause.items}
            else:
                missing = clause.refs - defined
            if missing:
                raise CypherSyntaxError(
                    f"variable(s) {sorted(missing)} not defined")

    # -- clauses

    def parse_match(self) -> MatchClause:
        self.expect_kw("match")
        patterns = [self.parse_pattern()]
        while self.at_op(","):
            self.next()
            patterns.append(self.parse_pattern())
        where = None
        self._refs.append(set())
        if self.at_kw("where"):
            self.next()
            where = self.parse_expr()
        return MatchClause(patterns, where, refs=self._refs.pop())

    def parse_with(self) -> WithClause:
        self.expect_kw("with")
        self._refs.append(set())
        items = self.parse_items()
        limit = None
        if self.at_kw("limit"):
            self.next()
            limit = self.expect_int()
        return WithClause(items, limit, refs=self._refs.pop())

    def parse_return(self) -> ReturnClause:
        self.expect_kw("return")
        distinct = False
        if self.at_kw("distinct"):
            self.next()
            distinct = True
        self._refs.append(set())
        items = self.parse_items()
        order_by: List[Tuple[Expr, bool]] = []
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.at_kw("asc"):
                    self.next()
                elif self.at_kw("desc"):
                    self.next()
                    desc = True
                order_by.append((e, desc))
                if self.at_op(","):
                    self.next()
                    continue
                break
        limit = None
        if self.at_kw("limit"):
            self.next()
            limit = self.expect_int()
        return ReturnClause(items, distinct, order_by, limit,
                            refs=self._refs.pop())

    def parse_items(self) -> List[Tuple[str, Expr]]:
        items: List[Tuple[str, Expr]] = []
        while True:
            start = self.peek()
            expr = self.parse_expr()
            end = self.peek()
            name = self.slice_text(start, end)
            if self.at_kw("as"):
                self.next()
                name = self.expect_name()
            items.append((name, expr))
            if self.at_op(","):
                self.next()
                continue
            break
        return items

    # -- patterns

    def parse_pattern(self) -> Pattern:
        path_var = None
        if (self.peek().kind == "name" and self.peek(1).kind == "op"
                and self.peek(1).value == "=" and self.peek(2).kind == "op"
                and self.peek(2).value == "("):
            path_var = self.next().value
            self.next()  # '='
        nodes = [self.parse_node_pat()]
        rels: List[RelPat] = []
        while self.at_op("-", "<-"):
            rels.append(self.parse_rel_pat())
            nodes.append(self.parse_node_pat())
        return Pattern(path_var, nodes, rels)

    def parse_node_pat(self) -> NodePat:
        self.expect_op("(")
        var = label = None
        if self.peek().kind == "name":
            var = self.next().value
        if self.at_op(":"):
            self.next()
            label = self.expect_name()
        self.expect_op(")")
        return NodePat(var, label)

    def parse_rel_pat(self) -> RelPat:
        direction = "both"
        if self.at_op("<-"):
            self.next()
            direction = "in"
        else:
            self.expect_op("-")
        var = rtype = None
        min_hops = max_hops = 1
        var_length = False
        if self.at_op("["):
            self.next()
            if self.peek().kind == "name":
                var = self.next().value
            if self.at_op(":"):
                self.next()
                rtype = self.expect_name()
            if self.at_op("*"):
                self.next()
                var_length = True
                min_hops, max_hops = 1, 3
                if self.peek().kind == "number":
                    min_hops = self.expect_int()
                    max_hops = min_hops
                    if self.at_op(".."):
                        self.next()
                        max_hops = self.expect_int()
                elif self.at_op(".."):
                    self.next()
                    max_hops = self.expect_int()
            self.expect_op("]")
        if self.at_op("->"):
            if direction == "in":
                raise CypherSyntaxError("relationship has both directions")
            self.next()
            direction = "out"
        elif self.at_op("-"):
            self.next()
        else:
            raise CypherSyntaxError(
                f"unterminated relationship at offset {self.peek().pos}")
        return RelPat(var, rtype, direction, min_hops, max_hops, var_length)

    # -- expressions

    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.at_kw("or"):
            self.next()
            right = self.parse_and()
            l, r = left, right
            left = lambda ctx, l=l, r=r: bool(l(ctx)) or bool(r(ctx))
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.at_kw("and"):
            self.next()
            right = self.parse_not()
            l, r = left, right
            left = lambda ctx, l=l, r=r: bool(l(ctx)) and bool(r(ctx))
        return left

    def parse_not(self) -> Expr:
        if self.at_kw("not"):
            self.next()
            inner = self.parse_not()
            return lambda ctx, e=inner: not bool(e(ctx))
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        left = self.parse_postfix()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "<", ">", "<=", ">="):
            op = self.next().value
            right = self.parse_postfix()
            return _compare(op, left, right)
        if self.at_kw("contains"):
            self.next()
            right = self.parse_postfix()
            return lambda ctx, l=left, r=right: (
                isinstance(l(ctx), str) and isinstance(r(ctx), str)
                and r(ctx) in l(ctx))
        if self.at_kw("starts"):
            self.next()
            self.expect_kw("with")
            right = self.parse_postfix()
            return lambda ctx, l=left, r=right: (
                isinstance(l(ctx), str) and isinstance(r(ctx), str)
                and l(ctx).startswith(r(ctx)))
        if self.at_kw("ends"):
            self.next()
            self.expect_kw("with")
            right = self.parse_postfix()
            return lambda ctx, l=left, r=right: (
                isinstance(l(ctx), str) and isinstance(r(ctx), str)
                and l(ctx).endswith(r(ctx)))
        if self.at_kw("in"):
            self.next()
            right = self.parse_postfix()
            def _in(ctx, l=left, r=right):
                lv, rv = l(ctx), r(ctx)
                if rv is None or not isinstance(rv, (list, tuple)):
                    return False
                return lv in rv
            return _in
        if self.at_kw("is"):
            self.next()
            negate = False
            if self.at_kw("not"):
                self.next()
                negate = True
            self.expect_kw("null")
            return lambda ctx, l=left, n=negate: (l(ctx) is not None) if n \
                else (l(ctx) is None)
        return left

    def parse_postfix(self) -> Expr:
        expr = self.parse_primary()
        while True:
            if self.at_op("."):
                self.next()
                key = self.expect_name()
                def _prop(ctx, e=expr, k=key):
                    obj = e(ctx)
                    if obj is None:
                        return None
                    if isinstance(obj, (Node, Relationship)):
                        return obj[k]
                    if isinstance(obj, dict):
                        return obj.get(k)
                    raise CypherSyntaxError(
                        f"cannot access property {k!r} on {type(obj).__name__}")
                expr = _prop
            elif self.at_op("["):
                self.next()
                # index or slice [a..b] where either side optional
                lo = hi = None
                is_slice = False
                if not self.at_op(".."):
                    lo = self.parse_expr()
                if self.at_op(".."):
                    self.next()
                    is_slice = True
                    if not self.at_op("]"):
                        hi = self.parse_expr()
                self.expect_op("]")
                def _index(ctx, e=expr, lo=lo, hi=hi, is_slice=is_slice):
                    seq = e(ctx)
                    if seq is None:
                        return None
                    if is_slice:
                        lov = lo(ctx) if lo is not None else None
                        hiv = hi(ctx) if hi is not None else None
                        return list(seq)[lov:hiv]
                    return seq[lo(ctx)]
                expr = _index
            else:
                return expr

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "string" or t.kind == "number":
            self.next()
            return lambda ctx, v=t.value: v
        if t.kind == "param":
            self.next()
            return lambda ctx, name=t.value: ctx.params.get(name)
        if t.kind == "op" and t.value == "-":       # unary minus (e.g. [1..-1])
            self.next()
            inner = self.parse_primary()
            return lambda ctx, e=inner: -e(ctx)
        if t.kind == "op" and t.value == "(":
            self.next()
            inner = self.parse_expr()
            self.expect_op(")")
            return inner
        if t.kind == "op" and t.value == "[":
            self.next()
            elems: List[Expr] = []
            while not self.at_op("]"):
                elems.append(self.parse_expr())
                if self.at_op(","):
                    self.next()
            self.expect_op("]")
            return lambda ctx, es=tuple(elems): [e(ctx) for e in es]
        if t.kind == "kw":
            if t.value == "null":
                self.next()
                return lambda ctx: None
            if t.value == "true":
                self.next()
                return lambda ctx: True
            if t.value == "false":
                self.next()
                return lambda ctx: False
            if t.value in ("all", "any", "single", "none"):
                return self.parse_quantifier()
            if t.value in ("size", "nodes", "relationships"):
                fn = t.value
                self.next()
                self.expect_op("(")
                arg = self.parse_expr()
                self.expect_op(")")
                return _builtin(fn, arg)
        if t.kind == "name":
            name = self.next().value
            if self.at_op("(") :
                raise CypherSyntaxError(f"unknown function {name!r}")
            self._refs[-1].add(name)
            def _var(ctx, n=name):
                if n not in ctx.row:
                    raise CypherSyntaxError(f"variable {n!r} not defined")
                return ctx.row[n]
            return _var
        raise CypherSyntaxError(
            f"unexpected token {t.value!r} at offset {t.pos}")

    def parse_quantifier(self) -> Expr:
        kind = self.next().value            # all | any | single | none
        self.expect_op("(")
        var = self.expect_name()
        self.expect_kw("in")
        list_expr = self.parse_expr()
        self.expect_kw("where")
        self._refs.append(set())            # quantifier var is locally bound
        pred = self.parse_expr()
        inner_refs = self._refs.pop()
        self._refs[-1].update(inner_refs - {var})
        self.expect_op(")")

        def _quant(ctx, kind=kind, var=var, list_expr=list_expr, pred=pred):
            seq = list_expr(ctx)
            if seq is None:
                return False
            hits = 0
            for item in seq:
                inner = Ctx({**ctx.row, var: item}, ctx.params)
                if bool(pred(inner)):
                    hits += 1
            if kind == "all":
                return hits == len(list(seq))
            if kind == "any":
                return hits >= 1
            if kind == "none":
                return hits == 0
            return hits == 1                 # single
        return _quant


def _compare(op: str, left: Expr, right: Expr) -> Expr:
    def cmp(ctx):
        lv, rv = left(ctx), right(ctx)
        if op == "=":
            return lv == rv if lv is not None and rv is not None else False
        if op == "<>":
            return lv != rv if lv is not None and rv is not None else False
        if lv is None or rv is None:
            return False
        if isinstance(lv, bool) or isinstance(rv, bool):
            return False
        if isinstance(lv, str) != isinstance(rv, str):
            return False                     # mixed-type ordering is null
        if op == "<":
            return lv < rv
        if op == ">":
            return lv > rv
        if op == "<=":
            return lv <= rv
        return lv >= rv
    return cmp


def _builtin(fn: str, arg: Expr) -> Expr:
    def call(ctx):
        v = arg(ctx)
        if v is None:
            return None
        if fn == "size":
            return len(v)
        if fn == "nodes":
            if not isinstance(v, Path):
                raise CypherSyntaxError("nodes() expects a path")
            return list(v.nodes)
        if fn == "relationships":
            if not isinstance(v, Path):
                raise CypherSyntaxError("relationships() expects a path")
            return list(v.relationships)
        raise CypherSyntaxError(f"unknown function {fn!r}")
    return call


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


def _match_pattern(graph: Graph, pattern: Pattern, row: Dict[str, Any],
                   params: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All extensions of ``row`` that satisfy one pattern (trail semantics)."""
    results: List[Dict[str, Any]] = []

    def node_candidates(pat: NodePat, bound: Dict[str, Any]) -> List[Node]:
        if pat.var is not None and pat.var in bound:
            n = bound[pat.var]
            if not isinstance(n, Node):
                raise CypherSyntaxError(
                    f"variable {pat.var!r} is not a node")
            if pat.label is not None and pat.label not in n.labels:
                return []
            return [n]
        return graph.nodes_with_label(pat.label)

    def bind_node(pat: NodePat, node: Node, bound: Dict[str, Any]
                  ) -> Optional[Dict[str, Any]]:
        if pat.label is not None and pat.label not in node.labels:
            return None
        if pat.var is None:
            return bound
        if pat.var in bound:
            return bound if bound[pat.var] == node else None
        new = dict(bound)
        new[pat.var] = node
        return new

    def rel_steps(node: Node, rel_pat: RelPat):
        """(relationship, neighbor) pairs leaving ``node`` under rel_pat.

        An undirected pattern traverses a SELF-LOOP once, not once per
        orientation (Neo4j/openCypher loop semantics; found by the
        brute-force differential oracle, tests/test_cypher_differential
        .py — the out pass already yielded the loop, so the in pass must
        skip it or every loop row would double)."""
        steps = []
        if rel_pat.direction in ("out", "both"):
            for r in graph.out_rels(node):
                steps.append((r, r.end_node))
        if rel_pat.direction in ("in", "both"):
            for r in graph.in_rels(node):
                if rel_pat.direction == "both" \
                        and r.start_node is r.end_node:
                    continue
                steps.append((r, r.start_node))
        if rel_pat.type is not None:
            steps = [(r, n) for (r, n) in steps if r.type == rel_pat.type]
        return steps

    def extend(i: int, node: Node, bound: Dict[str, Any],
               path_nodes: List[Node], path_rels: List[Relationship],
               used: frozenset) -> None:
        if i == len(pattern.rels):
            final = bound
            if pattern.path_var is not None:
                final = dict(final)
                final[pattern.path_var] = Path(path_nodes, path_rels)
            results.append(final)
            return
        rel_pat = pattern.rels[i]
        next_pat = pattern.nodes[i + 1]
        if not rel_pat.var_length:
            for rel, nbr in rel_steps(node, rel_pat):
                if rel.element_id in used:
                    continue
                nb = bind_node(next_pat, nbr, bound)
                if nb is None:
                    continue
                if rel_pat.var is not None:
                    if rel_pat.var in nb and nb[rel_pat.var] != rel:
                        continue
                    nb = dict(nb)
                    nb[rel_pat.var] = rel
                extend(i + 1, nbr, nb, path_nodes + [nbr], path_rels + [rel],
                       used | {rel.element_id})
        else:
            # enumerate trails of length min..max from ``node``
            def walk(cur: Node, hops: int, trail_nodes: List[Node],
                     trail_rels: List[Relationship], wused: frozenset) -> None:
                if rel_pat.min_hops <= hops:
                    nb = bind_node(next_pat, cur, bound)
                    if nb is not None:
                        if rel_pat.var is not None:
                            nb = dict(nb)
                            nb[rel_pat.var] = list(trail_rels[-hops:] if hops
                                                   else [])
                        extend(i + 1, cur, nb, trail_nodes, trail_rels, wused)
                if hops >= rel_pat.max_hops:
                    return
                for rel, nbr in rel_steps(cur, rel_pat):
                    if rel.element_id in wused:
                        continue
                    walk(nbr, hops + 1, trail_nodes + [nbr],
                         trail_rels + [rel], wused | {rel.element_id})

            walk(node, 0, path_nodes, path_rels, used)

    first = pattern.nodes[0]
    for start in node_candidates(first, row):
        bound = bind_node(first, start, row)
        if bound is None:
            continue
        extend(0, start, bound, [start], [], frozenset())
    return results


def _dedup_key(value: Any) -> Any:
    if isinstance(value, list):
        return tuple(_dedup_key(v) for v in value)
    return value


def run_query(graph: Graph, query: str,
              parameters: Optional[Dict[str, Any]] = None) -> List[Record]:
    """Parse + execute; returns a list of Records (eagerly materialized, like
    the reference executor's list(result) — neo4j_query_executor.py:15-24)."""
    params = parameters or {}
    clauses = _Parser(tokenize(query), query).parse()

    rows: List[Dict[str, Any]] = [{}]
    for clause in clauses:
        if isinstance(clause, MatchClause):
            for pattern in clause.patterns:
                new_rows: List[Dict[str, Any]] = []
                for row in rows:
                    new_rows.extend(_match_pattern(graph, pattern, row, params))
                rows = new_rows
            if clause.where is not None:
                rows = [r for r in rows
                        if bool(clause.where(Ctx(r, params)))]
        elif isinstance(clause, WithClause):
            projected = []
            for row in rows:
                ctx = Ctx(row, params)
                projected.append(
                    {name: expr(ctx) for name, expr in clause.items})
            rows = projected
            if clause.limit is not None:
                rows = rows[: clause.limit]
        elif isinstance(clause, ReturnClause):
            records: List[Record] = []
            keys = [name for name, _ in clause.items]
            evaluated: List[Tuple[List[Any], Dict[str, Any]]] = []
            for row in rows:
                ctx = Ctx(row, params)
                evaluated.append(([expr(ctx) for _, expr in clause.items], row))
            if clause.order_by:
                # stable multi-key sort: precompute each key once per row
                for e, desc in reversed(clause.order_by):
                    keyed = []
                    for pair in evaluated:
                        v = e(Ctx(pair[1], params))
                        keyed.append(((v is None, v), pair))
                    keyed.sort(key=lambda kv: kv[0], reverse=desc)
                    evaluated = [pair for _, pair in keyed]
            seen = set()
            for values, _ in evaluated:
                if clause.distinct:
                    key = tuple(_dedup_key(v) for v in values)
                    if key in seen:
                        continue
                    seen.add(key)
                records.append(Record(keys, values))
                if clause.limit is not None and len(records) >= clause.limit:
                    break
            return records
    raise CypherSyntaxError("query must end with RETURN")  # pragma: no cover
