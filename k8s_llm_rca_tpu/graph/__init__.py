from k8s_llm_rca_tpu.graph.store import (  # noqa: F401
    Graph, Node, Relationship, Path, Record,
)
from k8s_llm_rca_tpu.graph.executor import (  # noqa: F401
    GraphQueryExecutor, InMemoryGraphExecutor, Neo4jQueryExecutor,
    CypherSyntaxError,
)
