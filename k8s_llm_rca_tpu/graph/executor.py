"""Graph query executors: one protocol, two backends.

``InMemoryGraphExecutor`` runs the mini-Cypher interpreter over a canned
Graph — the hermetic default.  ``Neo4jQueryExecutor`` is a thin param-safe
bolt client equivalent to the reference's (common/neo4j_query_executor.py:6-24),
import-gated so the hermetic path never touches the neo4j driver.

Both backends carry the same fault-injection point (``faults/inject.py``):
when a FaultPlan is armed, each ``run_query`` polls its ``fault_site``
before executing, so a chaos run can schedule Neo4j failures, timeouts,
slow calls, empty result sets, and poisoned payloads deterministically.
Disarmed, the check is a single module-attribute ``is None`` test.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Protocol

from k8s_llm_rca_tpu.faults import inject
from k8s_llm_rca_tpu.graph import cypher
from k8s_llm_rca_tpu.graph.cypher import CypherSyntaxError  # noqa: F401 (re-export)
from k8s_llm_rca_tpu.graph.store import Graph, Record
from k8s_llm_rca_tpu.obs import trace as obs_trace


class GraphQueryExecutor(Protocol):
    def run_query(self, query: str,
                  parameters: Optional[Dict[str, Any]] = None) -> List[Record]: ...
    def close(self) -> None: ...


class InMemoryGraphExecutor:
    def __init__(self, graph: Graph, fault_site: str = inject.SITE_GRAPH):
        self.graph = graph
        self.fault_site = fault_site

    @classmethod
    def from_dump_file(cls, path: str) -> "InMemoryGraphExecutor":
        return cls(Graph.load(path))

    def run_query(self, query: str,
                  parameters: Optional[Dict[str, Any]] = None) -> List[Record]:
        with obs_trace.span("graph.query", cat="graph",
                            site=self.fault_site, query=query[:80]):
            if inject._ARMED is not None:
                fault = inject._ARMED.poll(self.fault_site)
                if fault is not None:
                    return inject.apply_query_fault(
                        fault, inject._ARMED,
                        lambda: cypher.run_query(self.graph, query,
                                                 parameters))
            return cypher.run_query(self.graph, query, parameters)

    def close(self) -> None:
        pass


class Neo4jQueryExecutor:
    """Bolt client matching the reference's executor surface: eager
    ``run_query`` returning list(records), ``close``, connectivity verified
    at construction (reference :7-9,15-24)."""

    def __init__(self, uri: str, user: str, password: str,
                 fault_site: str = inject.SITE_GRAPH):
        from neo4j import GraphDatabase  # deferred: optional dependency

        self.driver = GraphDatabase.driver(uri, auth=(user, password))
        self.driver.verify_connectivity()
        self.fault_site = fault_site

    def _run(self, query: str, parameters: Optional[Dict[str, Any]]):
        with self.driver.session() as session:
            return list(session.run(query, parameters))

    def run_query(self, query: str,
                  parameters: Optional[Dict[str, Any]] = None):
        with obs_trace.span("graph.query", cat="graph",
                            site=self.fault_site, query=query[:80]):
            if inject._ARMED is not None:
                fault = inject._ARMED.poll(self.fault_site)
                if fault is not None:
                    return inject.apply_query_fault(
                        fault, inject._ARMED,
                        lambda: self._run(query, parameters))
            return self._run(query, parameters)

    def close(self) -> None:
        self.driver.close()
