"""In-memory property graph.

The reference stores all graph data in two external Neo4j servers reached
over bolt (common/neo4j_query_executor.py; hardcoded IPs test_all.py:21-22)
and ships no fixtures, so nothing is testable offline (SURVEY §4).  This
store is the hermetic backend: the same node/relationship/path/record shapes
the neo4j driver exposes — stage code written against neo4j records runs
unchanged — plus JSON dump save/load so test fixtures are canned data, not a
live cluster.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple


class Node:
    """Property node.  Subscript access returns None for missing keys, like
    the neo4j driver's Node (reference relies on this: message_compatible
    probes dest['isNative'] etc. — generate_query/generate_query.py:112-127)."""

    __slots__ = ("element_id", "labels", "properties")

    def __init__(self, element_id: int, labels: Iterable[str],
                 properties: Dict[str, Any]):
        self.element_id = element_id
        self.labels = frozenset(labels)
        self.properties = dict(properties)

    def __getitem__(self, key: str) -> Any:
        return self.properties.get(key)

    def get(self, key: str, default: Any = None) -> Any:
        return self.properties.get(key, default)

    def keys(self):
        return self.properties.keys()

    def items(self):
        return self.properties.items()

    def __eq__(self, other) -> bool:
        return isinstance(other, Node) and other.element_id == self.element_id

    def __hash__(self) -> int:
        return hash(("node", self.element_id))

    def __repr__(self) -> str:
        return f"Node<{self.element_id} {set(self.labels)} {self.properties}>"


class Relationship:
    __slots__ = ("element_id", "type", "start_node", "end_node", "properties")

    def __init__(self, element_id: int, type_: str, start: Node, end: Node,
                 properties: Dict[str, Any]):
        self.element_id = element_id
        self.type = type_
        self.start_node = start
        self.end_node = end
        self.properties = dict(properties)

    def __getitem__(self, key: str) -> Any:
        return self.properties.get(key)

    def keys(self):
        return self.properties.keys()

    def items(self):
        return self.properties.items()

    def __eq__(self, other) -> bool:
        return isinstance(other, Relationship) and other.element_id == self.element_id

    def __hash__(self) -> int:
        return hash(("rel", self.element_id))

    def __repr__(self) -> str:
        return (f"Rel<{self.element_id} {self.type} "
                f"{self.start_node.element_id}->{self.end_node.element_id}>")


class Path:
    """len(path) == number of relationships, matching the neo4j driver
    (the reference's shortest-metapath pruning depends on it:
    find_metapath/find_srckind_metapath_neo4j.py:152-154)."""

    __slots__ = ("nodes", "relationships")

    def __init__(self, nodes: Sequence[Node], relationships: Sequence[Relationship]):
        self.nodes = tuple(nodes)
        self.relationships = tuple(relationships)

    def __len__(self) -> int:
        return len(self.relationships)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Path) and other.nodes == self.nodes
                and other.relationships == self.relationships)

    def __hash__(self) -> int:
        return hash((self.nodes, self.relationships))

    def __repr__(self) -> str:
        return f"Path<{[n['kind'] for n in self.nodes]}>"


class Record:
    """Query result row: indexable by position and by key, iterates over
    values — all three access styles the reference uses
    (record['n2.kind2'], record[len(record)-1], `for ele in record`)."""

    __slots__ = ("_keys", "_values")

    def __init__(self, keys: Sequence[str], values: Sequence[Any]):
        self._keys = list(keys)
        self._values = list(values)

    def __getitem__(self, key) -> Any:
        if isinstance(key, int):
            return self._values[key]
        return self._values[self._keys.index(key)]

    def __iter__(self) -> Iterator[Any]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def keys(self) -> List[str]:
        return list(self._keys)

    def values(self) -> List[Any]:
        return list(self._values)

    def data(self) -> Dict[str, Any]:
        return dict(zip(self._keys, self._values))

    def __repr__(self) -> str:
        return f"Record<{self.data()}>"


class Graph:
    """Mutable property graph with adjacency indexes."""

    def __init__(self):
        self._next_id = 0
        self.nodes: List[Node] = []
        self.relationships: List[Relationship] = []
        self._out: Dict[int, List[Relationship]] = {}
        self._in: Dict[int, List[Relationship]] = {}

    def add_node(self, labels: Iterable[str] = (), **properties) -> Node:
        node = Node(self._next_id, labels, properties)
        self._next_id += 1
        self.nodes.append(node)
        self._out[node.element_id] = []
        self._in[node.element_id] = []
        return node

    def add_relationship(self, start: Node, type_: str, end: Node,
                         **properties) -> Relationship:
        rel = Relationship(self._next_id, type_, start, end, properties)
        self._next_id += 1
        self.relationships.append(rel)
        self._out[start.element_id].append(rel)
        self._in[end.element_id].append(rel)
        return rel

    def out_rels(self, node: Node) -> List[Relationship]:
        return self._out.get(node.element_id, [])

    def in_rels(self, node: Node) -> List[Relationship]:
        return self._in.get(node.element_id, [])

    def nodes_with_label(self, label: Optional[str]) -> List[Node]:
        if label is None:
            return list(self.nodes)
        return [n for n in self.nodes if label in n.labels]

    # ------------------------------------------------------------ dump I/O

    def to_dump(self) -> Dict[str, Any]:
        return {
            "nodes": [
                {"id": n.element_id, "labels": sorted(n.labels),
                 "properties": n.properties}
                for n in self.nodes
            ],
            "relationships": [
                {"id": r.element_id, "type": r.type,
                 "start": r.start_node.element_id, "end": r.end_node.element_id,
                 "properties": r.properties}
                for r in self.relationships
            ],
        }

    @classmethod
    def from_dump(cls, dump: Dict[str, Any]) -> "Graph":
        g = cls()
        by_id: Dict[int, Node] = {}
        for nd in dump["nodes"]:
            node = Node(nd["id"], nd["labels"], nd["properties"])
            g.nodes.append(node)
            g._out[node.element_id] = []
            g._in[node.element_id] = []
            by_id[nd["id"]] = node
            g._next_id = max(g._next_id, nd["id"] + 1)
        for rd in dump["relationships"]:
            rel = Relationship(rd["id"], rd["type"], by_id[rd["start"]],
                               by_id[rd["end"]], rd["properties"])
            g.relationships.append(rel)
            g._out[rel.start_node.element_id].append(rel)
            g._in[rel.end_node.element_id].append(rel)
            g._next_id = max(g._next_id, rd["id"] + 1)
        return g

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dump(), f, indent=1)

    @classmethod
    def load(cls, path: str) -> "Graph":
        with open(path) as f:
            return cls.from_dump(json.load(f))
