"""Canned k8s metagraph + stategraph fixtures.

The reference has no offline fixtures at all — every run needs two live
Neo4j servers holding a Dec-2020 cluster dump that never shipped (SURVEY §4).
This module reconstructs an equivalent *synthetic* cluster implementing the
same data model (SURVEY §1 "Data model"):

- metagraph: one node per resource kind (``category`` =
  NativeEntity/ExternalEntity), edges typed HasEvent/ReferInternal/
  UseExternal carrying ``srcKind``/``destKind``/``key``;
- stategraph: lower-case entity nodes (kind/kind2/tag/id/isNative/isAtomic +
  the per-type name key name2|val|path|containerName|imageName), ``Event``
  entities linked to upper-case ``EVENT`` records via HasEvent(metadata_uid)
  and to the involved entity via ReferInternal(involvedObject_uid), and
  upper-case STATE nodes reached through HasState edges carrying the
  ``[tmin, tmax)`` validity interval.

Four incident scenarios cover the pipeline's distinct control paths:
missing-STATE audits (Secret, nfs), a healthy-but-misconfigured STATE
(ResourceQuota exhausted), the via-Namespace metapath rung, the undirected
rung (PV->PVC points against the Pod->PVC flow), and a decoy record for the
message-compatibility filter.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List

from k8s_llm_rca_tpu.graph.store import Graph, Node

TS_EVENT = "2020-12-11 06:35:02.011"
TS_STATE_MIN = "2020-12-11 06:00:00.000"
TS_STATE_MAX = "2020-12-11 07:00:00.000"

NATIVE_KINDS = [
    "ConfigMap", "CronJob", "Deployment", "Job", "Namespace", "Node",
    "PersistentVolume", "PersistentVolumeClaim", "Pod", "ReplicaSet",
    "ResourceQuota", "Secret", "Service", "ServiceAccount", "StatefulSet",
]
EXTERNAL_KINDS = ["container", "hostPath", "image", "nfs"]

# (type, srcKind, destKind, key)
META_EDGES = [
    ("ReferInternal", "Pod", "Secret", "spec_volumes_secret_secretName"),
    ("ReferInternal", "Pod", "ConfigMap", "spec_volumes_configMap_name"),
    ("ReferInternal", "Pod", "PersistentVolumeClaim",
     "spec_volumes_persistentVolumeClaim_claimName"),
    ("ReferInternal", "PersistentVolume", "PersistentVolumeClaim",
     "spec_claimRef_uid"),
    ("ReferInternal", "Pod", "ServiceAccount", "spec_serviceAccountName"),
    ("ReferInternal", "Pod", "Node", "spec_nodeName"),
    ("ReferInternal", "Job", "Pod", "metadata_ownerReferences_uid"),
    ("ReferInternal", "CronJob", "Job", "metadata_ownerReferences_uid"),
    ("ReferInternal", "StatefulSet", "Pod", "metadata_ownerReferences_uid"),
    ("ReferInternal", "Pod", "Namespace", "metadata_namespace"),
    ("ReferInternal", "CronJob", "Namespace", "metadata_namespace"),
    ("ReferInternal", "ResourceQuota", "Namespace", "metadata_namespace"),
    ("UseExternal", "PersistentVolume", "nfs", "spec_nfs_path"),
    ("UseExternal", "PersistentVolume", "hostPath", "spec_hostPath_path"),
    ("UseExternal", "Pod", "container", "spec_containers_name"),
    ("UseExternal", "container", "image", "image"),
]


def build_metagraph() -> Graph:
    g = Graph()
    by_kind: Dict[str, Node] = {}
    for kind in NATIVE_KINDS:
        by_kind[kind] = g.add_node([kind], kind=kind, category="NativeEntity")
    for kind in EXTERNAL_KINDS:
        by_kind[kind] = g.add_node([kind], kind=kind, category="ExternalEntity")
    # Event participates in the graph but is excluded from the planning
    # vocabulary (the ladder also bars it from paths explicitly).
    by_kind["Event"] = g.add_node(["Event"], kind="Event", category="EventEntity")
    for type_, src, dest, key in META_EDGES:
        g.add_relationship(by_kind[src], type_, by_kind[dest],
                           srcKind=src, destKind=dest, key=key)
    return g


# ---------------------------------------------------------------------------
# incident corpus
# ---------------------------------------------------------------------------


@dataclass
class Incident:
    name: str
    message: str
    src_kind: str
    dest_kind: str
    relevant: List[str]
    # what a correct end-to-end run should surface
    expect_missing_state: List[str] = field(default_factory=list)
    expect_state_kinds: List[str] = field(default_factory=list)


INCIDENTS = [
    Incident(
        name="secret-not-found",
        message=('MountVolume.SetUp failed for volume "es-account-token" : '
                 'secret "es-account-token" not found'),
        src_kind="Pod",
        dest_kind="Secret",
        relevant=["Pod", "Secret"],
        expect_missing_state=["Secret"],
        expect_state_kinds=["Pod"],
    ),
    Incident(
        name="configmap-not-found",
        message=('MountVolume.SetUp failed for volume "gen-white-list-conf" : '
                 'configmap "es-gen-white-list-configmap" not found'),
        src_kind="Pod",
        dest_kind="ConfigMap",
        relevant=["Pod", "ConfigMap"],
        expect_missing_state=["ConfigMap"],
        expect_state_kinds=["Pod"],
    ),
    Incident(
        name="exceeded-quota",
        message=('Error creating: pods "es-cronjob-1607752440-gprx7" is '
                 'forbidden: exceeded quota: compute-resources-team1, '
                 'requested: pods=1, used: pods=50, limited: pods=50'),
        src_kind="CronJob",
        dest_kind="ResourceQuota",
        relevant=["CronJob", "ResourceQuota"],
        expect_missing_state=[],
        expect_state_kinds=["CronJob", "ResourceQuota"],
    ),
    Incident(
        name="nfs-no-such-file",
        message=('MountVolume.SetUp failed for volume "pvc-f3788c43" : mount '
                 'failed: exit status 32 Mounting command: systemd-run mount '
                 '-t nfs 172.16.112.63:/mnt/k8s_nfs_pv/redis-pv failed, '
                 'reason given by server: No such file or directory'),
        src_kind="Pod",
        dest_kind="nfs",
        relevant=["PersistentVolumeClaim", "PersistentVolume", "nfs"],
        expect_missing_state=["nfs"],
        expect_state_kinds=["Pod", "PersistentVolumeClaim",
                            "PersistentVolume"],
    ),
]


def _native(g: Graph, kind: str, name: str, uid: str) -> Node:
    return g.add_node([kind], kind=kind, kind2=kind, name2=name, id=uid,
                      isNative="true", isAtomic="false")


def _state(g: Graph, entity: Node, kind: str, uid: str,
           tmin: str = TS_STATE_MIN, tmax: str = TS_STATE_MAX,
           **fields) -> Node:
    props = {"kind": kind, "id": uid}
    props.update({k: (v if isinstance(v, str) else json.dumps(v))
                  for k, v in fields.items()})
    st = g.add_node([kind.upper()], **props)
    g.add_relationship(entity, "HasState", st, tmin=tmin, tmax=tmax)
    return st


def _event(g: Graph, message: str, involved: Node, uid: str) -> Node:
    ev = g.add_node(["Event"], kind="Event", kind2="Event", id=uid,
                    isNative="true", isAtomic="false",
                    timestamp=TS_EVENT, message=message,
                    nextTimestamp=TS_STATE_MAX)
    rec = g.add_node(["EVENT"], kind="EVENT", id=uid + "-rec",
                     message=message, timestamp=TS_EVENT)
    g.add_relationship(ev, "HasEvent", rec, key="metadata_uid")
    g.add_relationship(ev, "ReferInternal", involved, key="involvedObject_uid")
    return ev


def build_stategraph() -> Graph:
    g = Graph()

    # --- incident 1: missing Secret (plus a decoy healthy secret)
    pod1 = _native(g, "Pod", "es-pod-0", "pod-0001")
    secret1 = _native(g, "Secret", "es-account-token", "sec-0001")
    decoy = _native(g, "Secret", "other-secret", "sec-0002")
    g.add_relationship(pod1, "ReferInternal", secret1,
                       key="spec_volumes_secret_secretName")
    g.add_relationship(pod1, "ReferInternal", decoy,
                       key="spec_volumes_secret_secretName")
    _state(g, pod1, "Pod", "pod-0001",
           spec={"volumes": [{"secret": {"secretName": "es-account-token"}}]},
           status={"phase": "Pending", "conditions": [
               {"type": "Ready", "status": "False",
                "reason": "ContainersNotReady"}]},
           metadata={"name": "es-pod-0", "namespace": "es"})
    _state(g, decoy, "Secret", "sec-0002",
           data={"token": "<redacted>"},
           metadata={"name": "other-secret", "namespace": "es"})
    # secret1 deliberately has NO STATE node
    _event(g, INCIDENTS[0].message, pod1, "evt-0001")

    # --- incident 2: missing ConfigMap
    pod2 = _native(g, "Pod", "es-gen-pod", "pod-0002")
    cm1 = _native(g, "ConfigMap", "es-gen-white-list-configmap", "cm-0001")
    g.add_relationship(pod2, "ReferInternal", cm1,
                       key="spec_volumes_configMap_name")
    _state(g, pod2, "Pod", "pod-0002",
           spec={"volumes": [{"configMap": {"name":
                 "es-gen-white-list-configmap"}}]},
           status={"phase": "Pending"},
           metadata={"name": "es-gen-pod", "namespace": "es"})
    _event(g, INCIDENTS[1].message, pod2, "evt-0002")

    # --- incident 3: exhausted ResourceQuota, reached via Namespace
    cron1 = _native(g, "CronJob", "es-cronjob", "cron-0001")
    ns1 = _native(g, "Namespace", "team1", "ns-0001")
    quota1 = _native(g, "ResourceQuota", "compute-resources-team1", "rq-0001")
    g.add_relationship(cron1, "ReferInternal", ns1, key="metadata_namespace")
    g.add_relationship(quota1, "ReferInternal", ns1, key="metadata_namespace")
    _state(g, cron1, "CronJob", "cron-0001",
           spec={"schedule": "*/1 * * * *"},
           status={"active": 50},
           metadata={"name": "es-cronjob", "namespace": "team1"})
    _state(g, ns1, "Namespace", "ns-0001",
           spec={"finalizers": ["kubernetes"]},
           status={"phase": "Active"},
           metadata={"name": "team1"})
    _state(g, quota1, "ResourceQuota", "rq-0001",
           spec={"hard": {"pods": "50"}},
           status={"hard": {"pods": "50"}, "used": {"pods": "50"}},
           metadata={"name": "compute-resources-team1", "namespace": "team1"})
    _event(g, INCIDENTS[2].message, cron1, "evt-0003")

    # --- incident 4: nfs path gone; chain Pod->PVC<-PV->nfs (undirected rung)
    pod4 = _native(g, "Pod", "redis-0", "pod-0004")
    pvc1 = _native(g, "PersistentVolumeClaim", "redis-pvc", "pvc-0001")
    pv1 = _native(g, "PersistentVolume", "redis-pv", "pv-0001")
    nfs1 = g.add_node(["nfs"], kind="nfs", tag="nfs",
                      path="172.16.112.63:/mnt/k8s_nfs_pv/redis-pv",
                      id="nfs-0001", isNative="false", isAtomic="false")
    g.add_relationship(pod4, "ReferInternal", pvc1,
                       key="spec_volumes_persistentVolumeClaim_claimName")
    g.add_relationship(pv1, "ReferInternal", pvc1, key="spec_claimRef_uid")
    g.add_relationship(pv1, "UseExternal", nfs1, key="spec_nfs_path")
    _state(g, pod4, "Pod", "pod-0004",
           spec={"volumes": [{"persistentVolumeClaim":
                 {"claimName": "redis-pvc"}}]},
           status={"phase": "Running"},
           metadata={"name": "redis-0", "namespace": "redis"})
    _state(g, pvc1, "PersistentVolumeClaim", "pvc-0001",
           spec={"volumeName": "redis-pv"},
           status={"phase": "Bound"},
           metadata={"name": "redis-pvc", "namespace": "redis"})
    _state(g, pv1, "PersistentVolume", "pv-0001",
           spec={"nfs": {"server": "172.16.112.63",
                 "path": "/mnt/k8s_nfs_pv/redis-pv"}},
           status={"phase": "Bound"},
           metadata={"name": "redis-pv"})
    # nfs1 deliberately has NO STATE node
    _event(g, INCIDENTS[3].message, pod4, "evt-0004")

    return g
